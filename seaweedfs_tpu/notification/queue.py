"""Notification queue SPI: publish filer meta events to a message queue.

Functional equivalent of reference weed/notification (kafka/aws_sqs/
gcp_pub_sub/gocdk/log backends behind a MessageQueue interface). The
cloud SDKs aren't available here, so we ship the SPI plus in-memory,
log, and JSONL-file queues; external-broker backends implement the same
two methods.
"""

from __future__ import annotations

import abc
import json
import queue
import threading
from typing import Optional


class MessageQueue(abc.ABC):
    name = "abstract"

    @abc.abstractmethod
    def send_message(self, key: str, message: dict) -> None: ...

    def close(self) -> None:
        pass


class InMemoryQueue(MessageQueue):
    name = "memory"

    def __init__(self, maxsize: int = 65536):
        self.q: queue.Queue = queue.Queue(maxsize)

    def send_message(self, key: str, message: dict) -> None:
        self.q.put((key, message))

    def receive(self, timeout: Optional[float] = None):
        return self.q.get(timeout=timeout)


class LogQueue(MessageQueue):
    """Log-only backend (reference notification/log)."""

    name = "log"

    def __init__(self, logger=None):
        import logging
        self.logger = logger or logging.getLogger("seaweedfs_tpu.notify")

    def send_message(self, key: str, message: dict) -> None:
        self.logger.info("notification %s: %s", key, json.dumps(message))


class FileQueue(MessageQueue):
    """Durable JSONL file queue."""

    name = "file"

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()

    def send_message(self, key: str, message: dict) -> None:
        with self._lock:
            with open(self.path, "a") as f:
                f.write(json.dumps({"key": key, "message": message}) + "\n")


def attach_to_filer(filer, mq: MessageQueue) -> None:
    """Forward every filer meta event to the queue (the reference wires
    this inside Filer.NotifyUpdateEvent)."""
    original = filer._notify

    def notify(directory, old_entry, new_entry):
        original(directory, old_entry, new_entry)
        path = (new_entry or old_entry or {}).get("full_path", directory)
        mq.send_message(path, {"directory": directory,
                               "old_entry": old_entry,
                               "new_entry": new_entry})
    filer._notify = notify
