"""Google-Pub/Sub-wire notification queue (reference weed/notification/
google_pub_sub/google_pub_sub.go, which uses the GCP SDK; here the
Pub/Sub REST publish API is spoken directly — JSON POST with a Bearer
token, no SDK).

Auth is a static bearer token from configuration (a service-account
OAuth flow needs egress this environment doesn't have; any
Pub/Sub-compatible emulator accepts tokenless/static-token calls).
Tests run against MiniPubSubServer below.
"""

from __future__ import annotations

import base64
import json

from seaweedfs_tpu.notification.queue import MessageQueue
from seaweedfs_tpu.utils.httpd import http_call


class PubSubQueue(MessageQueue):
    name = "google_pub_sub"

    def __init__(self, endpoint: str, project: str, topic: str,
                 token: str = "", timeout: float = 10.0):
        self.endpoint = endpoint.rstrip("/")
        self.project = project
        self.topic = topic
        self.token = token
        self.timeout = timeout

    def send_message(self, key: str, message: dict) -> None:
        url = (f"{self.endpoint}/v1/projects/{self.project}"
               f"/topics/{self.topic}:publish")
        payload = json.dumps({"messages": [{
            "data": base64.b64encode(
                json.dumps(message).encode()).decode(),
            "attributes": {"key": key},
        }]}).encode()
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        # http_call (not raw urllib) so the ambient Deadline/QoS-class/
        # Trace of the write that triggered this notification propagate
        # into the broker hop
        status, _, _ = http_call("POST", url, body=payload,
                                 timeout=self.timeout, headers=headers)
        if status >= 300:
            raise ConnectionError(f"Pub/Sub publish: {status}")


class MiniPubSubServer:
    """In-process Pub/Sub publish endpoint for tests: checks the Bearer
    token and records decoded messages per topic."""

    def __init__(self, token: str = ""):
        from seaweedfs_tpu.utils.httpd import HttpServer, Response
        self.token = token
        self.messages: list[dict] = []
        self._response_cls = Response
        self.http = HttpServer("127.0.0.1", 0)
        self.http.add("POST",
                      r"/v1/projects/([^/]+)/topics/([^:]+):publish$",
                      self._publish)

    def start(self):
        self.http.start()
        return self

    def stop(self):
        self.http.stop()

    @property
    def url(self) -> str:
        return f"http://{self.http.host}:{self.http.port}"

    def _publish(self, req) -> "Response":
        Response = self._response_cls
        if self.token:
            if req.headers.get("Authorization") != f"Bearer {self.token}":
                return Response({"error": {"code": 401}}, status=401)
        body = req.json()
        ids = []
        for m in body.get("messages", []):
            self.messages.append({
                "project": req.match.group(1),
                "topic": req.match.group(2),
                "key": m.get("attributes", {}).get("key", ""),
                "message": json.loads(base64.b64decode(m["data"])),
            })
            ids.append(str(len(self.messages)))
        return Response({"messageIds": ids})
