"""AWS-SQS-wire notification queue (reference weed/notification/aws_sqs/
aws_sqs_pub.go, which uses the AWS SDK; here the SQS HTTP query API is
spoken directly — SigV4-signed form POSTs, no SDK, same dependency-free
approach as the Kafka and S3 wire clients).

Works against real SQS-compatible endpoints (AWS, localstack,
elasticmq); tests run against MiniSqsServer below.
"""

from __future__ import annotations

import hashlib
import json
import time
import urllib.parse

from seaweedfs_tpu.notification.queue import MessageQueue
from seaweedfs_tpu.utils import sigv4
from seaweedfs_tpu.utils.httpd import http_call

API_VERSION = "2012-11-05"


class SqsQueue(MessageQueue):
    name = "aws_sqs"

    def __init__(self, queue_url: str, access_key: str = "",
                 secret_key: str = "", region: str = "us-east-1",
                 timeout: float = 10.0):
        self.queue_url = queue_url.rstrip("/")
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.timeout = timeout

    def send_message(self, key: str, message: dict) -> None:
        body = urllib.parse.urlencode({
            "Action": "SendMessage",
            "Version": API_VERSION,
            "MessageBody": json.dumps({"key": key, "message": message}),
            "MessageAttribute.1.Name": "key",
            "MessageAttribute.1.Value.DataType": "String",
            "MessageAttribute.1.Value.StringValue": key,
        }).encode()
        u = urllib.parse.urlparse(self.queue_url)
        amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        date = amz_date[:8]
        payload_hash = hashlib.sha256(body).hexdigest()
        headers = {
            "Host": u.netloc,
            "Content-Type": "application/x-www-form-urlencoded",
            "x-amz-date": amz_date,
            "x-amz-content-sha256": payload_hash,
        }
        signed = ["content-type", "host", "x-amz-content-sha256",
                  "x-amz-date"]
        lower = {k.lower(): v for k, v in headers.items()}
        sig = sigv4.signature(self.secret_key, date, self.region, "sqs",
                              amz_date, "POST", u.path or "/", {},
                              lower, signed, payload_hash)
        scope = f"{date}/{self.region}/sqs/aws4_request"
        headers["Authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={';'.join(signed)}, Signature={sig}")
        # SigV4 signs only the headers in `signed`; the extra
        # X-Weed-* headers http_call injects ride unsigned, so the
        # signature stays valid while deadline/class/trace propagate
        status, _, _ = http_call("POST", self.queue_url, body=body,
                                 timeout=self.timeout, headers=headers)
        if status >= 300:
            raise ConnectionError(f"SQS SendMessage: {status}")


class MiniSqsServer:
    """In-process SQS endpoint for tests: verifies the SigV4 signature
    against the configured secret and records SendMessage bodies."""

    def __init__(self, access_key: str = "AK", secret_key: str = "SK",
                 region: str = "us-east-1"):
        from seaweedfs_tpu.utils.httpd import HttpServer, Response
        self.access_key, self.secret_key = access_key, secret_key
        self.region = region
        self.messages: list[dict] = []
        self.http = HttpServer("127.0.0.1", 0)
        self._response_cls = Response
        self.http.add("POST", r"/queue/(.+)$", self._send)

    def start(self):
        self.http.start()
        return self

    def stop(self):
        self.http.stop()

    @property
    def url(self) -> str:
        return f"http://{self.http.host}:{self.http.port}"

    def _send(self, req) -> "Response":
        Response = self._response_cls
        auth = req.headers.get("Authorization", "")
        if not self._verify(req, auth):
            return Response(b"<Error><Code>SignatureDoesNotMatch"
                            b"</Code></Error>", status=403,
                            content_type="application/xml")
        form = urllib.parse.parse_qs(req.body.decode())
        if form.get("Action") != ["SendMessage"]:
            return Response(b"<Error><Code>InvalidAction</Code></Error>",
                            status=400, content_type="application/xml")
        body = form["MessageBody"][0]
        self.messages.append({
            "queue": req.match.group(1),
            "body": json.loads(body),
            "key": form.get(
                "MessageAttribute.1.Value.StringValue", [""])[0],
        })
        md5 = hashlib.md5(body.encode()).hexdigest()
        return Response(
            (f"<SendMessageResponse><SendMessageResult>"
             f"<MD5OfMessageBody>{md5}</MD5OfMessageBody>"
             f"<MessageId>{len(self.messages)}</MessageId>"
             f"</SendMessageResult></SendMessageResponse>").encode(),
            content_type="application/xml")

    def _verify(self, req, auth: str) -> bool:
        try:
            cred = auth.split("Credential=")[1].split(",")[0]
            access_key, date, region, service, _ = cred.split("/")
            signed = auth.split("SignedHeaders=")[1].split(",")[0].split(";")
            their_sig = auth.split("Signature=")[1].strip()
        except (IndexError, ValueError):
            return False
        if access_key != self.access_key:
            return False
        headers = {k.lower(): v for k, v in req.headers.items()}
        ours = sigv4.signature(
            self.secret_key, date, region, service,
            headers.get("x-amz-date", ""), "POST", req.path, {},
            headers, signed, headers.get("x-amz-content-sha256", ""))
        return ours == their_sig
