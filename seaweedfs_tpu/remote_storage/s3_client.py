"""S3-protocol RemoteStorageClient: cloud remotes over raw SigV4 HTTP.

Redesign of reference weed/remote_storage/s3/s3_storage_client.go —
there the AWS SDK does the lifting; here a ~100-line SigV4 signer over
urllib talks to ANY S3-compatible endpoint (AWS, MinIO, Ceph RGW, or
this repo's own gateway, which is what the tests mount against). This
closes the most-used cloud-remote path with zero SDK dependencies: the
framework both SERVES the S3 dialect (gateway/s3_server.py) and now
SPEAKS it as a client.
"""

from __future__ import annotations

import hashlib
import time
import urllib.parse
import xml.etree.ElementTree as ET
from typing import Iterator, Optional

from seaweedfs_tpu.remote_storage.remote_storage import (RemoteFile,
                                                         RemoteStorageClient)
from seaweedfs_tpu.utils.httpd import http_call


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


class SigV4Signer:
    """Header-based AWS Signature Version 4. Canonicalization and key
    derivation live in utils/sigv4.py, shared with the gateway's
    verifier — one copy, so the two can never drift."""

    def __init__(self, access_key: str, secret_key: str,
                 region: str = "us-east-1", service: str = "s3"):
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.service = service

    def signed_headers(self, method: str, host: str, path: str,
                       query: dict, body: bytes) -> dict:
        from seaweedfs_tpu.utils import sigv4
        amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        date = amz_date[:8]
        payload_hash = _sha256(body)
        headers = {"Host": host, "x-amz-date": amz_date,
                   "x-amz-content-sha256": payload_hash}
        signed = ["host", "x-amz-content-sha256", "x-amz-date"]
        lower = {k.lower(): v for k, v in headers.items()}
        sig = sigv4.signature(self.secret_key, date, self.region,
                              self.service, amz_date, method, path,
                              query, lower, signed, payload_hash)
        scope = f"{date}/{self.region}/{self.service}/aws4_request"
        headers["Authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={';'.join(signed)}, Signature={sig}")
        return headers


class S3Remote(RemoteStorageClient):
    """RemoteStorageClient over the S3 REST dialect."""

    def __init__(self, endpoint: str, bucket: str, access_key: str = "",
                 secret_key: str = "", region: str = "us-east-1"):
        if not endpoint.startswith("http"):
            endpoint = "http://" + endpoint
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self.host = urllib.parse.urlparse(self.endpoint).netloc
        self.signer = (SigV4Signer(access_key, secret_key, region)
                       if access_key else None)

    # ---- plumbing ----
    def _call(self, method: str, key: str, query: Optional[dict] = None,
              body: bytes = b"", extra_headers: Optional[dict] = None
              ) -> tuple[int, bytes, dict]:
        query = query or {}
        path = "/" + urllib.parse.quote(
            f"{self.bucket}/{key.lstrip('/')}".rstrip("/"), safe="/~")
        headers = {}
        if self.signer is not None:
            headers.update(self.signer.signed_headers(
                method, self.host, path, query, body))
        if extra_headers:
            headers.update(extra_headers)
        qs = ("?" + urllib.parse.urlencode(sorted(query.items()))
              if query else "")
        status, resp, rheaders = http_call(
            method, f"{self.endpoint}{path}{qs}", body=body or None,
            headers=headers, timeout=120)
        return status, resp, rheaders

    @staticmethod
    def _clean_etag(etag: str) -> str:
        return etag.strip().strip('"')

    def list_buckets(self) -> list[str]:
        """GET / — ListAllMyBucketsResult (shell remote.mount.buckets
        enumerates the remote's buckets with this)."""
        headers = {}
        if self.signer is not None:
            headers = self.signer.signed_headers("GET", self.host, "/",
                                                 {}, b"")
        status, resp, _ = http_call("GET", f"{self.endpoint}/",
                                    headers=headers, timeout=30)
        if status >= 300:
            raise ConnectionError(f"ListBuckets: HTTP {status}")
        root = ET.fromstring(resp)
        names = []
        for b in root.iter():
            if b.tag.rsplit("}", 1)[-1] == "Bucket":
                for child in b:
                    if child.tag.rsplit("}", 1)[-1] == "Name":
                        names.append(child.text or "")
        return names

    # ---- SPI ----
    def traverse(self, prefix: str = "") -> Iterator[RemoteFile]:
        token = ""
        seen_dirs: set[str] = set()
        while True:
            query = {"list-type": "2", "max-keys": "1000"}
            if prefix:
                query["prefix"] = prefix.lstrip("/")
            if token:
                query["continuation-token"] = token
            status, body, _ = self._call("GET", "", query=query)
            if status != 200:
                raise IOError(f"s3 list: HTTP {status}: {body[:200]!r}")
            root = ET.fromstring(body)
            ns = ""
            if root.tag.startswith("{"):
                ns = root.tag[:root.tag.index("}") + 1]
            for c in root.findall(f"{ns}Contents"):
                key = c.findtext(f"{ns}Key", "")
                size = int(c.findtext(f"{ns}Size", "0"))
                etag = self._clean_etag(c.findtext(f"{ns}ETag", ""))
                mtime = _parse_iso(c.findtext(f"{ns}LastModified", ""))
                # synthesize parent directory entries (the local
                # backend yields them; pull_metadata mkdirs them)
                parts = key.split("/")[:-1]
                for i in range(1, len(parts) + 1):
                    d = "/".join(parts[:i])
                    if d and d not in seen_dirs:
                        seen_dirs.add(d)
                        yield RemoteFile(path=d, size=0, mtime=0,
                                         is_directory=True)
                yield RemoteFile(path=key, size=size, mtime=mtime,
                                 etag=etag)
            token = root.findtext(f"{ns}NextContinuationToken", "")
            if root.findtext(f"{ns}IsTruncated", "false") != "true" \
                    or not token:
                return

    def read_file(self, path: str, offset: int = 0,
                  size: int = -1) -> bytes:
        headers = {}
        if offset or size >= 0:
            end = "" if size < 0 else str(offset + size - 1)
            headers["Range"] = f"bytes={offset}-{end}"
        status, body, _ = self._call("GET", path, extra_headers=headers)
        if status not in (200, 206):
            raise IOError(f"s3 read {path}: HTTP {status}")
        if status == 200 and (offset or size >= 0):
            body = body[offset:offset + size if size >= 0 else None]
        return body

    def write_file(self, path: str, data: bytes) -> RemoteFile:
        status, body, headers = self._call("PUT", path, body=data)
        if status >= 300:
            raise IOError(f"s3 write {path}: HTTP {status}: "
                          f"{body[:200]!r}")
        return RemoteFile(path=path.lstrip("/"), size=len(data),
                          mtime=int(time.time()),
                          etag=self._clean_etag(headers.get("ETag", "")))

    def remove_file(self, path: str) -> None:
        status, body, _ = self._call("DELETE", path)
        if status not in (200, 204, 404):
            raise IOError(f"s3 delete {path}: HTTP {status}")

    def stat(self, path: str) -> Optional[RemoteFile]:
        status, _, headers = self._call("HEAD", path)
        if status == 404:
            return None
        if status >= 300:
            raise IOError(f"s3 stat {path}: HTTP {status}")
        return RemoteFile(
            path=path.lstrip("/"),
            size=int(headers.get("Content-Length", 0)),
            mtime=_parse_http_date(headers.get("Last-Modified", "")),
            etag=self._clean_etag(headers.get("ETag", "")))


def _parse_iso(s: str) -> int:
    if not s:
        return 0
    try:
        import calendar
        return calendar.timegm(
            time.strptime(s.split(".")[0], "%Y-%m-%dT%H:%M:%S"))
    except ValueError:
        return 0


def _parse_http_date(s: str) -> int:
    if not s:
        return 0
    try:
        from email.utils import parsedate_to_datetime
        return int(parsedate_to_datetime(s).timestamp())
    except (TypeError, ValueError):
        return 0
