"""Azure-Blob-wire remote storage client (reference
weed/remote_storage/azure/azure_storage_client.go, which uses the Azure
SDK; here the Blob service REST API is spoken directly — SharedKey
HMAC-SHA256 request signing, Put/Get/Delete Blob, List Blobs — the same
dependency-free approach as the S3/SQS/Kafka wire clients).

Works against any Blob-protocol endpoint (Azure, azurite); tests run
against MiniAzureServer below, which verifies the SharedKey signature.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import time
import urllib.parse
import xml.etree.ElementTree as ET
from typing import Iterator, Optional

from seaweedfs_tpu.utils.httpd import http_call
from seaweedfs_tpu.remote_storage.remote_storage import (RemoteFile,
                                                         RemoteStorageClient)

API_VERSION = "2020-10-02"


def shared_key_signature(account: str, key_b64: str, method: str,
                         path: str, query: dict, headers: dict) -> str:
    """SharedKey StringToSign (the 2015+ scheme: empty Content-Length
    for zero-length bodies). `headers` keys must be lower-case."""
    length = headers.get("content-length", "")
    if length in ("0", 0):
        length = ""
    canon_headers = "".join(
        f"{k}:{headers[k]}\n"
        for k in sorted(h for h in headers if h.startswith("x-ms-")))
    canon_resource = f"/{account}{path}"
    for k in sorted(query):
        canon_resource += f"\n{k.lower()}:{query[k]}"
    sts = "\n".join([
        method,
        headers.get("content-encoding", ""),
        headers.get("content-language", ""),
        str(length),
        headers.get("content-md5", ""),
        headers.get("content-type", ""),
        headers.get("date", ""),
        headers.get("if-modified-since", ""),
        headers.get("if-match", ""),
        headers.get("if-none-match", ""),
        headers.get("if-unmodified-since", ""),
        headers.get("range", ""),
    ]) + "\n" + canon_headers + canon_resource
    mac = hmac.new(base64.b64decode(key_b64), sts.encode("utf-8"),
                   hashlib.sha256)
    return base64.b64encode(mac.digest()).decode()


class AzureRemote(RemoteStorageClient):
    """Blob container as a remote (account key = RemoteConf.secret_key,
    account name = RemoteConf.access_key, container = bucket)."""

    def __init__(self, endpoint: str, container: str, account: str,
                 key_b64: str, timeout: float = 20.0):
        self.endpoint = endpoint.rstrip("/")
        self.container = container
        self.account = account
        self.key_b64 = key_b64
        self.timeout = timeout

    def _call(self, method: str, blob: str, query: Optional[dict] = None,
              body: bytes = b"", headers: Optional[dict] = None,
              ok=(200, 201, 202, 206)):
        query = query or {}
        path = f"/{self.container}"
        if blob:
            path += "/" + urllib.parse.quote(blob)
        hdrs = {
            "x-ms-date": time.strftime("%a, %d %b %Y %H:%M:%S GMT",
                                       time.gmtime()),
            "x-ms-version": API_VERSION,
            **(headers or {}),
        }
        if body:
            hdrs["Content-Length"] = str(len(body))
        lower = {k.lower(): v for k, v in hdrs.items()}
        sig = shared_key_signature(self.account, self.key_b64, method,
                                   path, query, lower)
        hdrs["Authorization"] = f"SharedKey {self.account}:{sig}"
        qs = ("?" + urllib.parse.urlencode(query)) if query else ""
        # http_call: deadline/class/trace headers propagate to the
        # remote tier; SharedKey only canonicalizes x-ms-* headers, so
        # the extra X-Weed-* headers don't disturb the signature
        status, data, resp_headers = http_call(
            method, f"{self.endpoint}{path}{qs}", body=body or None,
            timeout=self.timeout, headers=hdrs)
        if status not in ok:
            raise ConnectionError(f"azure {method} {path}: {status}")
        return status, data, resp_headers

    # ---- RemoteStorageClient ----
    def traverse(self, prefix: str = "") -> Iterator[RemoteFile]:
        marker = ""
        while True:
            query = {"restype": "container", "comp": "list"}
            if prefix:
                query["prefix"] = prefix.lstrip("/")
            if marker:
                query["marker"] = marker
            _, data, _ = self._call("GET", "", query=query)
            root = ET.fromstring(data)
            for b in root.iter("Blob"):
                name = b.findtext("Name")
                props = b.find("Properties")
                size = int(props.findtext("Content-Length", "0"))
                etag = props.findtext("Etag", "")
                yield RemoteFile(path=name, size=size, mtime=0,
                                 etag=etag)
            marker = root.findtext("NextMarker") or ""
            if not marker:
                return

    def read_file(self, path: str, offset: int = 0,
                  size: int = -1) -> bytes:
        headers = {}
        if offset or size >= 0:
            end = "" if size < 0 else str(offset + size - 1)
            headers["Range"] = f"bytes={offset}-{end}"
        _, data, _ = self._call("GET", path.lstrip("/"), headers=headers)
        return data

    def write_file(self, path: str, data: bytes) -> RemoteFile:
        _, _, resp_headers = self._call(
            "PUT", path.lstrip("/"), body=data,
            headers={"x-ms-blob-type": "BlockBlob",
                     "Content-Type": "application/octet-stream"})
        return RemoteFile(path=path.lstrip("/"), size=len(data),
                          mtime=int(time.time()),
                          etag=resp_headers.get("Etag", ""))

    def remove_file(self, path: str) -> None:
        self._call("DELETE", path.lstrip("/"), ok=(200, 202, 404))

    def stat(self, path: str) -> Optional[RemoteFile]:
        status, _, h = self._call("HEAD", path.lstrip("/"),
                                  ok=(200, 404))
        if status == 404:
            return None
        return RemoteFile(path=path.lstrip("/"),
                          size=int(h.get("Content-Length", 0)),
                          mtime=0, etag=h.get("Etag", ""))


class MiniAzureServer:
    """In-process Blob endpoint for tests: verifies the SharedKey
    signature and keeps blobs in memory."""

    def __init__(self, account: str = "devaccount",
                 key_b64: str = ""):
        from seaweedfs_tpu.utils.httpd import HttpServer, Response
        self.account = account
        self.key_b64 = key_b64 or base64.b64encode(b"devkey").decode()
        self.blobs: dict[str, dict[str, bytes]] = {}
        self._response_cls = Response
        self.http = HttpServer("127.0.0.1", 0)
        self.http.add("GET", r"/([^/?]+)$", self._list)
        self.http.add("PUT", r"/([^/?]+)/(.+)$", self._put)
        self.http.add("GET", r"/([^/?]+)/(.+)$", self._get)
        self.http.add("HEAD", r"/([^/?]+)/(.+)$", self._get)
        self.http.add("DELETE", r"/([^/?]+)/(.+)$", self._delete)

    def start(self):
        self.http.start()
        return self

    def stop(self):
        self.http.stop()

    @property
    def url(self) -> str:
        return f"http://{self.http.host}:{self.http.port}"

    def _authed(self, req, method: str) -> bool:
        auth = req.headers.get("Authorization", "")
        if not auth.startswith("SharedKey "):
            return False
        try:
            account, their_sig = auth[len("SharedKey "):].split(":", 1)
        except ValueError:
            return False
        if account != self.account:
            return False
        lower = {k.lower(): v for k, v in req.headers.items()}
        if req.body:
            lower["content-length"] = str(len(req.body))
        path = urllib.parse.quote(req.path)
        ours = shared_key_signature(self.account, self.key_b64, method,
                                    path, req.query, lower)
        return hmac.compare_digest(ours, their_sig)

    def _deny(self):
        return self._response_cls(b"<Error>AuthenticationFailed</Error>",
                                  status=403,
                                  content_type="application/xml")

    def _put(self, req):
        if not self._authed(req, "PUT"):
            return self._deny()
        container, blob = req.match.group(1), req.match.group(2)
        self.blobs.setdefault(container, {})[blob] = req.body or b""
        return self._response_cls(
            b"", status=201,
            headers={"Etag": f'"{hashlib.md5(req.body or b"").hexdigest()}"'})

    def _get(self, req):
        if not self._authed(req, req.method):
            return self._deny()
        container, blob = req.match.group(1), req.match.group(2)
        data = self.blobs.get(container, {}).get(blob)
        if data is None:
            return self._response_cls(b"", status=404)
        rng = req.headers.get("Range", "")
        status = 200
        if rng.startswith("bytes="):
            lo_s, _, hi_s = rng[len("bytes="):].partition("-")
            lo = int(lo_s)
            hi = int(hi_s) + 1 if hi_s else len(data)
            data, status = data[lo:hi], 206
        body = b"" if req.method == "HEAD" else data
        return self._response_cls(
            body, status=status,
            headers={"Content-Length": str(len(data)),
                     "Etag": f'"{hashlib.md5(data).hexdigest()}"'})

    def _delete(self, req):
        if not self._authed(req, "DELETE"):
            return self._deny()
        container, blob = req.match.group(1), req.match.group(2)
        existed = self.blobs.get(container, {}).pop(blob, None)
        return self._response_cls(
            b"", status=202 if existed is not None else 404)

    def _list(self, req):
        if req.query.get("comp") != "list":
            return self._response_cls(b"", status=400)
        if not self._authed(req, "GET"):
            return self._deny()
        container = req.match.group(1)
        prefix = req.query.get("prefix", "")
        root = ET.Element("EnumerationResults")
        blobs_el = ET.SubElement(root, "Blobs")
        for name, data in sorted(self.blobs.get(container, {}).items()):
            if not name.startswith(prefix):
                continue
            b = ET.SubElement(blobs_el, "Blob")
            ET.SubElement(b, "Name").text = name
            props = ET.SubElement(b, "Properties")
            ET.SubElement(props, "Content-Length").text = str(len(data))
            ET.SubElement(props, "Etag").text = \
                f'"{hashlib.md5(data).hexdigest()}"'
        ET.SubElement(root, "NextMarker")
        return self._response_cls(ET.tostring(root),
                                  content_type="application/xml")
