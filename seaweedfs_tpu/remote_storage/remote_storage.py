"""Remote storage SPI: cloud buckets mountable into the filer namespace.

Functional equivalent of reference weed/remote_storage/remote_storage.go:
a provider-neutral client interface (traverse/read/write/delete/stat) plus
a registry keyed by configuration. The reference ships s3/gcs/azure
implementations over their SDKs; this environment has no cloud SDKs or
egress, so the shipped backends are:

  - LocalDirRemote ("local" type): a directory tree as the remote —
    the gocdk/local-equivalent backend, and what tests/integration use
  - S3Remote ("s3" type): the volume layer already speaks the S3 REST
    dialect (storage/backend.py S3BackendFile); this client is a plug
    point that raises until an SDK/endpoint is wired

A remote location is written "name/bucket/path" (reference
remote_storage.ParseLocation / RemoteStorageLocation proto).
"""

from __future__ import annotations

import abc
import dataclasses
import json
import os
from typing import Callable, Iterator, Optional


@dataclasses.dataclass
class RemoteFile:
    """One object listed from the remote (reference traverse callback)."""
    path: str  # relative to the mounted bucket/prefix, "/"-separated
    size: int
    mtime: int  # unix seconds
    etag: str = ""
    is_directory: bool = False


@dataclasses.dataclass
class RemoteConf:
    """One configured remote storage (reference remote_pb.RemoteConf,
    persisted under /etc/remote.conf in the filer store)."""
    name: str
    type: str = "local"
    # local backend
    root: str = ""
    # s3 backend (any S3-compatible endpoint, incl. our own gateway)
    endpoint: str = ""
    access_key: str = ""
    secret_key: str = ""
    bucket: str = ""
    region: str = "us-east-1"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def to_public_dict(self) -> dict:
        """Listing form: credentials masked (the reference never echoes
        secrets back from remote.configure listings)."""
        d = self.to_dict()
        for secret in ("access_key", "secret_key"):
            if d.get(secret):
                d[secret] = "***"
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "RemoteConf":
        return cls(**{k: v for k, v in d.items()
                      if k in {f.name for f in dataclasses.fields(cls)}})


class RemoteStorageClient(abc.ABC):
    """Provider-neutral operations (reference RemoteStorageClient)."""

    @abc.abstractmethod
    def traverse(self, prefix: str = "") -> Iterator[RemoteFile]: ...

    @abc.abstractmethod
    def read_file(self, path: str, offset: int = 0,
                  size: int = -1) -> bytes: ...

    @abc.abstractmethod
    def write_file(self, path: str, data: bytes) -> RemoteFile: ...

    @abc.abstractmethod
    def remove_file(self, path: str) -> None: ...

    @abc.abstractmethod
    def stat(self, path: str) -> Optional[RemoteFile]: ...


class LocalDirRemote(RemoteStorageClient):
    """A plain directory tree as the remote store."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _abs(self, path: str) -> str:
        path = path.lstrip("/")
        rootn = os.path.normpath(self.root)
        full = os.path.normpath(os.path.join(rootn, path))
        if full != rootn and not full.startswith(rootn + os.sep):
            raise ValueError(f"path escapes remote root: {path}")
        return full

    @staticmethod
    def _etag(st: os.stat_result) -> str:
        return f"{st.st_mtime_ns:x}-{st.st_size:x}"

    def traverse(self, prefix: str = "") -> Iterator[RemoteFile]:
        base = self._abs(prefix)
        if not os.path.isdir(base):
            return
        for dirpath, dirnames, filenames in os.walk(base):
            rel_dir = os.path.relpath(dirpath, self.root)
            rel_dir = "" if rel_dir == "." else rel_dir.replace(os.sep, "/")
            for name in sorted(dirnames):
                yield RemoteFile(
                    path=(rel_dir + "/" if rel_dir else "") + name,
                    size=0, mtime=0, is_directory=True)
            for name in sorted(filenames):
                st = os.stat(os.path.join(dirpath, name))
                yield RemoteFile(
                    path=(rel_dir + "/" if rel_dir else "") + name,
                    size=st.st_size, mtime=int(st.st_mtime),
                    etag=self._etag(st))

    def read_file(self, path: str, offset: int = 0, size: int = -1) -> bytes:
        with open(self._abs(path), "rb") as f:
            f.seek(offset)
            return f.read() if size < 0 else f.read(size)

    def write_file(self, path: str, data: bytes) -> RemoteFile:
        full = self._abs(path)
        os.makedirs(os.path.dirname(full), exist_ok=True)
        with open(full, "wb") as f:
            f.write(data)
        st = os.stat(full)
        return RemoteFile(path=path.lstrip("/"), size=st.st_size,
                          mtime=int(st.st_mtime), etag=self._etag(st))

    def remove_file(self, path: str) -> None:
        try:
            os.remove(self._abs(path))
        except FileNotFoundError:
            pass

    def stat(self, path: str) -> Optional[RemoteFile]:
        try:
            st = os.stat(self._abs(path))
        except OSError:
            return None
        return RemoteFile(path=path.lstrip("/"), size=st.st_size,
                          mtime=int(st.st_mtime), etag=self._etag(st),
                          is_directory=os.path.isdir(self._abs(path)))


def make_remote_client(conf: RemoteConf) -> RemoteStorageClient:
    """Registry (reference RemoteStorageClientMakers)."""
    if conf.type == "local":
        if not conf.root:
            raise ValueError("local remote needs a root directory")
        return LocalDirRemote(conf.root)
    if conf.type in ("s3", "gcs", "b2", "wasabi"):
        # gcs (XML interop mode with HMAC keys), backblaze b2, and
        # wasabi all serve the S3 dialect — one client covers them
        # (reference ships separate SDK wrappers per provider; the
        # wire protocol is the same)
        from seaweedfs_tpu.remote_storage.s3_client import S3Remote
        endpoint = conf.endpoint or {
            "gcs": "https://storage.googleapis.com",
            "b2": "https://s3.us-west-004.backblazeb2.com",
            "wasabi": "https://s3.wasabisys.com",
        }.get(conf.type, "")
        if not endpoint or not conf.bucket:
            raise ValueError(f"{conf.type} remote needs endpoint and "
                             "bucket")
        return S3Remote(endpoint, conf.bucket,
                        access_key=conf.access_key,
                        secret_key=conf.secret_key, region=conf.region)
    if conf.type == "azure":
        # Blob REST protocol with SharedKey signing, spoken directly
        # (reference wraps the Azure SDK): access_key = account name,
        # secret_key = base64 account key, bucket = container
        from seaweedfs_tpu.remote_storage.azure_client import AzureRemote
        endpoint = conf.endpoint or \
            f"https://{conf.access_key}.blob.core.windows.net"
        if not conf.bucket:
            raise ValueError("azure remote needs a container (bucket)")
        return AzureRemote(endpoint, conf.bucket, conf.access_key,
                           conf.secret_key)
    raise NotImplementedError(
        f"remote type {conf.type!r}: no S3-compatible dialect and no "
        "cloud SDK in this environment; "
        "implement a RemoteStorageClient and register it here")
