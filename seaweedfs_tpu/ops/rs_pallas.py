"""Pallas TPU kernel for the RS parity/decode GF(256) transform.

The jnp path (ops/rs_jax.py) leaves scheduling to XLA; this kernel tiles
the stripe into VMEM blocks and runs the whole unrolled doubling-chain in
one fused pass per tile — one HBM read of the data shards, one HBM write
of the parity, everything else stays in VMEM registers. Grid iterates over
the word dimension; the (k x tile) block auto-pipelines HBM<->VMEM DMA.

Falls back to interpreter mode off-TPU so tests validate bit-identity on
the CPU mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from seaweedfs_tpu.models.coder import (DEFAULT_SCHEME, RSScheme,
                                        register_coder)
from seaweedfs_tpu.ops import gf256
from seaweedfs_tpu.ops.rs_jax import JaxCoder, _mat_to_tuple

_LOW7 = np.uint32(0x7F7F7F7F)
_HIGH1 = np.uint32(0x80808080)

DEFAULT_TILE = 64 * 1024  # uint32 words per grid step (256KB block)


def _xtime(v):
    # multiply form measures ~40% faster than a shift/xor chain on v5e
    hi = v & _HIGH1
    lo = (v & _LOW7) << 1
    return lo ^ ((hi >> 7) * np.uint32(0x1D))


def _make_kernel(mat: tuple[tuple[int, ...], ...]):
    m = len(mat)
    k = len(mat[0])

    def kernel(data_ref, out_ref):
        acc = [None] * m
        for j in range(k):
            d = data_ref[pl.ds(j, 1), :]
            for b in range(8):
                for i in range(m):
                    if (mat[i][j] >> b) & 1:
                        acc[i] = d if acc[i] is None else acc[i] ^ d
                if b < 7 and any((mat[i][j] >> (b + 1)) for i in range(m)):
                    d = _xtime(d)
        for i in range(m):
            row = acc[i] if acc[i] is not None else \
                jnp.zeros_like(out_ref[pl.ds(i, 1), :])
            out_ref[pl.ds(i, 1), :] = row

    return kernel, m, k


@functools.lru_cache(maxsize=None)
def pallas_apply_fn(mat: tuple[tuple[int, ...], ...],
                    tile: int = DEFAULT_TILE):
    """jitted (k, nw) uint32 -> (m, nw) uint32 running the GF matrix as a
    Pallas kernel. nw must be a multiple of `tile`."""
    kernel, m, k = _make_kernel(mat)
    interpret = jax.default_backend() not in ("tpu", "axon")

    @jax.jit
    def run(words):
        nw = words.shape[1]
        grid = (nw // tile,)
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[pl.BlockSpec((k, tile), lambda i: (0, i),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((m, tile), lambda i: (0, i),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((m, nw), jnp.uint32),
            interpret=interpret,
        )(words)

    return run


def _pad_to_tile(words: np.ndarray, tile: int) -> tuple[np.ndarray, int]:
    nw = words.shape[1]
    pad = (-nw) % tile
    if pad:
        words = np.concatenate(
            [words, np.zeros((words.shape[0], pad), dtype=words.dtype)],
            axis=1)
    return words, nw


@register_coder("pallas")
class PallasCoder(JaxCoder):
    """JaxCoder with the parity/decode transform lowered through Pallas."""

    def __init__(self, scheme: RSScheme = DEFAULT_SCHEME,
                 tile: int = DEFAULT_TILE):
        super().__init__(scheme)
        self.tile = tile
        pm = gf256.parity_matrix(scheme.data_shards, scheme.parity_shards)
        self._pallas_parity = pallas_apply_fn(_mat_to_tuple(pm), tile)
        # route the JaxCoder entry points through the pallas kernel
        self._parity_fn = self._parity_padded

    def _parity_padded(self, words):
        arr = np.asarray(words)
        padded, nw = _pad_to_tile(arr, self.tile)
        out = self._pallas_parity(padded)
        return out[:, :nw]

    def encode_array(self, data: np.ndarray) -> np.ndarray:
        assert data.shape[1] % 4 == 0
        words = np.ascontiguousarray(data).view(np.uint32)
        parity = np.asarray(jax.device_get(self._parity_padded(words)))
        return parity.view(np.uint8)
