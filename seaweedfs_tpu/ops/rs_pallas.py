"""Pallas TPU kernel for the RS parity/decode GF(256) transform.

Same Horner-form math as ops/rs_jax.py, but with explicit VMEM tiling:
each grid step DMAs one (TILE,)-word block of every flat shard row into
VMEM, runs the unrolled bitplane-Horner transform, and writes the parity
blocks back — one HBM read of the data, one HBM write of the parity.

Measured on v5e (32MB shards, parity materialized to HBM): this explicit
tiling reaches ~117 GB/s of input, LOSING to the plain XLA-fused jnp path
(~193 GB/s) — XLA pipelines the 14 HBM streams across grid steps better
than the hand-written block spec. The kernel is kept because (a) it is the
natural home for future fusion with streaming DMA (host->HBM prefetch
rings), and (b) it documents the measured design space (see PERF.md). The
production default remains rs_jax.JaxCoder.

Falls back to interpreter mode off-TPU so tests validate bit-identity on
the CPU mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from seaweedfs_tpu.models.coder import (DEFAULT_SCHEME, RSScheme,
                                        register_coder)
from seaweedfs_tpu.ops import gf256
from seaweedfs_tpu.ops.rs_jax import (JaxCoder, _apply_matrix_rows,
                                      _mat_to_tuple, interpret_mode,
                                      pad_rows_to_multiple)

# 64K uint32 words = 256KB per row block; 14 blocks * double buffering
# stays under the 16MB VMEM budget.
DEFAULT_TILE = 64 * 1024


def _make_kernel(mat: tuple[tuple[int, ...], ...]):
    m, k = len(mat), len(mat[0])

    def kernel(*refs):
        ins, outs = refs[:k], refs[k:]
        rows = [r[:] for r in ins]
        parity = _apply_matrix_rows(rows, mat)
        for i in range(m):
            outs[i][:] = parity[i]

    return kernel, m, k


@functools.lru_cache(maxsize=None)
def pallas_apply_fn(mat: tuple[tuple[int, ...], ...],
                    tile: int = DEFAULT_TILE):
    """jitted (k flat uint32 rows) -> tuple of m flat uint32 rows, running
    the GF matrix as a Pallas kernel. Row length must be a multiple of
    `tile`."""
    kernel, m, k = _make_kernel(mat)
    interpret = interpret_mode()

    @jax.jit
    def run(*rows):
        nw = rows[0].shape[0]
        grid = (nw // tile,)
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[pl.BlockSpec((tile,), lambda i: (i,),
                                   memory_space=pltpu.VMEM)] * k,
            out_specs=[pl.BlockSpec((tile,), lambda i: (i,),
                                    memory_space=pltpu.VMEM)] * m,
            out_shape=[jax.ShapeDtypeStruct((nw,), jnp.uint32)] * m,
            interpret=interpret,
        )(*rows)

    return run


@register_coder("pallas")
class PallasCoder(JaxCoder):
    """JaxCoder with the parity transform lowered through an explicit
    Pallas VMEM-tiled kernel (decode stays on the jnp path)."""

    def __init__(self, scheme: RSScheme = DEFAULT_SCHEME,
                 tile: int = DEFAULT_TILE):
        super().__init__(scheme)
        self.tile = tile
        pm = gf256.parity_matrix(scheme.data_shards, scheme.parity_shards)
        self._pallas_parity = pallas_apply_fn(_mat_to_tuple(pm), tile)
        # route the JaxCoder parity entry points through the pallas kernel
        self._parity_fn = self._parity_rows

    def _parity_rows(self, *rows):
        arr = np.stack([np.asarray(r) for r in rows])
        padded, nw = pad_rows_to_multiple(arr, self.tile)
        outs = self._pallas_parity(*[padded[i] for i in range(padded.shape[0])])
        return tuple(o[:nw] for o in outs)
