"""JAX/TPU Reed-Solomon coder — the north-star compute kernel.

Replaces the reference's CPU SIMD codec (klauspost/reedsolomon, invoked from
weed/storage/erasure_coding/ec_encoder.go:199) with an XLA program that runs
on TPU.

Formulation: GF(256) multiplication is linear over GF(2), so the parity
transform factors into bitplanes. The production kernel uses the Horner
form over output bits: for each parity row i, first XOR-combine the input
shards selected by bit b of the matrix constants (S_ib), then fold the 8
planes with one doubling chain per OUTPUT row:
    P_i = ((((S_i7 * 2) ^ S_i6) * 2) ^ ...) ^ S_i0
That needs 7 doublings per parity row (m=4) instead of 7 per input shard
(k=10) in the naive per-input chain — ~1.7x fewer VPU ops. We pack 4 field
elements per uint32 lane (SWAR: x2 via shift/mask/multiply) because TPU
vector registers have 32-bit lanes. The matrix is static at trace time, so
everything unrolls into an elementwise XOR/shift graph that XLA fuses into
one HBM-bound pass — no gather, no table lookup, no data-dependent control
flow.

Layout matters more than anything else here: shards are passed as SEPARATE
flat device arrays, not one stacked (k, n) array. A stacked uint32 (10, n)
operand forces an 8-sublane-padded 2D tiling and measured 4x slower than
flat rows on v5e (54 vs 193 GB/s of input with parity materialized to
HBM). A Pallas-tiled variant lives in ops/rs_pallas.py (measured slower
than this XLA-fused path — see PERF.md); this module is both the
production kernel and the semantics ground truth.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from seaweedfs_tpu.models.coder import (DEFAULT_SCHEME, ErasureCoder,
                                        RSScheme, register_coder)
from seaweedfs_tpu.ops import gf256

_LOW7 = np.uint32(0x7F7F7F7F)
_HIGH1 = np.uint32(0x80808080)
_RED = np.uint32(0x1D)  # 0x11D reduced into the low byte


def _xtime(v: jnp.ndarray) -> jnp.ndarray:
    """Multiply each packed byte by 2 in GF(2^8) (SWAR over uint32 lanes)."""
    hi = v & _HIGH1
    lo = (v & _LOW7) << 1
    return lo ^ ((hi >> 7) * _RED)


def _apply_matrix_words(words: jnp.ndarray, mat: tuple[tuple[int, ...], ...]) -> jnp.ndarray:
    """out[i] = XOR_j mat[i][j] * words[j] over GF(256), words: (k, nw) uint32.

    `mat` is a static python tuple -> the bit structure unrolls at trace time.
    """
    m = len(mat)
    k = len(mat[0])
    assert words.shape[0] == k
    acc: list[Optional[jnp.ndarray]] = [None] * m
    for j in range(k):
        d = words[j]
        for b in range(8):
            used = False
            for i in range(m):
                if (mat[i][j] >> b) & 1:
                    acc[i] = d if acc[i] is None else acc[i] ^ d
                    used = True
            # keep doubling only while some higher bit still needs it
            del used
            if b < 7 and any((mat[i][j] >> (b + 1)) for i in range(m)):
                d = _xtime(d)
    return jnp.stack([a if a is not None else jnp.zeros_like(words[0])
                      for a in acc])


def _apply_matrix_rows(rows: Sequence[jnp.ndarray],
                       mat: tuple[tuple[int, ...], ...]) -> list[jnp.ndarray]:
    """Horner-form transform over separate flat uint32 row arrays.

    Bit-identical to _apply_matrix_words (tested); this is the production
    formulation — see the module docstring for why.
    """
    m, k = len(mat), len(mat[0])
    assert len(rows) == k
    outs = []
    for i in range(m):
        p = None
        for b in range(7, -1, -1):
            s = None
            for j in range(k):
                if (mat[i][j] >> b) & 1:
                    s = rows[j] if s is None else s ^ rows[j]
            if p is None:
                p = s
            else:
                p = _xtime(p)
                if s is not None:
                    p = p ^ s
        outs.append(p if p is not None else jnp.zeros_like(rows[0]))
    return outs


@functools.lru_cache(maxsize=None)
def _encode_fn(mat: tuple[tuple[int, ...], ...]):
    """jitted k flat uint32 rows -> tuple of m flat uint32 rows."""
    @jax.jit
    def f(*rows):
        return tuple(_apply_matrix_rows(rows, mat))
    return f


def _mat_to_tuple(mat: np.ndarray) -> tuple[tuple[int, ...], ...]:
    return tuple(tuple(int(x) for x in row) for row in np.asarray(mat))


def interpret_mode() -> bool:
    """Pallas kernels run the interpreter off-TPU so the CPU test mesh
    validates bit-identity (shared by rs_pallas / rs_mxu)."""
    return jax.default_backend() not in ("tpu", "axon")


def pad_rows_to_multiple(rows: np.ndarray, tile: int
                         ) -> tuple[np.ndarray, int]:
    """Zero-pad the last axis of a (k, n) array up to a multiple of
    `tile`; returns (padded, original_n)."""
    n = rows.shape[1]
    pad = (-n) % tile
    if pad:
        rows = np.concatenate(
            [rows, np.zeros((rows.shape[0], pad), dtype=rows.dtype)],
            axis=1)
    return rows, n


def parity_fn(scheme: RSScheme = DEFAULT_SCHEME):
    """The jitted parity kernel: k flat uint32 rows -> tuple of m rows.
    Flat separate rows are the fast device layout (module docstring)."""
    pm = gf256.parity_matrix(scheme.data_shards, scheme.parity_shards)
    return _encode_fn(_mat_to_tuple(pm))


@functools.lru_cache(maxsize=None)
def parity_words_fn(scheme: RSScheme = DEFAULT_SCHEME):
    """2D variant for vmap/mesh composition: (k, nw) uint32 -> (m, nw)."""
    pm = _mat_to_tuple(
        gf256.parity_matrix(scheme.data_shards, scheme.parity_shards))

    @jax.jit
    def f(words):
        return _apply_matrix_words(words, pm)
    return f


def decode_fn(scheme: RSScheme, present: tuple[int, ...]):
    """jitted kernel mapping the first k present shards -> all k data shards."""
    dm = gf256.decode_matrix(scheme.data_shards, scheme.total_shards, present)
    return _encode_fn(_mat_to_tuple(dm))


def bytes_to_words(rows: Sequence[bytes | np.ndarray]) -> tuple[np.ndarray, int]:
    """Stack byte rows into a (k, nw) uint32 matrix (zero-padded to 4B)."""
    n = len(rows[0])
    pad = (-n) % 4
    mats = []
    for r in rows:
        a = np.frombuffer(bytes(r), dtype=np.uint8) if not isinstance(r, np.ndarray) else r
        if pad:
            a = np.concatenate([a, np.zeros(pad, dtype=np.uint8)])
        mats.append(a.view(np.uint32))
    return np.stack(mats), n


def words_to_bytes(words: np.ndarray, n: int) -> list[bytes]:
    out = []
    for i in range(words.shape[0]):
        out.append(np.asarray(words[i]).view(np.uint8)[:n].tobytes())
    return out


@register_coder("jax")
class JaxCoder(ErasureCoder):
    """ErasureCoder running the GF(256) math on the default JAX backend
    (TPU when present). Byte-level results are bit-identical to CpuCoder."""

    def __init__(self, scheme: RSScheme = DEFAULT_SCHEME):
        super().__init__(scheme)
        self._parity_fn = parity_fn(scheme)

    def _run_rows(self, fn, words: np.ndarray) -> np.ndarray:
        """Apply a row-based jitted kernel to a (k, nw) uint32 host matrix,
        feeding each row as its own flat device array (see module
        docstring for why), and restack on the host."""
        outs = fn(*[words[i] for i in range(words.shape[0])])
        return np.stack([np.asarray(jax.device_get(o)) for o in outs])

    def encode(self, shards: Sequence[bytes]) -> list[bytes]:
        k = self.scheme.data_shards
        words, n = bytes_to_words([shards[i] for i in range(k)])
        parity = self._run_rows(self._parity_fn, words)
        return [bytes(shards[i]) for i in range(k)] + words_to_bytes(parity, n)

    def encode_array(self, data: np.ndarray) -> np.ndarray:
        """(k, n) uint8 -> (m, n) uint8 parity. n must be a multiple of 4."""
        assert data.shape[1] % 4 == 0
        words = np.ascontiguousarray(data).view(np.uint32)
        parity = self._run_rows(self._parity_fn, words)
        return parity.view(np.uint8)

    def reconstruct(self, shards: Sequence[Optional[bytes]]) -> list[bytes]:
        k, total = self.scheme.data_shards, self.scheme.total_shards
        present = tuple(i for i in range(total) if shards[i] is not None)
        if len(present) < k:
            raise ValueError(f"too few shards: {len(present)} < {k}")
        missing = [i for i in range(total) if shards[i] is None]
        if not missing:
            return [bytes(s) for s in shards]
        words, n = bytes_to_words([shards[i] for i in present[:k]])
        data_words = self._run_rows(decode_fn(self.scheme, present), words)
        data_rows = words_to_bytes(data_words, n)
        out = [bytes(shards[i]) if shards[i] is not None else None
               for i in range(total)]
        for i in range(k):
            if out[i] is None:
                out[i] = data_rows[i]
        if any(i >= k for i in missing):
            parity = self._run_rows(self._parity_fn, data_words)
            prows = words_to_bytes(parity, n)
            for i in missing:
                if i >= k:
                    out[i] = prows[i - k]
        return [bytes(s) for s in out]

    def reconstruct_data(self, shards: Sequence[Optional[bytes]]) -> list[Optional[bytes]]:
        k, total = self.scheme.data_shards, self.scheme.total_shards
        present = tuple(i for i in range(total) if shards[i] is not None)
        if len(present) < k:
            raise ValueError(f"too few shards: {len(present)} < {k}")
        if all(shards[i] is not None for i in range(k)):
            return [bytes(s) if s is not None else None for s in shards]
        words, n = bytes_to_words([shards[i] for i in present[:k]])
        data_words = self._run_rows(decode_fn(self.scheme, present), words)
        rows = words_to_bytes(data_words, n)
        out = [bytes(s) if s is not None else None for s in shards]
        for i in range(k):
            out[i] = rows[i]
        return out


# `pallas` name resolves here too until ops/rs_pallas.py specializes it.
register_coder("tpu")(JaxCoder)
