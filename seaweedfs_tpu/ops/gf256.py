"""GF(2^8) arithmetic and Reed-Solomon matrix construction.

Field: GF(2^8) with the primitive polynomial x^8+x^4+x^3+x^2+1 (0x11D),
generator 2 — the same field used by the reference's vendored codec
(klauspost/reedsolomon, itself derived from Backblaze's construction; see
reference go.mod:61 and weed/storage/erasure_coding/ec_encoder.go:17-23 for
where RS(10,4) is wired in). The encoding matrix is the systematic
Vandermonde construction: rows r of V are [r^0, r^1, ..., r^(k-1)], and the
final matrix is V * inv(V[:k]) so the top k rows are the identity. Matching
this construction exactly is what makes our .ec shards bit-identical to the
reference's.

Everything here is plain numpy — it is the ground-truth/reference path. The
TPU path (ops/rs_jax.py, ops/rs_pallas.py) is validated bit-for-bit against
this module.
"""

from __future__ import annotations

import functools

import numpy as np

GF_POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1
GF_GENERATOR = 2


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    """exp/log tables for GF(2^8) under GF_POLY with generator 2."""
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= GF_POLY
    # duplicate so exp[(log a + log b)] never needs an explicit mod
    for i in range(255, 512):
        exp[i] = exp[i - 255]
    return exp, log


GF_EXP, GF_LOG = _build_tables()


def gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return int(GF_EXP[int(GF_LOG[a]) + int(GF_LOG[b])])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("GF(256) division by zero")
    if a == 0:
        return 0
    return int(GF_EXP[(int(GF_LOG[a]) - int(GF_LOG[b])) % 255])


def gf_inv(a: int) -> int:
    return gf_div(1, a)


def gf_exp_pow(base: int, n: int) -> int:
    """base**n in GF(256), with 0**0 == 1 (matches the reference construction)."""
    if n == 0:
        return 1
    if base == 0:
        return 0
    return int(GF_EXP[(int(GF_LOG[base]) * n) % 255])


@functools.lru_cache(maxsize=None)
def _mul_table() -> np.ndarray:
    """Full 256x256 product table; MUL_TABLE[a, b] = a*b in GF(256)."""
    a = np.arange(256)
    la = GF_LOG[a][:, None]
    lb = GF_LOG[a][None, :]
    prod = GF_EXP[(la + lb) % 255].astype(np.uint8)
    prod[0, :] = 0
    prod[:, 0] = 0
    return prod


MUL_TABLE = _mul_table()


@functools.lru_cache(maxsize=None)
def nibble_tables(c: int) -> tuple[np.ndarray, np.ndarray]:
    """Split-nibble tables for constant c: (low, high), 16 entries each,
    with c*d == low[d & 0xF] ^ high[d >> 4]. This is the table shape the
    PSHUFB/VGF2P8 kernels consume (native/rs_cpu.cpp make_nibble_tables);
    exposed here for the pure-numpy fallback and its cross-validation."""
    low = MUL_TABLE[c, :16].copy()
    high = MUL_TABLE[c, [v << 4 for v in range(16)]].copy()
    low.setflags(write=False)
    high.setflags(write=False)
    return low, high


@functools.lru_cache(maxsize=None)
def pair_table(c: int) -> np.ndarray:
    """65536-entry uint16 table applying c bytewise to a little-endian
    byte pair: pair_table(c)[b0 | b1<<8] == (c*b0) | (c*b1)<<8.

    One gather per TWO bytes — the numpy analogue of widening the
    split-nibble trick to byte granularity (numpy has no in-register
    shuffle, so fewer/larger gathers beat two 16-entry lookups; measured
    3.1x over the single-byte MUL_TABLE gather, see PERF.md round 6).
    128KiB per cached coefficient; an RS(10,4) parity matrix uses <=40."""
    row = MUL_TABLE[c].astype(np.uint16)
    tab = (row[None, :] | (row[:, None] << 8)).reshape(-1)
    tab.setflags(write=False)
    return tab


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Matrix product over GF(256). a: (m, k) uint8, b: (k, n) uint8 -> (m, n)."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    m, k = a.shape
    k2, n = b.shape
    assert k == k2
    out = np.zeros((m, n), dtype=np.uint8)
    for j in range(k):
        # out ^= a[:, j] * b[j, :] elementwise over GF(256)
        out ^= MUL_TABLE[a[:, j][:, None], b[j, :][None, :]]
    return out


def gf_mat_invert(mat: np.ndarray) -> np.ndarray:
    """Invert a square matrix over GF(256) by Gauss-Jordan elimination."""
    mat = np.array(mat, dtype=np.uint8)
    n = mat.shape[0]
    assert mat.shape == (n, n)
    work = np.concatenate([mat, np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        # pivot
        if work[col, col] == 0:
            for r in range(col + 1, n):
                if work[r, col] != 0:
                    work[[col, r]] = work[[r, col]]
                    break
            else:
                raise np.linalg.LinAlgError("singular matrix over GF(256)")
        pivot = int(work[col, col])
        inv_p = gf_inv(pivot)
        work[col] = MUL_TABLE[inv_p, work[col]]
        for r in range(n):
            if r != col and work[r, col] != 0:
                factor = int(work[r, col])
                work[r] ^= MUL_TABLE[factor, work[col]]
    return work[:, n:].copy()


@functools.lru_cache(maxsize=None)
def rs_matrix(data_shards: int, total_shards: int) -> np.ndarray:
    """Systematic Vandermonde encoding matrix, (total, data) uint8.

    Top `data_shards` rows are the identity; the remaining rows generate
    parity. Construction matches the reference codec so RS(10,4) shards are
    bit-identical.
    """
    assert 0 < data_shards < total_shards <= 256
    rows = total_shards
    cols = data_shards
    vm = np.zeros((rows, cols), dtype=np.uint8)
    for r in range(rows):
        for c in range(cols):
            vm[r, c] = gf_exp_pow(r, c)
    top_inv = gf_mat_invert(vm[:cols, :cols])
    mat = gf_matmul(vm, top_inv)
    mat.setflags(write=False)
    return mat


def parity_matrix(data_shards: int, parity_shards: int) -> np.ndarray:
    """The (parity, data) sub-matrix that maps data shards to parity shards."""
    return rs_matrix(data_shards, data_shards + parity_shards)[data_shards:]


@functools.lru_cache(maxsize=None)
def decode_matrix(data_shards: int, total_shards: int,
                  present: tuple[int, ...]) -> np.ndarray:
    """Matrix mapping the first `data_shards` present shards -> data shards.

    `present` is the sorted tuple of available shard indices (>= data_shards
    of them). Returns (data_shards, data_shards) uint8 D such that
    data = D @ stack(shards[present[:data_shards]]).
    """
    assert len(present) >= data_shards
    rows = rs_matrix(data_shards, total_shards)
    sub = rows[list(present[:data_shards]), :]
    return gf_mat_invert(sub)
