"""CPU Reed-Solomon coder (numpy, with optional native C++ backend).

Plays the role klauspost/reedsolomon's SIMD codec plays in the reference
(go.mod:61; invoked from weed/storage/erasure_coding/ec_encoder.go:199):
the default, always-available codec the TPU path is measured against and
validated bit-for-bit against.

Two coders are registered:
  - "cpu":    single-threaded (the benchmark denominator — one core, so
              TPU-vs-CPU ratios stay comparable across machines)
  - "cpu-mt": shards each batch across a thread pool by column range.
              The native kernel releases the GIL and its strided entry
              point writes only its own columns, so workers need zero
              copies; the numpy fallback shards by column slices. Both
              produce output bit-identical to "cpu" regardless of worker
              count — XOR accumulation is positionally independent.
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Optional, Sequence

import numpy as np

from seaweedfs_tpu.models.coder import (DEFAULT_SCHEME, ErasureCoder,
                                        RSScheme, register_coder)
from seaweedfs_tpu.ops import gf256

# column-shard boundaries stay multiples of the widest vector stride (the
# GFNI tier consumes 128B; 64 keeps word alignment and cache-line locality)
_SHARD_ALIGN = 64
# below this, pool dispatch overhead beats the parallelism
_MIN_PARALLEL_BYTES = 1 << 16

_pool_lock = threading.Lock()
_pool: Optional[ThreadPoolExecutor] = None
_pool_size = 0


def _worker_pool(workers: int) -> ThreadPoolExecutor:
    """Shared process-wide pool, grown to the largest size requested —
    coders are cheap to construct, threads are not."""
    global _pool, _pool_size
    with _pool_lock:
        if _pool is None or _pool_size < workers:
            if _pool is not None:
                _pool.shutdown(wait=False)
            _pool = ThreadPoolExecutor(max_workers=workers,
                                       thread_name_prefix="rs-cpu")
            _pool_size = workers
        return _pool


def auto_workers() -> int:
    """Worker count for 'auto': SEAWEEDFS_TPU_EC_WORKERS overrides, else
    the scheduler-visible core count."""
    env = os.environ.get("SEAWEEDFS_TPU_EC_WORKERS")
    if env:
        return max(1, int(env))
    try:
        return len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        return os.cpu_count() or 1


def _column_cuts(n: int, workers: int) -> list[int]:
    """[0, ..., n] boundaries: `workers` near-equal ranges, all interior
    cuts _SHARD_ALIGN-aligned."""
    per = -(-n // workers)
    per = -(-per // _SHARD_ALIGN) * _SHARD_ALIGN
    cuts = list(range(0, n, per)) + [n]
    return cuts


def _as_matrix(shards: Sequence[bytes], indices: list[int]) -> np.ndarray:
    rows = [np.frombuffer(shards[i], dtype=np.uint8) for i in indices]
    return np.stack(rows, axis=0)


def _native():
    try:
        from seaweedfs_tpu.native import rs_native
        if rs_native.available():
            return rs_native
    except ImportError:
        pass
    return None


def _gf_apply_numpy_into(mat: np.ndarray, data: np.ndarray,
                         out: np.ndarray) -> None:
    """Pure-numpy fallback: one 65536-entry table gather per byte PAIR
    (gf256.pair_table). 3.1x the old per-byte MUL_TABLE gather; the
    classic two-16-entry split-nibble gathers are SLOWER under numpy
    (no in-register shuffle — see _gf_apply_nibble and PERF.md)."""
    m, k = mat.shape
    n = data.shape[1]
    even = n - (n & 1)
    for j in range(k):
        d = data[j]
        d16 = d[:even].view(np.uint16)
        for i in range(m):
            c = int(mat[i, j])
            if c == 0:
                continue
            o16 = out[i, :even].view(np.uint16)
            if c == 1:
                o16 ^= d16
                if even != n:
                    out[i, -1] ^= d[-1]
            else:
                o16 ^= gf256.pair_table(c)[d16]
                if even != n:
                    out[i, -1] ^= gf256.MUL_TABLE[c][d[-1]]


def _gf_apply_nibble(mat: np.ndarray, data: np.ndarray) -> np.ndarray:
    """The textbook split-nibble formulation (two 16-entry tables, two
    np.take gathers per byte) — what the AVX2 PSHUFB kernel does in
    registers. Kept as a cross-check and for the PERF.md comparison; the
    pair-table path above wins in numpy because gather cost scales with
    gather COUNT, not table size."""
    m, k = mat.shape
    out = np.zeros((m, data.shape[1]), dtype=np.uint8)
    for j in range(k):
        d = data[j]
        lo = d & 0x0F
        hi = d >> 4
        for i in range(m):
            c = int(mat[i, j])
            if c == 0:
                continue
            tlo, thi = gf256.nibble_tables(c)
            out[i] ^= np.take(tlo, lo) ^ np.take(thi, hi)
    return out


def _gf_apply(mat: np.ndarray, data: np.ndarray, use_native: bool = True,
              workers: int = 1, out: Optional[np.ndarray] = None) -> np.ndarray:
    """out[i] = XOR_j mat[i,j] * data[j] over GF(256).

    data: (k, n) uint8; mat: (m, k) uint8 -> (m, n) uint8. With
    workers > 1 the columns are sharded across a thread pool; output is
    bit-identical to workers == 1. A caller-provided `out` must be
    zero-filled (the kernels accumulate)."""
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    m, _ = mat.shape
    n = data.shape[1]
    if out is None:
        out = np.zeros((m, n), dtype=np.uint8)
    native = _native() if use_native else None
    if workers > 1 and n >= _MIN_PARALLEL_BYTES:
        cuts = _column_cuts(n, workers)
        if len(cuts) > 2:
            pool = _worker_pool(len(cuts) - 1)
            if native is not None:
                futs = [pool.submit(native.gf_apply_into, mat, data, out,
                                    a, b - a)
                        for a, b in zip(cuts, cuts[1:])]
            else:
                futs = [pool.submit(_gf_apply_numpy_into, mat,
                                    data[:, a:b], out[:, a:b])
                        for a, b in zip(cuts, cuts[1:])]
            for f in futs:
                f.result()
            return out
    if native is not None:
        native.gf_apply_into(mat, data, out)
    else:
        _gf_apply_numpy_into(mat, data, out)
    return out


def gf_partial_product(coeffs: np.ndarray, rows: np.ndarray,
                       out: Optional[np.ndarray] = None,
                       use_native: bool = True,
                       workers: int = 1) -> np.ndarray:
    """Partial-column product for distributed repair: out[i] ^=
    XOR_j coeffs[i,j] * rows[j] over GF(256).

    This is the per-holder half of a decode matmul split by column: a
    shard holder applies its own columns of the rebuild matrix to its
    local shard ranges and ships the pre-reduced (n_rows, n) result;
    the rebuilder (or the next hop of a reduction chain) XOR-folds the
    contributions, which is associative and commutative, so any
    grouping of holders produces bytes identical to the one-machine
    decode. `coeffs` may be 1-D (a single output row); a caller-provided
    `out` must be zero-filled on first use (the kernels accumulate)."""
    mat = np.asarray(coeffs, dtype=np.uint8)
    if mat.ndim == 1:
        mat = mat[None, :]
    data = np.asarray(rows, dtype=np.uint8)
    if data.ndim == 1:
        data = data[None, :]
    return _gf_apply(mat, data, use_native, workers, out)


@register_coder("cpu")
class CpuCoder(ErasureCoder):
    def __init__(self, scheme: RSScheme = DEFAULT_SCHEME,
                 use_native: bool = True, workers: int | str = 1):
        super().__init__(scheme)
        self.use_native = use_native
        self.workers = auto_workers() if workers == "auto" else max(1, workers)
        self._parity = np.asarray(
            gf256.parity_matrix(scheme.data_shards, scheme.parity_shards))

    def _apply(self, mat: np.ndarray, data: np.ndarray,
               out: Optional[np.ndarray] = None) -> np.ndarray:
        return _gf_apply(mat, data, self.use_native, self.workers, out)

    def encode(self, shards: Sequence[bytes]) -> list[bytes]:
        k, total = self.scheme.data_shards, self.scheme.total_shards
        assert len(shards) >= k
        n = len(shards[0])
        assert all(len(shards[i]) == n for i in range(k)), "unequal shard sizes"
        data = _as_matrix(shards, list(range(k)))
        parity = self._apply(self._parity, data)
        out = [bytes(shards[i]) for i in range(k)]
        out += [parity[i].tobytes() for i in range(total - k)]
        return out

    def encode_array(self, data: np.ndarray) -> np.ndarray:
        """(k, n) uint8 -> (m, n) uint8 parity, no bytes round-trip."""
        return self._apply(self._parity,
                           np.ascontiguousarray(data, dtype=np.uint8))

    def encode_into(self, data: np.ndarray, out: np.ndarray) -> np.ndarray:
        """encode_array into a caller-owned (m, n) buffer (pipelines reuse
        pooled buffers to avoid per-batch allocation). Zero-fills `out`
        first — the kernels accumulate."""
        out[:] = 0
        return self._apply(self._parity,
                           np.ascontiguousarray(data, dtype=np.uint8), out)

    def reconstruct(self, shards: Sequence[Optional[bytes]]) -> list[bytes]:
        k, total = self.scheme.data_shards, self.scheme.total_shards
        assert len(shards) == total
        present = [i for i in range(total) if shards[i] is not None]
        if len(present) < k:
            raise ValueError(
                f"too few shards to reconstruct: {len(present)} < {k}")
        missing = [i for i in range(total) if shards[i] is None]
        if not missing:
            return [bytes(s) for s in shards]
        out = [bytes(s) if s is not None else None for s in shards]
        n = len(shards[present[0]])

        src = present[:k]
        dmat = np.asarray(gf256.decode_matrix(k, total, tuple(present)))
        srcdata = _as_matrix(shards, src)

        missing_data = [i for i in missing if i < k]
        if missing_data:
            rows = dmat[missing_data, :]
            rec = self._apply(rows, srcdata)
            for r, i in enumerate(missing_data):
                out[i] = rec[r].tobytes()

        missing_parity = [i for i in missing if i >= k]
        if missing_parity:
            # need full data matrix; reuse recovered rows
            full = np.empty((k, n), dtype=np.uint8)
            for i in range(k):
                full[i] = np.frombuffer(out[i], dtype=np.uint8)
            pm = self._parity[[i - k for i in missing_parity], :]
            par = self._apply(pm, full)
            for r, i in enumerate(missing_parity):
                out[i] = par[r].tobytes()
        return out

    def rebuild_matrix(self, present: Sequence[int],
                       missing: Sequence[int]) -> np.ndarray:
        """Coefficient rows expressing each `missing` shard (data OR
        parity) as a GF(256) combination of the first k `present` shards.
        Constant across a whole volume walk — pipelines compute it once
        and stream batches through reconstruct_arrays/_apply."""
        k, total = self.scheme.data_shards, self.scheme.total_shards
        present = tuple(sorted(present))
        assert len(present) >= k
        dmat = np.asarray(gf256.decode_matrix(k, total, present))
        rows = []
        for i in missing:
            if i < k:
                rows.append(dmat[i])
            else:
                rows.append(gf256.gf_matmul(
                    self._parity[i - k][None, :], dmat)[0])
        return np.stack(rows).astype(np.uint8)

    def reconstruct_rows(self, srcdata: np.ndarray,
                         rebuild_mat: np.ndarray,
                         out: Optional[np.ndarray] = None) -> np.ndarray:
        """Apply a rebuild_matrix() to (k, n) rows of the first k present
        shards -> (len(missing), n) recovered rows. (Distinct from the
        base reconstruct_arrays, which takes a {shard_id: row} dict and
        re-derives the matrix per call.)"""
        if out is not None:
            out[:] = 0
        return self._apply(rebuild_mat,
                           np.ascontiguousarray(srcdata, dtype=np.uint8), out)

    def reconstruct_data(self, shards: Sequence[Optional[bytes]]) -> list[Optional[bytes]]:
        k, total = self.scheme.data_shards, self.scheme.total_shards
        present = [i for i in range(total) if shards[i] is not None]
        if len(present) < k:
            raise ValueError(
                f"too few shards to reconstruct: {len(present)} < {k}")
        out = [bytes(s) if s is not None else None for s in shards]
        missing_data = [i for i in range(k) if shards[i] is None]
        if missing_data:
            dmat = np.asarray(gf256.decode_matrix(k, total, tuple(present)))
            rows = dmat[missing_data, :]
            rec = self._apply(rows, _as_matrix(shards, present[:k]))
            for r, i in enumerate(missing_data):
                out[i] = rec[r].tobytes()
        return out


@register_coder("cpu-mt")
class CpuCoderMT(CpuCoder):
    """CpuCoder with workers='auto' — what the volume-server EC pipelines
    construct by default. Same bits out, more cores in."""

    def __init__(self, scheme: RSScheme = DEFAULT_SCHEME,
                 use_native: bool = True):
        super().__init__(scheme, use_native=use_native, workers="auto")
