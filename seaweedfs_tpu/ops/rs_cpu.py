"""CPU Reed-Solomon coder (numpy, with optional native C++ backend).

Plays the role klauspost/reedsolomon's SIMD codec plays in the reference
(go.mod:61; invoked from weed/storage/erasure_coding/ec_encoder.go:199):
the default, always-available codec the TPU path is measured against and
validated bit-for-bit against.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from seaweedfs_tpu.models.coder import (DEFAULT_SCHEME, ErasureCoder,
                                        RSScheme, register_coder)
from seaweedfs_tpu.ops import gf256


def _as_matrix(shards: Sequence[bytes], indices: list[int]) -> np.ndarray:
    rows = [np.frombuffer(shards[i], dtype=np.uint8) for i in indices]
    return np.stack(rows, axis=0)


def _gf_apply(mat: np.ndarray, data: np.ndarray, use_native: bool = True) -> np.ndarray:
    """out[i] = XOR_j mat[i,j] * data[j] over GF(256), vectorized per entry.

    data: (k, n) uint8; mat: (m, k) uint8 -> (m, n) uint8.
    """
    if use_native:
        try:
            from seaweedfs_tpu.native import rs_native
            if rs_native.available():
                return rs_native.gf_apply(mat, data)
        except ImportError:
            pass
    m, k = mat.shape
    out = np.zeros((m, data.shape[1]), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            c = int(mat[i, j])
            if c == 0:
                continue
            elif c == 1:
                out[i] ^= data[j]
            else:
                out[i] ^= gf256.MUL_TABLE[c][data[j]]
    return out


@register_coder("cpu")
class CpuCoder(ErasureCoder):
    def __init__(self, scheme: RSScheme = DEFAULT_SCHEME, use_native: bool = True):
        super().__init__(scheme)
        self.use_native = use_native
        self._parity = np.asarray(
            gf256.parity_matrix(scheme.data_shards, scheme.parity_shards))

    def encode(self, shards: Sequence[bytes]) -> list[bytes]:
        k, total = self.scheme.data_shards, self.scheme.total_shards
        assert len(shards) >= k
        n = len(shards[0])
        assert all(len(shards[i]) == n for i in range(k)), "unequal shard sizes"
        data = _as_matrix(shards, list(range(k)))
        parity = _gf_apply(self._parity, data, self.use_native)
        out = [bytes(shards[i]) for i in range(k)]
        out += [parity[i].tobytes() for i in range(total - k)]
        return out

    def encode_array(self, data: np.ndarray) -> np.ndarray:
        """(k, n) uint8 -> (m, n) uint8 parity, no bytes round-trip."""
        return _gf_apply(self._parity, np.ascontiguousarray(data, dtype=np.uint8),
                         self.use_native)

    def reconstruct(self, shards: Sequence[Optional[bytes]]) -> list[bytes]:
        k, total = self.scheme.data_shards, self.scheme.total_shards
        assert len(shards) == total
        present = [i for i in range(total) if shards[i] is not None]
        if len(present) < k:
            raise ValueError(
                f"too few shards to reconstruct: {len(present)} < {k}")
        missing = [i for i in range(total) if shards[i] is None]
        if not missing:
            return [bytes(s) for s in shards]
        out = [bytes(s) if s is not None else None for s in shards]
        n = len(shards[present[0]])

        src = present[:k]
        dmat = np.asarray(gf256.decode_matrix(k, total, tuple(present)))
        srcdata = _as_matrix(shards, src)

        missing_data = [i for i in missing if i < k]
        if missing_data:
            rows = dmat[missing_data, :]
            rec = _gf_apply(rows, srcdata, self.use_native)
            for r, i in enumerate(missing_data):
                out[i] = rec[r].tobytes()

        missing_parity = [i for i in missing if i >= k]
        if missing_parity:
            # need full data matrix; reuse recovered rows
            full = np.empty((k, n), dtype=np.uint8)
            for i in range(k):
                full[i] = np.frombuffer(out[i], dtype=np.uint8)
            pm = self._parity[[i - k for i in missing_parity], :]
            par = _gf_apply(pm, full, self.use_native)
            for r, i in enumerate(missing_parity):
                out[i] = par[r].tobytes()
        return out

    def reconstruct_data(self, shards: Sequence[Optional[bytes]]) -> list[Optional[bytes]]:
        k, total = self.scheme.data_shards, self.scheme.total_shards
        present = [i for i in range(total) if shards[i] is not None]
        if len(present) < k:
            raise ValueError(
                f"too few shards to reconstruct: {len(present)} < {k}")
        out = [bytes(s) if s is not None else None for s in shards]
        missing_data = [i for i in range(k) if shards[i] is None]
        if missing_data:
            dmat = np.asarray(gf256.decode_matrix(k, total, tuple(present)))
            rows = dmat[missing_data, :]
            rec = _gf_apply(rows, _as_matrix(shards, present[:k]), self.use_native)
            for r, i in enumerate(missing_data):
                out[i] = rec[r].tobytes()
        return out
