"""Fused MXU bitplane RS kernel — built, measured, and NOT the default.

GF(256) multiplication is GF(2)-linear, so the whole RS(10,4) parity
transform factors into one binary matrix B (8m x 8k) acting on bitplanes:
bit r of parity byte i at position t = XOR over (j,b) of
B[8i+r, 8j+b] & (bit b of data byte j at t), with
B[8i+r, 8j+b] = bit r of (M[i,j] * 2^b).

This kernel fuses, per VMEM tile: uint8 -> 8 bitplane unpack (VPU), a
bf16 (32 x 80) @ (80 x TILE) matmul on the MXU (sums <= 80 are exact in
bf16), mod-2 via the result's LSB, and bitplane -> byte repack (VPU).

Measured on v5e (32MB shards, parity materialized to HBM):
    fused MXU bitplane (this kernel):   ~7.6 GB/s of input
    XLA-fused flat-row Horner (rs_jax): ~193  GB/s of input
Two structural reasons, with the arithmetic:
  1. The MXU runs a 32x80 stationary matrix on a 128x128 systolic array —
     15.6% utilization, capping the matmul path near ~60 GB/s of input
     even if unpack/pack were free.
  2. Unpack/pack 8x the data through int32 lanes plus the (80, TILE)
     relayout is far more VPU work than the Horner chain it replaces; the
     VPU is the bottleneck, not the MXU.
The VPU Horner path is HBM-bandwidth-bound (~270 GB/s of traffic), so no
MXU formulation of this transform can beat it on this part. Kept as a
registered coder ("mxu") for the measurement to stay reproducible; see
PERF.md.

Bit-identity with the CPU coder is tested in interpret mode on the CPU
mesh (tests/test_pallas.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from seaweedfs_tpu.models.coder import (DEFAULT_SCHEME, RSScheme,
                                        register_coder)
from seaweedfs_tpu.ops import gf256
from seaweedfs_tpu.ops.rs_jax import (JaxCoder, interpret_mode,
                                      pad_rows_to_multiple)

DEFAULT_TILE = 4096  # bytes per row block (VMEM bound: 80 int32 planes)


def bitplane_matrix(mat: np.ndarray) -> np.ndarray:
    """The (8m, 8k) GF(2) matrix equivalent to byte matrix `mat`."""
    m, k = mat.shape
    B = np.zeros((8 * m, 8 * k), dtype=np.float32)
    for i in range(m):
        for j in range(k):
            for b in range(8):
                prod = int(gf256.gf_mul(int(mat[i, j]), 1 << b))
                for r in range(8):
                    if (prod >> r) & 1:
                        B[8 * i + r, 8 * j + b] = 1.0
    return B


def _make_kernel(m: int, k: int):
    def kernel(*refs):
        bref = refs[0]
        ins, outs = refs[1:1 + k], refs[1 + k:1 + k + m]
        B = bref[:]
        planes = []
        for j in range(k):
            d = ins[j][:].astype(jnp.int32)
            for b in range(8):
                planes.append(((d >> b) & 1).astype(jnp.bfloat16))
        X = jnp.stack(planes)                      # (8k, TILE)
        Y = jax.lax.dot_general(B, X, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        Yi = Y.astype(jnp.int32) & 1               # mod 2
        for i in range(m):
            acc = Yi[8 * i]
            for r in range(1, 8):
                acc = acc | (Yi[8 * i + r] << r)
            outs[i][:] = acc.astype(jnp.uint8)
    return kernel


@functools.lru_cache(maxsize=None)
def mxu_apply_fn(mat_key: tuple[tuple[int, ...], ...],
                 tile: int = DEFAULT_TILE):
    """jitted (k flat uint8 rows) -> tuple of m flat uint8 rows via the
    fused bitplane MXU kernel. Row length must be a multiple of `tile`."""
    mat = np.array(mat_key, dtype=np.uint8)
    m, k = mat.shape
    B = jnp.asarray(bitplane_matrix(mat), jnp.bfloat16)
    kernel = _make_kernel(m, k)
    interpret = interpret_mode()

    @jax.jit
    def run(*rows):
        n = rows[0].shape[0]
        grid = (n // tile,)
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[pl.BlockSpec((8 * m, 8 * k), lambda i: (0, 0),
                                   memory_space=pltpu.VMEM)] +
                     [pl.BlockSpec((tile,), lambda i: (i,),
                                   memory_space=pltpu.VMEM)] * k,
            out_specs=[pl.BlockSpec((tile,), lambda i: (i,),
                                    memory_space=pltpu.VMEM)] * m,
            out_shape=[jax.ShapeDtypeStruct((n,), jnp.uint8)] * m,
            interpret=interpret,
        )(B, *rows)

    return run


@register_coder("mxu")
class MxuCoder(JaxCoder):
    """JaxCoder with the parity transform on the fused MXU bitplane kernel.
    Registered for reproducible measurement; slower than the default —
    see module docstring."""

    def __init__(self, scheme: RSScheme = DEFAULT_SCHEME,
                 tile: int = DEFAULT_TILE):
        super().__init__(scheme)
        self.tile = tile
        pm = np.asarray(gf256.parity_matrix(scheme.data_shards,
                                            scheme.parity_shards))
        self._mxu_parity = mxu_apply_fn(
            tuple(tuple(int(x) for x in row) for row in pm), tile)
        self._parity_fn = self._parity_rows

    def _parity_rows(self, *rows):
        # rows arrive as uint32 words (JaxCoder convention); the bitplane
        # kernel works on bytes
        arr = np.stack([np.asarray(r) for r in rows]).view(np.uint8)
        arr, n = pad_rows_to_multiple(arr, self.tile)
        outs = self._mxu_parity(*[arr[i] for i in range(arr.shape[0])])
        return tuple(np.asarray(o)[:n].view(np.uint32) for o in outs)
