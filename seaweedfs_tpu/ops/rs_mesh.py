"""Mesh-sharded Reed-Solomon coder: a BATCH of block-groups per dispatch.

The single-volume coders (rs_cpu / rs_jax) encode one (k, n) block-group
per call, so concurrent ``ec.encode`` pipelines and repair jobs serialize
on the device.  MeshCoder lowers a batch of B independent block-groups —
typically coalesced from several volumes by parallel/batcher.py — into
ONE vmapped dispatch whose leading axis is sharded across a 1-D device
mesh (parallel/mesh.batch_mesh): device d computes lanes
[d*B/n .. (d+1)*B/n) with no collectives, so throughput scales with
device count for batches that fill the mesh.

Two compiled programs cover every operation:

  - encode: the static RS(10,4) parity matrix unrolls at trace time into
    the same Horner/XOR graph as rs_jax (bit-identical by construction);
  - rebuild: the coefficient matrix arrives as a TRACED (B, m, k) operand
    (zero rows disabled), so one program serves every survivor pattern in
    the batch — jobs with different loss patterns ride one dispatch.

Batches are zero-padded to a device-count multiple on the leading axis
(NamedSharding needs even division); pad lanes are discarded on the host.
Output is bit-identical to CpuCoder in all modes — GF(256) has no
rounding to disagree about, and the tests hold it to that.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from seaweedfs_tpu.models.coder import (DEFAULT_SCHEME, ErasureCoder,
                                        RSScheme, register_coder)
from seaweedfs_tpu.ops import gf256
from seaweedfs_tpu.ops.rs_jax import _apply_matrix_words, _mat_to_tuple
from seaweedfs_tpu.parallel import mesh as mesh_mod
from seaweedfs_tpu.parallel.distributed import _gf_mul_dynamic


@functools.lru_cache(maxsize=None)
def batch_encode_fn(scheme: RSScheme, mesh: Mesh):
    """jit over the mesh: (B, k, nw) uint32 sharded P('batch', None, None)
    -> (B, m, nw) parity with matching sharding.  Static parity matrix,
    no collectives."""
    mat = _mat_to_tuple(gf256.parity_matrix(scheme.data_shards,
                                            scheme.parity_shards))

    def one(words):
        return _apply_matrix_words(words, mat)

    s3 = mesh_mod.batch_spec(mesh)
    return jax.jit(jax.vmap(one), in_shardings=(s3,), out_shardings=s3)


@functools.lru_cache(maxsize=None)
def batch_apply_fn(mesh: Mesh, n_out: int):
    """jit over the mesh: per-lane GF matrix application with TRACED
    coefficients — (B, k, nw) words x (B, n_out, k) coeff -> (B, n_out,
    nw).  Zero coefficient rows yield zero output rows, so one compiled
    program serves every (survivor pattern, missing set) mix in a
    batch."""

    def one(words, coeff):
        outs = []
        for i in range(n_out):
            acc = jnp.zeros_like(words[0])
            for j in range(words.shape[0]):
                acc = acc ^ _gf_mul_dynamic(coeff[i, j], words[j])
            outs.append(acc)
        return jnp.stack(outs)

    s3 = mesh_mod.batch_spec(mesh)
    return jax.jit(jax.vmap(one), in_shardings=(s3, s3), out_shardings=s3)


@register_coder("mesh")
class MeshCoder(ErasureCoder):
    """ErasureCoder whose unit of dispatch is a batch of block-groups
    sharded across a 1-D device mesh.  The scalar ErasureCoder API is a
    batch of one (bit-identical, just not faster); the batch API is what
    parallel/batcher.py feeds."""

    def __init__(self, scheme: RSScheme = DEFAULT_SCHEME,
                 n_devices: int | None = None, mesh: Optional[Mesh] = None):
        super().__init__(scheme)
        self.mesh = mesh if mesh is not None else mesh_mod.batch_mesh(n_devices)
        # host-side helper for rebuild-matrix derivation (pure numpy)
        from seaweedfs_tpu.ops.rs_cpu import CpuCoder
        self._host = CpuCoder(scheme)

    @property
    def n_devices(self) -> int:
        return self.mesh.devices.size

    # ---- batch API (the batcher's entry points) ----

    def _pad_batch(self, words: np.ndarray) -> np.ndarray:
        b = words.shape[0]
        pb = -(-b // self.n_devices) * self.n_devices
        if pb == b:
            return words
        pad = np.zeros((pb - b,) + words.shape[1:], dtype=words.dtype)
        return np.concatenate([words, pad], axis=0)

    def encode_batch(self, batch: np.ndarray) -> np.ndarray:
        """(B, k, n) uint8 -> (B, m, n) uint8 parity, one sharded
        dispatch.  n must be a multiple of 4 (uint32 lanes)."""
        B, k, n = batch.shape
        assert k == self.scheme.data_shards, (k, self.scheme)
        assert n % 4 == 0, n
        words = self._pad_batch(np.ascontiguousarray(batch).view(np.uint32))
        fn = batch_encode_fn(self.scheme, self.mesh)
        out = np.asarray(jax.device_get(fn(words)))
        return np.ascontiguousarray(out[:B]).view(np.uint8)

    def rebuild_batch(self, srcdata: np.ndarray,
                      mats: Sequence[np.ndarray]) -> list[np.ndarray]:
        """srcdata: (B, k, n) uint8 — per job, rows of the first k
        present shards.  mats[i]: (r_i, k) uint8 rebuild matrix (from
        rebuild_matrix(); r_i <= parity_shards).  Returns a list of
        (r_i, n) uint8 recovered rows, one per job, in one sharded
        dispatch even when jobs lost different shards."""
        B, k, n = srcdata.shape
        assert k == self.scheme.data_shards and n % 4 == 0
        assert len(mats) == B
        m = self.scheme.parity_shards
        coeff = np.zeros((B, m, k), dtype=np.uint32)
        for i, mt in enumerate(mats):
            mt = np.asarray(mt)
            assert mt.shape == (mt.shape[0], k) and mt.shape[0] <= m, mt.shape
            coeff[i, :mt.shape[0]] = mt.astype(np.uint32)
        words = self._pad_batch(np.ascontiguousarray(srcdata).view(np.uint32))
        coeff = self._pad_batch(coeff)
        fn = batch_apply_fn(self.mesh, m)
        out = np.asarray(jax.device_get(fn(words, coeff)))  # (pb, m, nw)
        out8 = np.ascontiguousarray(out[:B]).view(np.uint8)  # (B, m, n)
        return [np.ascontiguousarray(out8[i, :np.asarray(mats[i]).shape[0]])
                for i in range(B)]

    # ---- scalar ErasureCoder API (batch of one) ----

    def encode_array(self, data: np.ndarray) -> np.ndarray:
        assert data.shape[1] % 4 == 0
        return self.encode_batch(
            np.ascontiguousarray(data, dtype=np.uint8)[None])[0]

    def encode_into(self, data: np.ndarray, out: np.ndarray) -> np.ndarray:
        out[:] = self.encode_array(data)
        return out

    def encode(self, shards: Sequence[bytes]) -> list[bytes]:
        k = self.scheme.data_shards
        n = len(shards[0])
        pad = (-n) % 4
        data = np.zeros((k, n + pad), dtype=np.uint8)
        for i in range(k):
            data[i, :n] = np.frombuffer(bytes(shards[i]), dtype=np.uint8)
        parity = self.encode_batch(data[None])[0]
        return [bytes(shards[i]) for i in range(k)] + \
            [parity[i, :n].tobytes() for i in range(self.scheme.parity_shards)]

    def rebuild_matrix(self, present: Sequence[int],
                       missing: Sequence[int]) -> np.ndarray:
        return self._host.rebuild_matrix(present, missing)

    def reconstruct_rows(self, srcdata: np.ndarray,
                         rebuild_mat: np.ndarray,
                         out: Optional[np.ndarray] = None) -> np.ndarray:
        rec = self.rebuild_batch(
            np.ascontiguousarray(srcdata, dtype=np.uint8)[None],
            [rebuild_mat])[0]
        if out is not None:
            out[:] = rec
            return out
        return rec

    def reconstruct(self, shards: Sequence[Optional[bytes]]) -> list[bytes]:
        k, total = self.scheme.data_shards, self.scheme.total_shards
        present = [i for i in range(total) if shards[i] is not None]
        if len(present) < k:
            raise ValueError(f"too few shards: {len(present)} < {k}")
        missing = [i for i in range(total) if shards[i] is None]
        if not missing:
            return [bytes(s) for s in shards]
        n = len(shards[present[0]])
        pad = (-n) % 4
        src = np.zeros((k, n + pad), dtype=np.uint8)
        for r, i in enumerate(sorted(present)[:k]):
            src[r, :n] = np.frombuffer(bytes(shards[i]), dtype=np.uint8)
        # rebuild_matrix expresses data AND parity losses directly as
        # combinations of the first k present shards — one dispatch
        mat = self.rebuild_matrix(present, missing)
        rec = self.rebuild_batch(src[None], [mat])[0]
        out = [bytes(s) if s is not None else None for s in shards]
        for r, i in enumerate(missing):
            out[i] = rec[r, :n].tobytes()
        return [bytes(s) for s in out]

    def reconstruct_data(self, shards: Sequence[Optional[bytes]]
                         ) -> list[Optional[bytes]]:
        k, total = self.scheme.data_shards, self.scheme.total_shards
        present = [i for i in range(total) if shards[i] is not None]
        if len(present) < k:
            raise ValueError(f"too few shards: {len(present)} < {k}")
        missing_data = [i for i in range(k) if shards[i] is None]
        out = [bytes(s) if s is not None else None for s in shards]
        if not missing_data:
            return out
        n = len(shards[present[0]])
        pad = (-n) % 4
        src = np.zeros((k, n + pad), dtype=np.uint8)
        for r, i in enumerate(sorted(present)[:k]):
            src[r, :n] = np.frombuffer(bytes(shards[i]), dtype=np.uint8)
        mat = self.rebuild_matrix(present, missing_data)
        rec = self.rebuild_batch(src[None], [mat])[0]
        for r, i in enumerate(missing_data):
            out[i] = rec[r, :n].tobytes()
        return out
