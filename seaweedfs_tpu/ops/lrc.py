"""Locally-repairable code LRC(k, l, g) on the rs_cpu GF(2^8) substrate.

Pyramid-style construction (Huang et al., "Pyramid Codes"; the Facebook
warehouse study arXiv:1309.0186 measures why): take the systematic
RS(k, k+g+1) generator, split its first parity row into `l` group-local
rows (coefficients zeroed outside the group), keep the remaining `g`
rows as global parities. Basic pyramid codes are *maximally
recoverable*: an erasure pattern decodes iff it is information-
theoretically decodable for the (k, l, g) topology — one erasure per
local group absorbed by that group's parity plus up to g more anywhere
(tests/test_lrc.py brute-forces all <=4-erasure patterns against that
criterion).

Shard id layout matches RS(10,4)'s so every byte of plumbing (.ec00-
.ec13 files, ecx indexes, layout constants) carries over: [0..k) data,
[k..k+l) local parities, [k+l..k+l+g) globals — 14 shards total for the
default LRC(10,2,2).

What the family buys: a single lost shard inside a group rebuilds from
the 5 surviving group members instead of k=10 columns — half the bytes
read per rebuilt MB — and degraded reads prefer the same 5-shard set
(arXiv:2306.10528). plan_rebuild() returns the cheapest (sources,
matrix) pair per failure pattern; its matrices are ordinary GF(256)
matmuls, so encode/rebuild ride the same _gf_apply kernels (and the
EcBatchScheduler / jax backends) as Reed-Solomon.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from seaweedfs_tpu.models.coder import LrcScheme, register_coder
from seaweedfs_tpu.ops import gf256
from seaweedfs_tpu.ops.rs_cpu import CpuCoder, auto_workers

DEFAULT_LRC_SCHEME = LrcScheme(10, 2, 2)


def generator_matrix(spec: LrcScheme) -> np.ndarray:
    """(total, k) uint8 generator: identity over data rows, then l local
    rows (the first RS parity row masked to each group), then g globals."""
    k = spec.data_shards
    base = np.asarray(gf256.rs_matrix(k, k + spec.global_parities + 1))
    split_row = base[k]
    rows = [np.eye(k, dtype=np.uint8)]
    gs = spec.group_size
    for g in range(spec.local_groups):
        local = np.zeros(k, dtype=np.uint8)
        local[g * gs:(g + 1) * gs] = split_row[g * gs:(g + 1) * gs]
        rows.append(local[None, :])
    rows.append(base[k + 1:k + 1 + spec.global_parities])
    return np.ascontiguousarray(np.vstack(rows), dtype=np.uint8)


def _gf_rref_pick(rows: np.ndarray, order: Sequence[int]) -> list[int]:
    """Greedy row selection: walk `order`, keep each row that raises the
    GF(256) rank, stop at full rank. Returns the kept indices (into the
    original row set) or all kept rows if rank stays short."""
    k = rows.shape[1]
    basis = np.zeros((0, k), dtype=np.uint8)
    pivots: list[int] = []
    kept: list[int] = []
    for idx in order:
        r = rows[idx].astype(np.uint8).copy()
        for b, p in zip(basis, pivots):
            if r[p]:
                r ^= gf256.MUL_TABLE[int(r[p])][b]
        nz = np.flatnonzero(r)
        if nz.size == 0:
            continue
        p = int(nz[0])
        r = gf256.MUL_TABLE[gf256.gf_inv(int(r[p]))][r]
        basis = np.vstack([basis, r[None, :]]) if basis.size else r[None, :]
        pivots.append(p)
        kept.append(idx)
        if len(kept) == k:
            break
    return kept


@register_coder("lrc")
class LrcCoder(CpuCoder):
    """LRC coder with the CpuCoder surface (encode/encode_array/
    encode_into/reconstruct/rebuild_matrix/reconstruct_rows/_parity/
    _apply) so every RS consumer — scrubber, partial-column chain,
    EcBatchScheduler, streaming encoder — works unchanged, plus
    plan_rebuild()/repair_strategy() for cheapest-repair planning."""

    def __init__(self, scheme: Optional[LrcScheme] = None,
                 use_native: bool = True, workers: int | str = 1):
        if scheme is None or not isinstance(scheme, LrcScheme):
            scheme = DEFAULT_LRC_SCHEME
        # skip CpuCoder.__init__'s RS parity_matrix: build the LRC one
        super(CpuCoder, self).__init__(scheme)
        self.use_native = use_native
        self.workers = auto_workers() if workers == "auto" else max(1, workers)
        self._gen = generator_matrix(scheme)
        self._parity = np.ascontiguousarray(
            self._gen[scheme.data_shards:])

    # ---- decode machinery (generator-matrix based, not Vandermonde) ----

    def _source_order(self, present: Sequence[int],
                      prefer_groups: Sequence[int] = ()) -> list[int]:
        """Row-selection preference: shards of the groups we are repairing
        first (data before local parity), then remaining data, remaining
        local parities, globals last — so single-group failures resolve
        group-locally and the zero-column filter strips the rest."""
        spec: LrcScheme = self.scheme
        prefer = set()
        for g in prefer_groups:
            prefer.update(spec.group_members(g))

        def key(sid: int) -> tuple:
            in_group = 0 if sid in prefer else 1
            if sid < spec.data_shards:
                tier = 0
            elif sid < spec.data_shards + spec.local_groups:
                tier = 1
            else:
                tier = 2
            return (in_group, tier, sid)

        return sorted(present, key=key)

    def _decode_rows(self, present: Sequence[int],
                     missing: Sequence[int],
                     prefer_groups: Sequence[int] = ()
                     ) -> tuple[list[int], np.ndarray]:
        """(src_sids, mat): mat rows express each `missing` shard as a
        GF(256) combination of the chosen source shards. Raises
        ValueError when the pattern is not recoverable (present rows of
        the generator do not span the data space)."""
        spec: LrcScheme = self.scheme
        k = spec.data_shards
        order = self._source_order(present, prefer_groups)
        kept = _gf_rref_pick(self._gen[order], list(range(len(order))))
        if len(kept) < k:
            raise ValueError(
                f"unrecoverable erasure pattern: missing={sorted(missing)} "
                f"(present rows span only {len(kept)}/{k} dims)")
        src = [order[i] for i in kept]
        gsub = np.ascontiguousarray(self._gen[src])
        dec = np.asarray(gf256.gf_mat_invert(gsub))  # data = dec @ src rows
        rows = []
        for sid in missing:
            rows.append(np.asarray(
                gf256.gf_matmul(self._gen[sid][None, :], dec))[0])
        return src, np.stack(rows).astype(np.uint8)

    def plan_rebuild(self, present: Sequence[int],
                     missing: Sequence[int]
                     ) -> tuple[list[int], np.ndarray]:
        """Cheapest repair plan: (src_sids, mat) with all-zero source
        columns already dropped, so len(src_sids) IS the read cost. A
        single shard lost inside a group plans to its 5 surviving group
        members; anything wider falls back to a global decode."""
        spec: LrcScheme = self.scheme
        present = sorted(set(present) - set(missing))
        missing = sorted(missing)
        groups = sorted({g for g in (spec.group_of(s) for s in missing)
                         if g is not None})
        src, mat = self._decode_rows(present, missing, prefer_groups=groups)
        used = [j for j in range(len(src)) if mat[:, j].any()]
        if not used:  # all-zero shards still need one source row to size by
            used = [0]
        return [src[j] for j in used], np.ascontiguousarray(mat[:, used])

    def repair_strategy(self, present: Sequence[int],
                        missing: Sequence[int]) -> dict:
        """Classify the cheapest repair: 'local' when every source the
        plan reads sits inside the damaged shards' own local groups,
        'global' otherwise. Returns the plan alongside for callers."""
        spec: LrcScheme = self.scheme
        src, mat = self.plan_rebuild(present, missing)
        groups = {g for g in (spec.group_of(s) for s in missing)
                  if g is not None}
        members = set()
        for g in groups:
            members.update(spec.group_members(g))
        local = bool(groups) and set(src) <= members
        return {"strategy": "local" if local else "global",
                "sources": src, "mat": mat,
                "reads": len(src), "groups": sorted(groups)}

    def rebuild_matrix(self, present: Sequence[int],
                       missing: Sequence[int]) -> np.ndarray:
        """CpuCoder contract: coefficient rows over the FIRST k of
        sorted(present). For LRC that subset can be rank-deficient even
        when the pattern is recoverable — callers that can honor
        arbitrary sources should use plan_rebuild() instead (the volume
        server's partial rebuild does)."""
        k = self.scheme.data_shards
        present = sorted(set(present) - set(missing))
        src = present[:k]
        gsub = np.ascontiguousarray(self._gen[src])
        dec = np.asarray(gf256.gf_mat_invert(gsub))
        rows = [np.asarray(gf256.gf_matmul(
            self._gen[sid][None, :], dec))[0] for sid in missing]
        return np.stack(rows).astype(np.uint8)

    def reconstruct(self, shards: Sequence[Optional[bytes]]) -> list[bytes]:
        spec: LrcScheme = self.scheme
        total = spec.total_shards
        assert len(shards) == total
        present = [i for i in range(total) if shards[i] is not None]
        missing = [i for i in range(total) if shards[i] is None]
        if not missing:
            return [bytes(s) for s in shards]
        src, mat = self.plan_rebuild(present, missing)
        srcdata = np.stack([np.frombuffer(shards[i], dtype=np.uint8)
                            for i in src])
        rec = self._apply(mat, srcdata)
        out = [bytes(s) if s is not None else None for s in shards]
        for r, i in enumerate(missing):
            out[i] = rec[r].tobytes()
        return out

    def reconstruct_data(self, shards: Sequence[Optional[bytes]]
                         ) -> list[Optional[bytes]]:
        spec: LrcScheme = self.scheme
        k, total = spec.data_shards, spec.total_shards
        present = [i for i in range(total) if shards[i] is not None]
        missing_data = [i for i in range(k) if shards[i] is None]
        out = [bytes(s) if s is not None else None for s in shards]
        if missing_data:
            src, mat = self.plan_rebuild(present, missing_data)
            srcdata = np.stack([np.frombuffer(shards[i], dtype=np.uint8)
                                for i in src])
            rec = self._apply(mat, srcdata)
            for r, i in enumerate(missing_data):
                out[i] = rec[r].tobytes()
        return out


@register_coder("lrc-mt")
class LrcCoderMT(LrcCoder):
    """LrcCoder with workers='auto' — the per-volume default the store
    builds for LRC volumes (mirrors cpu vs cpu-mt)."""

    def __init__(self, scheme: Optional[LrcScheme] = None,
                 use_native: bool = True):
        super().__init__(scheme, use_native=use_native, workers="auto")
