"""Per-(class, tenant) resource ledger: who is burning the cluster.

RED histograms and hot-key sketches say what is slow and which keys
are hot; the ledger answers the chargeback question — which tenant's
traffic, in which QoS class, consumed the CPU, moved the bytes, and
read the disk.  The Facebook warehouse-cluster study (1309.0186)
frames incident analysis as exactly this attribution problem.

Accounting sites:

- ``HttpServer._dispatch_inner`` brackets every request with a
  ``clockctl.thread_time()`` delta (the handler runs on the dispatch
  thread, so per-thread CPU clock deltas are exact) plus wire bytes in
  (``BodyStream.consumed``) and out (response body length).  Tenant
  identity comes from the owning server's ``tenant_fn`` — client IP at
  the filer/volume tier, S3 access key at the gateway — matching the
  QoS governor's per-tenant bucket keys.
- Storage read paths call ``charge_disk()`` with the bytes a request
  pulled off disk, attributed to the ambient QoS class.

Rows are bounded: past ``max_rows`` distinct (class, tenant) pairs,
new tenants fold into a per-class ``(other)`` row — an aggregate that
still sums correctly, the same spirit as the hot-key sketch's bounded
counters.  Snapshots are plain mergeable dicts (elementwise row sums)
so they ride the telemetry piggyback — volume heartbeats, filer/S3
``/admin/telemetry`` pulls — into the master's cluster rollup.
"""

from __future__ import annotations

import threading
from typing import Optional

from seaweedfs_tpu.qos import classes as qos_classes

FIELDS = ("requests", "cpu_ms", "bytes_in", "bytes_out",
          "disk_bytes_read")
OTHER_TENANT = "(other)"


class ResourceLedger:
    def __init__(self, max_rows: int = 512):
        self.max_rows = max_rows
        # (cls, tenant) -> [requests, cpu_ms, bytes_in, bytes_out,
        #                   disk_bytes_read]
        self._rows: dict[tuple, list] = {}
        self._lock = threading.Lock()

    # ---- accounting ----
    def _row_locked(self, cls: str, tenant: str) -> list:
        key = (cls or "-", tenant or "-")
        row = self._rows.get(key)
        if row is None:
            if len(self._rows) >= self.max_rows \
                    and key[1] != OTHER_TENANT:
                return self._row_locked(cls, OTHER_TENANT)
            row = self._rows[key] = [0, 0.0, 0, 0, 0]
        return row

    def observe_request(self, cls: str, tenant: str, *,
                        cpu_s: float = 0.0, bytes_in: int = 0,
                        bytes_out: int = 0) -> None:
        """One dispatched request's bill.  cpu_s is the dispatch
        thread's thread-CPU delta across the handler."""
        with self._lock:
            row = self._row_locked(cls, tenant)
            row[0] += 1
            row[1] += cpu_s * 1000.0
            row[2] += bytes_in
            row[3] += bytes_out

    def charge_disk(self, nbytes: int, cls: Optional[str] = None,
                    tenant: str = "-") -> None:
        """Bytes a storage read pulled off disk.  Class defaults to
        the caller's ambient QoS scope (storage reads run inside the
        request's class_scope), so degraded-read reconstruction and
        scrub I/O land under background, not interactive."""
        if nbytes <= 0:
            return
        cls = cls or qos_classes.current_class() or "-"
        with self._lock:
            self._row_locked(cls, tenant)[4] += nbytes

    # ---- mergeable snapshots ----
    def snapshot(self) -> dict:
        with self._lock:
            rows = [[k[0], k[1]] + list(v)
                    for k, v in self._rows.items()]
        rows.sort(key=lambda r: -r[3])  # cpu_ms desc
        return {"fields": list(FIELDS), "rows": rows}

    def merge_from(self, snap: dict) -> None:
        """Fold another ledger's snapshot in (exact elementwise sums;
        the master's cluster rollup over node snapshots)."""
        for row in (snap or {}).get("rows", []):
            cls, tenant, values = row[0], row[1], row[2:]
            with self._lock:
                mine = self._row_locked(cls, tenant)
                for i, v in enumerate(values[:len(FIELDS)]):
                    mine[i] += v

    def rows(self) -> dict:
        """(cls, tenant) -> field dict, for tests and shell views."""
        with self._lock:
            return {k: dict(zip(FIELDS, v))
                    for k, v in self._rows.items()}

    def top(self, n: int = 20, field: str = "cpu_ms") -> list[dict]:
        idx = FIELDS.index(field)
        with self._lock:
            items = sorted(self._rows.items(),
                           key=lambda kv: -kv[1][idx])[:n]
        return [{"class": k[0], "tenant": k[1],
                 **dict(zip(FIELDS, v))} for k, v in items]
