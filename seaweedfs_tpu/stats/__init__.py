"""Cluster telemetry plane: hot-key sketches, snapshot aggregation,
and SLO burn-rate evaluation (see ARCHITECTURE.md "Observability")."""
