"""Per-node hot-key recording: Space-Saving sketches by dimension.

Volume servers track hot *needles*; filer and S3 edges track hot
*paths* and *tenants*. Every server exposes its sketches at
``/admin/hotkeys`` (on the metrics listener where the main port is
user namespace) and ships them to the master inside its telemetry
snapshot, where ClusterTelemetry merges them cluster-wide — the
measurement the roadmap's hot-needle cache and filer shard routing
depend on.
"""

from __future__ import annotations

from seaweedfs_tpu.utils.sketch import SpaceSaving

DEFAULT_CAPACITY = 64


class HotKeys:
    """A bundle of named Space-Saving sketches ("needle", "path",
    "tenant", ...). Thread-safety lives in the sketches themselves;
    the dimension map is fixed at construction."""

    def __init__(self, dims: tuple, capacity: int = DEFAULT_CAPACITY):
        self.sketches = {d: SpaceSaving(capacity) for d in dims}

    def record(self, dim: str, key: str, count: int = 1) -> None:
        sk = self.sketches.get(dim)
        if sk is not None and key:
            sk.offer(key, count)

    def top(self, k: int = 10) -> dict:
        return {d: [{"key": key, "count": c, "error": e}
                    for key, c, e in sk.top(k)]
                for d, sk in self.sketches.items()}

    def snapshot(self) -> dict:
        return {d: sk.snapshot() for d, sk in self.sketches.items()}

    def merge_from(self, snap: dict) -> None:
        """Fold another node's ``snapshot()`` in, growing dimensions
        as needed (the master's merged view spans dimensions no single
        node records)."""
        for dim, sk_snap in (snap or {}).items():
            sk = self.sketches.get(dim)
            if sk is None:
                sk = self.sketches[dim] = SpaceSaving(
                    int(sk_snap.get("capacity", DEFAULT_CAPACITY))
                    or DEFAULT_CAPACITY)
            sk.merge_from(sk_snap)

    def handler(self, url: str = ""):
        """An HttpServer handler serving this bundle at
        /admin/hotkeys?k=N."""
        from seaweedfs_tpu.utils.httpd import Request, Response

        def handle(req: Request) -> Response:
            k = int(req.query.get("k", 10))
            return Response({"url": url, "hotkeys": self.top(k),
                             "totals": {d: sk.total
                                        for d, sk in
                                        self.sketches.items()}})
        return handle
