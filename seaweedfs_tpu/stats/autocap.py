"""Ledger-driven tenant capping: close the loop from measurement to
enforcement.

PR 14's ResourceLedger names the top CPU/byte consumers per (class,
tenant); this module feeds that into QosGovernor per-tenant rate caps
on a slow loop, so a flood tenant gets clipped WITHOUT operator
action.  The loop is deliberately conservative:

- decisions use windowed DELTAS (this tick minus last tick), not
  lifetime totals — an old burst can't cap a now-quiet tenant;
- a tenant is capped only when it holds more than ``share_threshold``
  of the window's burn in its class AND the class burned at least
  ``min_cpu_ms`` (or ``min_requests``) — idle clusters never cap;
- the cap is derived from the aggressor's own observed rate
  (``clip_factor`` of it, floored at ``min_rate``), so enforcement
  bites immediately but never zeroes a tenant;
- caps LIFT automatically after ``release_ticks`` consecutive windows
  below half the threshold — a reformed tenant is forgiven without a
  human in the loop.

The aggregate rows the ledger folds small tenants into ("(other)") and
the unattributed row ("-") are never capped.
"""

from __future__ import annotations

import threading
from typing import Optional

from seaweedfs_tpu.stats.ledger import OTHER_TENANT, ResourceLedger
from seaweedfs_tpu.utils import clockctl, glog

_UNCAPPABLE = (OTHER_TENANT, "-", "")


class LedgerAutoCapper:
    def __init__(self, ledger: ResourceLedger, governor,
                 interval_s: float = 15.0,
                 share_threshold: float = 0.5,
                 min_cpu_ms: float = 200.0,
                 min_requests: int = 200,
                 clip_factor: float = 0.1,
                 min_rate: float = 1.0,
                 release_ticks: int = 2):
        self.ledger = ledger
        self.governor = governor
        self.interval_s = interval_s
        self.share_threshold = share_threshold
        self.min_cpu_ms = min_cpu_ms
        self.min_requests = min_requests
        self.clip_factor = clip_factor
        self.min_rate = min_rate
        self.release_ticks = release_ticks
        self._lock = threading.Lock()
        self._last_rows: dict = {}
        self._last_tick = 0.0
        # (cls, tenant) -> consecutive quiet windows while capped
        self._capped: dict[tuple, int] = {}
        self.caps_installed = 0
        self.caps_released = 0

    def maybe_tick(self) -> None:
        """Tick if interval_s elapsed — piggybacks on an existing slow
        loop (the filer's announce loop) instead of owning a thread."""
        now = clockctl.monotonic()
        with self._lock:
            if now - self._last_tick < self.interval_s:
                return
            self._last_tick = now
        self.tick()

    def tick(self) -> dict:
        """One capping decision over the window since the last tick.
        Returns {installed: [...], released: [...]} for tests/tools."""
        rows = self.ledger.rows()
        with self._lock:
            last = self._last_rows
            self._last_rows = rows
        window = max(self.interval_s, 1e-6)
        # per-class window totals + per-row deltas
        deltas: dict[tuple, dict] = {}
        cls_cpu: dict[str, float] = {}
        cls_req: dict[str, float] = {}
        for key, f in rows.items():
            prev = last.get(key, {})
            d = {"cpu_ms": f["cpu_ms"] - prev.get("cpu_ms", 0.0),
                 "requests": f["requests"] - prev.get("requests", 0)}
            deltas[key] = d
            cls_cpu[key[0]] = cls_cpu.get(key[0], 0.0) + max(0.0, d["cpu_ms"])
            cls_req[key[0]] = cls_req.get(key[0], 0.0) + max(0, d["requests"])
        installed, released = [], []
        for (cls, tenant), d in deltas.items():
            if tenant in _UNCAPPABLE:
                continue
            total_cpu = cls_cpu.get(cls, 0.0)
            total_req = cls_req.get(cls, 0.0)
            # two aggressor signatures: CPU hog, or pure request flood
            # (cheap requests barely register CPU but still saturate)
            hot = ((total_cpu >= self.min_cpu_ms
                    and d["cpu_ms"] > self.share_threshold * total_cpu)
                   or (total_req >= self.min_requests
                       and d["requests"] > self.share_threshold * total_req))
            key = (cls, tenant)
            if hot:
                rate = max(self.min_rate,
                           self.clip_factor * d["requests"] / window)
                self.governor.set_tenant_cap(cls, tenant, rate)
                if key not in self._capped:
                    self.caps_installed += 1
                    glog.warning(
                        "autocap: tenant %s capped at %.1f req/s in "
                        "class %s (%.0f%% of window cpu)", tenant, rate,
                        cls, 100.0 * d["cpu_ms"] / max(total_cpu, 1e-9))
                    installed.append({"class": cls, "tenant": tenant,
                                      "rate": rate})
                self._capped[key] = 0
                continue
            if key in self._capped:
                quiet = (total_cpu < self.min_cpu_ms
                         or d["cpu_ms"] < 0.5 * self.share_threshold
                         * total_cpu)
                if quiet:
                    self._capped[key] += 1
                    if self._capped[key] >= self.release_ticks:
                        del self._capped[key]
                        self.governor.clear_tenant_cap(cls, tenant)
                        self.caps_released += 1
                        glog.info("autocap: cap on %s/%s released",
                                  cls, tenant)
                        released.append({"class": cls, "tenant": tenant})
                else:
                    self._capped[key] = 0
        return {"installed": installed, "released": released}

    def snapshot(self) -> dict:
        with self._lock:
            capped = [{"class": c, "tenant": t, "quiet_ticks": q}
                      for (c, t), q in sorted(self._capped.items(),
                                              key=lambda kv: str(kv[0]))]
        return {"interval_s": self.interval_s, "capped": capped,
                "caps_installed": self.caps_installed,
                "caps_released": self.caps_released}
