"""SLO objectives + multi-window burn-rate alerting (the Google SRE
workbook shape): per traffic class, an objective like "99% of
requests are good", where good = completed without a 5xx AND under
the class's latency target. Burn rate over a window = observed bad
fraction / error budget; 1.0 means exactly spending the budget.

Two windows: a fast one (~5m production) that pages quickly on a
cliff, and a slow one (~1h) that catches slow leaks. Both elapse on
``clockctl`` time, so the deterministic sim compresses them to
virtual seconds and the alert timeline becomes part of the
bit-reproducible kernel log (same seed => same transitions).

The evaluator is pure bookkeeping over cumulative (total, bad)
samples — callers decide where those come from (the master feeds it
merged RED histogram rollups; the sim feeds it SimMetrics totals).
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Optional

# defaults; per-class overrides ride the objectives dict
DEFAULT_OBJECTIVES = {
    "interactive": {"latency_s": 0.5, "goal": 0.99},
    "write": {"latency_s": 1.0, "goal": 0.99},
    "background": {"latency_s": 10.0, "goal": 0.95},
    "none": {"latency_s": 1.0, "goal": 0.99},
}
FAST_WINDOW_S = 300.0
SLOW_WINDOW_S = 3600.0
# burn thresholds: fast window pages, slow window tickets
FAST_BURN_THRESHOLD = 10.0
SLOW_BURN_THRESHOLD = 2.0

OK = "ok"
FAST_BURN = "fast_burn"
SLOW_BURN = "slow_burn"


class SloEvaluator:
    def __init__(self, objectives: Optional[dict] = None,
                 fast_window_s: float = FAST_WINDOW_S,
                 slow_window_s: float = SLOW_WINDOW_S,
                 fast_burn_threshold: float = FAST_BURN_THRESHOLD,
                 slow_burn_threshold: float = SLOW_BURN_THRESHOLD,
                 on_transition: Optional[Callable] = None):
        self.objectives = dict(DEFAULT_OBJECTIVES)
        if objectives:
            self.objectives.update(objectives)
        self.fast_window_s = fast_window_s
        self.slow_window_s = slow_window_s
        self.fast_burn_threshold = fast_burn_threshold
        self.slow_burn_threshold = slow_burn_threshold
        # on_transition(t, cls, old_state, new_state, detail) — the sim
        # routes this into kernel.note so transitions enter log_hash;
        # the master routes it into glog
        self.on_transition = on_transition
        # cls -> deque[(t, cumulative_total, cumulative_bad)]
        self._hist: dict[str, deque] = {}
        self._state: dict[str, str] = {}
        # [(t, cls, old, new)] — the full alert timeline
        self.transitions: list = []

    def feed(self, now: float, cls: str, total: float,
             bad: float) -> None:
        """Record a cumulative (total, bad) sample for one class.
        Counter resets (a node restart shrinking the merged totals)
        are tolerated by clamping window deltas at zero."""
        dq = self._hist.setdefault(cls, deque())
        dq.append((now, total, bad))
        horizon = now - self.slow_window_s - 1.0
        while len(dq) > 2 and dq[1][0] <= horizon:
            dq.popleft()

    def _burn(self, cls: str, now: float, window: float) -> float:
        dq = self._hist.get(cls)
        if not dq:
            return 0.0
        t1, total1, bad1 = dq[-1]
        # the newest sample at or before the window start; fall back
        # to the oldest (partial coverage while the window fills)
        t0, total0, bad0 = dq[0]
        boundary = now - window
        for t, total, bad in dq:
            if t > boundary:
                break
            t0, total0, bad0 = t, total, bad
        d_total = max(total1 - total0, 0.0)
        d_bad = max(bad1 - bad0, 0.0)
        if d_total <= 0:
            return 0.0
        goal = self.objectives.get(
            cls, DEFAULT_OBJECTIVES["none"])["goal"]
        budget = max(1.0 - goal, 1e-9)
        return (d_bad / d_total) / budget

    def evaluate(self, now: float) -> dict:
        """Per-class burn rates + alert state; records (and reports)
        state transitions. Deterministic given the feed history."""
        out = {}
        for cls in sorted(self._hist):
            fast = self._burn(cls, now, self.fast_window_s)
            slow = self._burn(cls, now, self.slow_window_s)
            if fast >= self.fast_burn_threshold:
                state = FAST_BURN
            elif slow >= self.slow_burn_threshold:
                state = SLOW_BURN
            else:
                state = OK
            old = self._state.get(cls, OK)
            if state != old:
                self._state[cls] = state
                self.transitions.append((now, cls, old, state))
                if self.on_transition is not None:
                    self.on_transition(
                        now, cls, old, state,
                        f"fast={fast:.2f} slow={slow:.2f}")
            out[cls] = {"fast_burn": round(fast, 4),
                        "slow_burn": round(slow, 4),
                        "state": state,
                        "objective": self.objectives.get(
                            cls, DEFAULT_OBJECTIVES["none"])}
        return out

    def state(self, cls: str) -> str:
        return self._state.get(cls, OK)

    def firing(self) -> list:
        """Classes whose alert is currently not ok."""
        return sorted(c for c, s in self._state.items() if s != OK)

    def timeline(self) -> list:
        """[(t, cls, old, new)] — compare across runs for
        bit-reproducibility."""
        return list(self.transitions)
