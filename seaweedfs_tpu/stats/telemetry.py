"""Master-side telemetry aggregation: merge per-node RED histogram
and hot-key snapshots into cluster-wide per-class quantiles, top-k
keys, and exemplar trace ids, and judge them against SLO objectives.

Transport: volume servers piggyback their snapshot on heartbeats
(next to qos_pressure); filer/S3 snapshots are pulled through the
/cluster/register membership table. Histogram merging is exact
(bucket counts add); quantiles are computed once, after the merge —
never averaged across nodes.
"""

from __future__ import annotations

from typing import Optional

from seaweedfs_tpu.stats.hotkeys import HotKeys
from seaweedfs_tpu.stats.ledger import ResourceLedger
from seaweedfs_tpu.stats.slo import SloEvaluator
from seaweedfs_tpu.utils.metrics import RED_BUCKETS, Histogram

# label order of the RED histogram: see metrics.RedRecorder
_L_SERVER, _L_ROUTE, _L_CLASS, _L_STATUS = range(4)

# hint-journal staleness thresholds (SloEvaluator-adjacent: a simple
# level alert, not a burn rate — journal debt is a stock, not a flow).
# Either condition on ANY node fires `hints_stale` in alerts_firing.
HINTS_PENDING_MAX = 1024
HINTS_AGE_MAX_S = 60.0


def red_class_rollup(snapshot: dict, latency_targets: dict) -> dict:
    """Collapse a (merged) RED snapshot to per-class totals:
    {cls: {total, errors, slow, bad, sum}}. bad = 5xx + over-target
    among non-5xx — the SLO evaluator's numerator."""
    buckets = list(snapshot.get("buckets", RED_BUCKETS))
    out: dict[str, dict] = {}
    for labels, counts, total_sum, _ex in snapshot.get("series", ()):
        cls = labels[_L_CLASS]
        st = out.setdefault(cls, {"total": 0, "errors": 0, "slow": 0,
                                  "bad": 0, "sum": 0.0})
        n = sum(counts)
        st["total"] += n
        st["sum"] += total_sum
        if labels[_L_STATUS] == "5xx":
            st["errors"] += n
            st["bad"] += n
            continue
        target = latency_targets.get(cls)
        if target is None:
            continue
        fast = sum(c for b, c in zip(buckets, counts) if b <= target)
        slow = n - fast
        st["slow"] += slow
        st["bad"] += slow
    return out


class ClusterTelemetry:
    """Stateless merge + stateful judgement. ``rollup()`` rebuilds
    the merged view from scratch each call (node sets change); the
    SLO evaluator underneath accumulates the cumulative samples the
    burn-rate windows diff."""

    def __init__(self, objectives: Optional[dict] = None,
                 fast_window_s: Optional[float] = None,
                 slow_window_s: Optional[float] = None,
                 on_transition=None):
        kwargs = {}
        if fast_window_s is not None:
            kwargs["fast_window_s"] = fast_window_s
        if slow_window_s is not None:
            kwargs["slow_window_s"] = slow_window_s
        self.slo = SloEvaluator(objectives=objectives,
                                on_transition=on_transition, **kwargs)

    @staticmethod
    def merge(node_snaps: list) -> tuple:
        """Merge node telemetry snapshots ({"node", "server", "red",
        "hotkeys", "ledger"?, "hints"?}) into (red Histogram, HotKeys,
        ResourceLedger, per-node hint-journal rows, contributing node
        urls)."""
        red = Histogram(
            "cluster_red", "merged RED",
            label_names=("server", "route_family", "class",
                         "status_family"),
            buckets=RED_BUCKETS)
        hot = HotKeys(dims=())
        ledger = ResourceLedger()
        hints = []
        nodes = []
        for snap in node_snaps:
            if not snap:
                continue
            if snap.get("red"):
                red.merge_from(snap["red"])
            if snap.get("hotkeys"):
                hot.merge_from(snap["hotkeys"])
            if snap.get("ledger"):
                ledger.merge_from(snap["ledger"])
            if snap.get("hints"):
                hints.append({"node": snap.get("node", ""),
                              **snap["hints"]})
            if snap.get("node"):
                nodes.append(snap["node"])
        return red, hot, ledger, hints, nodes

    def rollup(self, now: float, node_snaps: list,
               top_k: int = 10) -> dict:
        """The /cluster/telemetry body: merged per-class quantiles +
        error rates, cluster top-k hot keys, bucket exemplars, and
        the SLO judgement (feeding the burn-rate windows as a side
        effect)."""
        red, hot, ledger, hints, nodes = self.merge(node_snaps)
        targets = {c: o["latency_s"]
                   for c, o in self.slo.objectives.items()}
        merged_snap = red.snapshot()
        per_class_totals = red_class_rollup(merged_snap, targets)
        per_class = {}
        for cls, st in sorted(per_class_totals.items()):
            self.slo.feed(now, cls, st["total"], st["bad"])
            exemplars = _class_exemplars(merged_snap, cls)
            per_class[cls] = {
                "count": st["total"],
                "errors": st["errors"],
                "error_rate": round(st["errors"] / st["total"], 6)
                if st["total"] else 0.0,
                "slow": st["slow"],
                "p50": red.quantile(
                    0.5, label_filter=lambda l: l[_L_CLASS] == cls),
                "p99": red.quantile(
                    0.99, label_filter=lambda l: l[_L_CLASS] == cls),
                "exemplars": exemplars,
            }
        slo_view = self.slo.evaluate(now)
        for cls, judged in slo_view.items():
            if cls in per_class:
                per_class[cls]["slo"] = judged
        alerts = list(self.slo.firing())
        stale = [h for h in hints
                 if h.get("pending_rows", 0) > HINTS_PENDING_MAX
                 or h.get("oldest_debt_age_s", 0.0) > HINTS_AGE_MAX_S]
        if stale:
            alerts.append("hints_stale")
        return {
            "per_class": per_class,
            "top_keys": hot.top(top_k),
            "key_totals": {d: sk.total
                           for d, sk in hot.sketches.items()},
            # per-(class, tenant) chargeback: cluster-merged CPU-ms,
            # wire bytes, disk reads — hottest tenants first
            "ledger": {"fields": ["class", "tenant", "requests",
                                  "cpu_ms", "bytes_in", "bytes_out",
                                  "disk_bytes_read"],
                       "rows": ledger.snapshot()["rows"][:max(top_k, 20)]},
            "hints": hints,
            "nodes": sorted(nodes),
            "slo": slo_view,
            "alerts_firing": alerts,
        }


def _class_exemplars(snapshot: dict, cls: str) -> list:
    """[{le, trace_id}] for one class across the merged series (last
    series wins per bucket — exemplars are samples, any one will do)."""
    buckets = [str(b) for b in snapshot.get("buckets", ())] + ["+Inf"]
    by_bucket: dict[str, str] = {}
    for labels, _counts, _sum, exemplars in snapshot.get("series", ()):
        if labels[_L_CLASS] != cls or not exemplars:
            continue
        for i, e in enumerate(exemplars):
            if e:
                by_bucket[buckets[i]] = e
    return [{"le": le, "trace_id": tid}
            for le, tid in sorted(by_bucket.items())]
