"""Minimal TOML reader for Python < 3.11 (no stdlib tomllib, and the
container bakes no third-party toml package).

Covers the subset this project's configs use — tables, arrays of
tables, dotted headers, basic/literal strings, ints/floats/bools,
(nested) arrays, inline tables, comments. Raises ValueError on
anything outside that subset rather than guessing.
"""

from __future__ import annotations

from typing import Any


class TomlError(ValueError):
    pass


def load(fp) -> dict:
    data = fp.read()
    if isinstance(data, bytes):
        data = data.decode("utf-8")
    return loads(data)


def loads(text: str) -> dict:
    root: dict[str, Any] = {}
    current = root
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = _strip_comment(raw).strip()
        if not line:
            continue
        try:
            if line.startswith("[["):
                if not line.endswith("]]"):
                    raise TomlError("unterminated table-array header")
                current = _enter(root, line[2:-2].strip(), array=True)
            elif line.startswith("["):
                if not line.endswith("]"):
                    raise TomlError("unterminated table header")
                current = _enter(root, line[1:-1].strip(), array=False)
            else:
                key, eq, rest = line.partition("=")
                if not eq:
                    raise TomlError("expected 'key = value'")
                value, tail = _parse_value(rest.strip())
                if tail.strip():
                    raise TomlError(f"trailing garbage {tail.strip()!r}")
                _assign(current, key.strip(), value)
        except TomlError as e:
            raise TomlError(f"TOML parse error on line {lineno}: {e}") from None
    return root


def _strip_comment(line: str) -> str:
    out = []
    in_str: str | None = None
    i = 0
    while i < len(line):
        ch = line[i]
        if in_str:
            out.append(ch)
            if ch == "\\" and in_str == '"' and i + 1 < len(line):
                out.append(line[i + 1])
                i += 2
                continue
            if ch == in_str:
                in_str = None
        elif ch in ("'", '"'):
            in_str = ch
            out.append(ch)
        elif ch == "#":
            break
        else:
            out.append(ch)
        i += 1
    return "".join(out)


def _split_dotted(key: str) -> list[str]:
    parts: list[str] = []
    buf = []
    in_str: str | None = None
    for ch in key:
        if in_str:
            if ch == in_str:
                in_str = None
            else:
                buf.append(ch)
        elif ch in ("'", '"'):
            in_str = ch
        elif ch == ".":
            parts.append("".join(buf).strip())
            buf = []
        else:
            buf.append(ch)
    parts.append("".join(buf).strip())
    if in_str or any(p == "" for p in parts):
        raise TomlError(f"bad key {key!r}")
    return parts


def _enter(root: dict, dotted: str, array: bool) -> dict:
    parts = _split_dotted(dotted)
    cur = root
    for p in parts[:-1]:
        nxt = cur.setdefault(p, {})
        if isinstance(nxt, list):
            nxt = nxt[-1]
        if not isinstance(nxt, dict):
            raise TomlError(f"key {p!r} is not a table")
        cur = nxt
    leaf = parts[-1]
    if array:
        arr = cur.setdefault(leaf, [])
        if not isinstance(arr, list):
            raise TomlError(f"key {leaf!r} is not a table array")
        arr.append({})
        return arr[-1]
    tbl = cur.setdefault(leaf, {})
    if isinstance(tbl, list):
        tbl = tbl[-1]
    if not isinstance(tbl, dict):
        raise TomlError(f"key {leaf!r} is not a table")
    return tbl


def _assign(table: dict, key: str, value: Any) -> None:
    parts = _split_dotted(key)
    for p in parts[:-1]:
        table = table.setdefault(p, {})
        if not isinstance(table, dict):
            raise TomlError(f"key {p!r} is not a table")
    table[parts[-1]] = value


def _parse_value(s: str) -> tuple[Any, str]:
    """Parse one value at the head of `s`; returns (value, rest)."""
    if not s:
        raise TomlError("missing value")
    ch = s[0]
    if ch == '"':
        return _parse_basic_string(s)
    if ch == "'":
        end = s.find("'", 1)
        if end < 0:
            raise TomlError("unterminated literal string")
        return s[1:end], s[end + 1:]
    if ch == "[":
        return _parse_array(s)
    if ch == "{":
        return _parse_inline_table(s)
    # bare token: up to a delimiter
    end = len(s)
    for i, c in enumerate(s):
        if c in ",]}":
            end = i
            break
    tok, rest = s[:end].strip(), s[end:]
    if tok == "true":
        return True, rest
    if tok == "false":
        return False, rest
    tok_num = tok.replace("_", "")
    try:
        if tok_num.lower().lstrip("+-").startswith(("0x", "0o", "0b")):
            return int(tok_num, 0), rest
        return int(tok_num), rest
    except ValueError:
        try:
            return float(tok_num), rest
        except ValueError:
            raise TomlError(f"unsupported value {tok!r}") from None


_ESCAPES = {"n": "\n", "t": "\t", "r": "\r", '"': '"', "\\": "\\",
            "b": "\b", "f": "\f"}


def _parse_basic_string(s: str) -> tuple[str, str]:
    out = []
    i = 1
    while i < len(s):
        ch = s[i]
        if ch == "\\":
            if i + 1 >= len(s):
                raise TomlError("dangling escape")
            nxt = s[i + 1]
            if nxt == "u" and i + 5 < len(s):
                out.append(chr(int(s[i + 2:i + 6], 16)))
                i += 6
                continue
            if nxt not in _ESCAPES:
                raise TomlError(f"unknown escape \\{nxt}")
            out.append(_ESCAPES[nxt])
            i += 2
            continue
        if ch == '"':
            return "".join(out), s[i + 1:]
        out.append(ch)
        i += 1
    raise TomlError("unterminated string")


def _parse_array(s: str) -> tuple[list, str]:
    vals: list[Any] = []
    rest = s[1:].strip()
    while True:
        if not rest:
            raise TomlError("unterminated array (multiline arrays must "
                            "close on the same line in this reader)")
        if rest[0] == "]":
            return vals, rest[1:]
        v, rest = _parse_value(rest)
        vals.append(v)
        rest = rest.strip()
        if rest.startswith(","):
            rest = rest[1:].strip()


def _parse_inline_table(s: str) -> tuple[dict, str]:
    tbl: dict[str, Any] = {}
    rest = s[1:].strip()
    while True:
        if not rest:
            raise TomlError("unterminated inline table")
        if rest[0] == "}":
            return tbl, rest[1:]
        key, eq, rest = rest.partition("=")
        if not eq:
            raise TomlError("expected 'key = value' in inline table")
        v, rest = _parse_value(rest.strip())
        _assign(tbl, key.strip(), v)
        rest = rest.strip()
        if rest.startswith(","):
            rest = rest[1:].strip()
