"""Always-on wall-stack sampling profiler + thread->scope registry.

Third leg of the observability stool: RED histograms say *what* is
slow, traces say *where in the request path*, this says *which code*.
A WallSampler is one dedicated daemon thread that walks
``sys._current_frames()`` at a low prime rate (default 19Hz — prime so
the sampler can't phase-lock with periodic work) and folds every
thread's stack into a bounded ``stack -> sample count`` table in the
standard folded format (``frame;frame;frame count``), directly
consumable by flamegraph tooling.

Attribution by construction: sampled stacks alone can't tell an
interactive read from a background scrub once both sit in the same
socket write.  So dispatch sites register the calling thread's ambient
scope — QoS class, route family, sampled trace id — in a process-wide
thread->scope registry (``tag()``/``untag()``; ``HttpServer._dispatch``
and the batcher/scrubber/repair workers re-enter it per unit of work),
and the sampler prefixes each folded stack with synthetic
``class:``/``route:`` root frames.  Untagged threads fold under their
``thread:<name>`` instead (weedlint's unnamed-thread rule exists so
that name means something).

Disabled path is ``_PASS``-grade, like NOOP spans: with no sampler
running, ``tag()`` is one module-global truthiness check and an
immediate return — no dict write, no allocation — and a sampler
constructed with ``hz=0`` never starts a thread.

The registry is a plain dict keyed by thread ident: each thread writes
only its own key and the sampler thread only reads, so the GIL's
per-op atomicity is the only synchronization needed (same reasoning as
``sys._current_frames()`` itself, which snapshots under the GIL).
"""

from __future__ import annotations

import sys
import threading
from contextlib import contextmanager
from typing import Iterable, Optional

DEFAULT_HZ = 19.0
# distinct folded stacks kept per sampler; the long tail lands in one
# overflow bucket so a pathological workload can't grow the table
DEFAULT_MAX_STACKS = 2048
MAX_DEPTH = 64
OVERFLOW_KEY = "(stack-table-overflow)"

# ---- thread -> ambient-scope registry (process-wide) -----------------

# ident -> (cls, route, trace_id); written by the owning thread only
_scopes: dict[int, tuple] = {}
# count of running samplers: the zero-cost gate for tag()
_active = 0


def tag(cls: Optional[str], route: Optional[str] = None,
        trace_id: Optional[str] = None):
    """Register the calling thread's ambient scope for the sampler.
    Returns a token for ``untag()``.  With no sampler running this is
    one global check and return — the zero-cost disabled path."""
    if not _active:
        return None
    ident = threading.get_ident()
    prev = _scopes.get(ident)
    _scopes[ident] = (cls, route, trace_id)
    return (ident, prev)


def untag(token) -> None:
    if token is None:
        return
    ident, prev = token
    if prev is None:
        _scopes.pop(ident, None)
    else:
        _scopes[ident] = prev


@contextmanager
def scope(cls: Optional[str] = None, route: Optional[str] = None,
          trace_id: Optional[str] = None):
    """Tag the calling thread for the duration of a with-block — the
    re-entry helper for worker loops (batcher dispatch, scrub passes,
    repair waves) that aren't HTTP requests."""
    token = tag(cls, route, trace_id)
    try:
        yield
    finally:
        untag(token)


# ---- folding ---------------------------------------------------------

def _frame_label(code) -> str:
    base = code.co_filename.rsplit("/", 1)[-1]
    if base.endswith(".py"):
        base = base[:-3]
    name = getattr(code, "co_qualname", code.co_name)
    # the folded format reserves ';' (frame separator) and ' ' (count
    # separator); qualnames like '<listcomp>' are fine
    return f"{base}.{name}".replace(";", ",").replace(" ", "_")


def _fold_stack(frame, prefix: list) -> str:
    parts = []
    depth = 0
    while frame is not None and depth < MAX_DEPTH:
        parts.append(_frame_label(frame.f_code))
        frame = frame.f_back
        depth += 1
    parts.reverse()  # folded format is root-first
    return ";".join(prefix + parts)


class WallSampler:
    """One sampling thread, one bounded folded-stack table.

    ``hz=0`` is the disabled sampler: ``start()`` is a no-op and
    ``window()`` returns an empty table — servers construct it
    unconditionally and the config decides whether it costs anything.
    """

    def __init__(self, hz: float = DEFAULT_HZ,
                 max_stacks: int = DEFAULT_MAX_STACKS):
        self.hz = float(hz)
        self.max_stacks = max_stacks
        self._counts: dict[str, int] = {}
        # folded stack -> last sampled trace id seen there (bounded by
        # the counts table: only admitted stacks get an exemplar)
        self._exemplars: dict[str, str] = {}
        self._total = 0
        self._ticks = 0
        self._errors = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- lifecycle ----
    def start(self) -> None:
        global _active
        if self.hz <= 0 or self._thread is not None:
            return
        _active += 1
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="wall-sampler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        global _active
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=2.0)
        self._thread = None
        _active = max(0, _active - 1)

    @property
    def running(self) -> bool:
        return self._thread is not None

    # ---- sampling loop (dedicated thread) ----
    def _loop(self) -> None:
        interval = 1.0 / self.hz
        while not self._stop.wait(interval):
            try:
                self._sample_once()
            except Exception:  # noqa: BLE001 — a torn frame walk
                self._errors += 1  # must never kill the sampler

    def _sample_once(self) -> None:
        me = threading.get_ident()
        names = {t.ident: t.name for t in threading.enumerate()}
        frames = sys._current_frames()
        folded: list[tuple[str, Optional[str]]] = []
        for ident, frame in frames.items():
            if ident == me:
                continue
            sc = _scopes.get(ident)
            if sc is not None:
                cls, route, tid = sc
                prefix = []
                if cls:
                    prefix.append(f"class:{cls}")
                if route:
                    prefix.append(f"route:{route}")
                if not prefix:
                    prefix = [f"thread:{names.get(ident, ident)}"]
            else:
                tid = None
                prefix = [f"thread:{names.get(ident, ident)}"]
            folded.append((_fold_stack(frame, prefix), tid))
        del frames  # drop frame refs before taking the lock
        with self._lock:
            for key, tid in folded:
                if key in self._counts \
                        or len(self._counts) < self.max_stacks:
                    self._counts[key] = self._counts.get(key, 0) + 1
                    if tid:
                        self._exemplars[key] = tid
                else:
                    self._counts[OVERFLOW_KEY] = \
                        self._counts.get(OVERFLOW_KEY, 0) + 1
            self._total += len(folded)
            self._ticks += 1

    # ---- export ----
    def snapshot(self) -> dict:
        """Cumulative folded table since start (mergeable: counts sum)."""
        with self._lock:
            return {"rate_hz": self.hz, "samples": self._total,
                    "ticks": self._ticks, "errors": self._errors,
                    "folded": dict(self._counts),
                    "exemplars": dict(self._exemplars)}

    def window(self, seconds: float) -> dict:
        """Folded-stack delta over the NEXT `seconds` (blocks the
        caller, not the sampler).  seconds<=0 returns the cumulative
        table — the no-wait form collectors use for quick sweeps."""
        if seconds <= 0 or not self.running:
            return self.snapshot()
        before = self.snapshot()
        self._stop.wait(seconds)  # stop() aborts the window early
        after = self.snapshot()
        base = before["folded"]
        folded = {}
        for key, count in after["folded"].items():
            d = count - base.get(key, 0)
            if d > 0:
                folded[key] = d
        return {"rate_hz": self.hz,
                "samples": after["samples"] - before["samples"],
                "ticks": after["ticks"] - before["ticks"],
                "errors": after["errors"], "seconds": seconds,
                "folded": folded,
                "exemplars": {k: v for k, v in
                              after["exemplars"].items() if k in folded}}


# ---- folded-table algebra (shared by /admin/profile consumers) -------

def merge_folded(tables: Iterable[dict]) -> dict:
    """Sum stack->count tables — node windows into a cluster profile."""
    out: dict[str, int] = {}
    for table in tables:
        for key, count in table.items():
            out[key] = out.get(key, 0) + count
    return out


def to_folded_text(table: dict) -> str:
    return "\n".join(f"{k} {v}"
                     for k, v in sorted(table.items())) + "\n" \
        if table else ""


def parse_folded(text: str) -> dict:
    """Inverse of to_folded_text; tolerates blank and comment lines."""
    out: dict[str, int] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        stack, _, count = line.rpartition(" ")
        try:
            out[stack] = out.get(stack, 0) + int(count)
        except ValueError:
            continue
    return out


def frame_shares(table: dict) -> dict:
    """Per-frame INCLUSIVE share of total samples: the fraction of
    samples whose stack contains the frame anywhere.  The unit of
    profile diffing — stable across refactors that merely re-shuffle
    callers, unlike per-stack counts."""
    total = sum(table.values())
    if not total:
        return {}
    by_frame: dict[str, int] = {}
    for stack, count in table.items():
        for frame in set(stack.split(";")):
            by_frame[frame] = by_frame.get(frame, 0) + count
    return {f: c / total for f, c in by_frame.items()}


def diff_folded(baseline: dict, current: dict, top_n: int = 10,
                min_share: float = 0.005) -> list[dict]:
    """Top-N frame-share regressions of `current` vs `baseline`:
    frames whose inclusive share grew, largest growth first.  Frames
    below `min_share` in both profiles are noise and skipped."""
    base = frame_shares(baseline)
    cur = frame_shares(current)
    rows = []
    for frame, share in cur.items():
        b = base.get(frame, 0.0)
        if share < min_share and b < min_share:
            continue
        if share > b:
            rows.append({"frame": frame, "base_share": round(b, 4),
                         "cur_share": round(share, 4),
                         "delta": round(share - b, 4)})
    rows.sort(key=lambda r: -r["delta"])
    return rows[:top_n]


def make_profile_handler(sampler: WallSampler, node_of,
                         server_kind: str):
    """Build the GET /admin/profile route body shared by all four
    server types: ?seconds=N (clamped to [0, 60]) blocks for one
    window; ?format=folded returns the raw text a flamegraph script
    eats, default JSON wraps it with node identity for prof_collect.
    `node_of` is a callable — servers learn their port at start()."""
    from seaweedfs_tpu.utils.httpd import Response

    def handle(req) -> "Response":
        try:
            seconds = float(req.query.get("seconds", "0") or 0)
        except ValueError:
            return Response({"error": "bad seconds"}, status=400)
        seconds = max(0.0, min(seconds, 60.0))
        win = sampler.window(seconds)
        if req.query.get("format") == "folded":
            return Response(to_folded_text(win["folded"]),
                            content_type="text/plain")
        win["node"] = node_of()
        win["server"] = server_kind
        return Response(win)

    return handle
