"""Image resizing + EXIF orientation fix on the volume read path
(reference weed/images/resizing.go + orientation.go, applied in
volume_server_handlers_read.go when width/height/mode query params are
present). Uses PIL; no-ops gracefully if PIL is unavailable."""

from __future__ import annotations

import io
from typing import Optional

try:
    from PIL import Image, ImageOps
    _HAVE_PIL = True
except ImportError:  # pragma: no cover
    _HAVE_PIL = False


def is_image(mime: str, name: str = "") -> bool:
    if mime.startswith("image/"):
        return True
    lower = name.lower()
    return lower.endswith((".jpg", ".jpeg", ".png", ".gif", ".webp"))


def fix_jpg_orientation(data: bytes) -> bytes:
    """Rotate per EXIF orientation tag (reference orientation.go)."""
    if not _HAVE_PIL:
        return data
    try:
        img = Image.open(io.BytesIO(data))
        fixed = ImageOps.exif_transpose(img)
        if fixed is img:
            return data
        out = io.BytesIO()
        fixed.save(out, format=img.format or "JPEG")
        return out.getvalue()
    except Exception:
        return data


def resized(data: bytes, width: Optional[int], height: Optional[int],
            mode: str = "") -> bytes:
    """Resize keeping aspect ratio ('' default), 'fit' letterbox, or
    'fill' center-crop (reference resizing.go Resized)."""
    if not _HAVE_PIL or (not width and not height):
        return data
    try:
        img = Image.open(io.BytesIO(data))
        fmt = img.format or "PNG"
        w, h = img.size
        width = width or w
        height = height or h
        if mode == "fill":
            resized_img = ImageOps.fit(img, (width, height))
        elif mode == "fit":
            img.thumbnail((width, height))
            resized_img = ImageOps.pad(img, (width, height))
        else:
            img.thumbnail((width, height))
            resized_img = img
        out = io.BytesIO()
        resized_img.save(out, format=fmt)
        return out.getvalue()
    except Exception:
        return data
