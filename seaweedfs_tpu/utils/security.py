"""Security: JWT (HS256) write authorization + IP guard.

Functional equivalent of reference weed/security/jwt.go + guard.go: the
master mints a short-lived token scoped to a fid when a signing key is
configured; volume servers require it on writes/deletes. Stdlib-only
HS256 implementation.
"""

from __future__ import annotations

import base64
import hmac
import hashlib
import ipaddress
import json
import time
from typing import Optional


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def gen_jwt(signing_key: str, fid: str, expires_seconds: int = 10) -> str:
    """Mint a token for one file id (reference GenJwtForVolumeServer)."""
    header = _b64(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    payload = _b64(json.dumps({
        "exp": int(time.time()) + expires_seconds,
        "fid": fid,
    }).encode())
    msg = f"{header}.{payload}".encode()
    sig = _b64(hmac.new(signing_key.encode(), msg, hashlib.sha256).digest())
    return f"{header}.{payload}.{sig}"


def verify_jwt(signing_key: str, token: str,
               fid: Optional[str] = None) -> bool:
    try:
        header, payload, sig = token.split(".")
    except ValueError:
        return False
    msg = f"{header}.{payload}".encode()
    want = _b64(hmac.new(signing_key.encode(), msg, hashlib.sha256).digest())
    if not hmac.compare_digest(want, sig):
        return False
    try:
        claims = json.loads(_unb64(payload))
    except (ValueError, json.JSONDecodeError):
        return False
    if claims.get("exp", 0) < time.time():
        return False
    if fid is not None and claims.get("fid") not in (fid, fid.split("_")[0]):
        return False
    return True


class Guard:
    """IP whitelist (reference security/guard.go:17-50). Empty list allows
    everyone."""

    def __init__(self, whitelist: Optional[list[str]] = None):
        self.networks = []
        for item in whitelist or []:
            if "/" in item:
                self.networks.append(ipaddress.ip_network(item, strict=False))
            else:
                self.networks.append(
                    ipaddress.ip_network(item + "/32", strict=False))

    def allowed(self, ip: str) -> bool:
        if not self.networks:
            return True
        try:
            addr = ipaddress.ip_address(ip)
        except ValueError:
            return False
        return any(addr in net for net in self.networks)
