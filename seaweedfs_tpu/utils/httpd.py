"""Minimal threaded HTTP/JSON server + client helpers.

The control plane speaks HTTP/JSON end to end (the reference speaks
gRPC + HTTP; we keep one wire format for the whole plane — long-lived
streams become periodic POSTs / long-polls). Data paths (uploads, shard
copy) use raw bodies with query params.
"""

from __future__ import annotations

import json
import re
import socket
import threading
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Optional


class Request:
    def __init__(self, handler: BaseHTTPRequestHandler, match: re.Match,
                 body: bytes):
        self.handler = handler
        self.method = handler.command
        parsed = urllib.parse.urlparse(handler.path)
        # percent-decode like every mainstream HTTP server: a client
        # PUTting /a%20b and one GETting "/a b" name the same resource.
        # raw_path keeps the wire form (SigV4 canonical URIs sign it).
        self.path = urllib.parse.unquote(parsed.path)
        self.raw_path = parsed.path
        self.query = {k: v[0] for k, v in
                      urllib.parse.parse_qs(
                          parsed.query, keep_blank_values=True).items()}
        self.match = match
        self.body = body
        self.headers = handler.headers

    def json(self) -> Any:
        return json.loads(self.body) if self.body else None


class LocalRequest:
    """Duck-typed Request for in-process dispatch (the gRPC planes reuse
    the HTTP handler bodies without a socket)."""

    def __init__(self, body: Any = None, query: Optional[dict] = None,
                 method: str = "POST", path: str = "/",
                 headers: Optional[dict] = None):
        self.method = method
        self.path = path
        self.raw_path = path
        self.query = query or {}
        self.body = (json.dumps(body).encode()
                     if isinstance(body, (dict, list)) else (body or b""))
        self.headers = headers or {}
        self.match = None
        self.handler = None

    def json(self) -> Any:
        return json.loads(self.body) if self.body else None


class Response:
    def __init__(self, body: Any = None, status: int = 200,
                 content_type: str = "application/json",
                 headers: Optional[dict] = None):
        self.status = status
        self.headers = headers or {}
        # invoked after the response hits the wire (in-flight accounting)
        self.on_sent = None
        if isinstance(body, (dict, list)):
            self.body = json.dumps(body).encode()
            self.content_type = "application/json"
        elif isinstance(body, str):
            self.body = body.encode()
            self.content_type = content_type
        elif body is None:
            self.body = b""
            self.content_type = content_type
        else:
            self.body = bytes(body)
            self.content_type = content_type


Route = tuple[str, re.Pattern, Callable[[Request], Response]]


class HttpServer:
    """Route table + ThreadingHTTPServer. Routes are (METHOD, regex)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.routes: list[Route] = []
        self.host = host
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # body_gate(path, content_length) is consulted BEFORE the request
        # body is read from the socket: it returns a Response to reject
        # the request unread (413/429 load shedding), a callable to be
        # invoked once the response is fully sent (in-flight byte
        # accounting), or None to proceed unthrottled (reference
        # weed/server/volume_server_handlers.go inFlight*DataLimitCond).
        self.body_gate = None

    def route(self, method: str, pattern: str):
        compiled = re.compile("^" + pattern + "$")

        def deco(fn):
            self.routes.append((method.upper(), compiled, fn))
            return fn
        return deco

    def add(self, method: str, pattern: str, fn) -> None:
        self.routes.append((method.upper(), re.compile("^" + pattern + "$"),
                            fn))

    def start(self) -> None:
        routes = self.routes
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _dispatch(self):
                length = int(self.headers.get("Content-Length") or 0)
                path = urllib.parse.unquote(
                    urllib.parse.urlparse(self.path).path)
                on_sent = None
                gate = server.body_gate
                if gate is not None and length and \
                        self.command in ("POST", "PUT"):
                    verdict = gate(path, length)
                    if isinstance(verdict, Response):
                        # reject WITHOUT buffering the body: drain it in
                        # discarded 64KB chunks (bounded memory) so the
                        # client finishes sending and can actually read
                        # the 413/429; truly huge payloads are cut off
                        # after a few MB like Go's http server does
                        remaining = min(length, 8 << 20)
                        try:
                            while remaining > 0:
                                got = self.rfile.read(min(remaining, 65536))
                                if not got:
                                    break
                                remaining -= len(got)
                        except OSError:
                            pass
                        verdict.headers.setdefault("Connection", "close")
                        self.close_connection = True
                        self._send(verdict)
                        return
                    on_sent = verdict
                resp = None
                try:
                    body = self.rfile.read(length) if length else b""
                    for method, pattern, fn in routes:
                        if method != self.command:
                            continue
                        m = pattern.match(path)
                        if m:
                            try:
                                resp = fn(Request(self, m, body))
                            except Exception as e:  # surface as 500 JSON
                                resp = Response(
                                    {"error": f"{type(e).__name__}: {e}"},
                                    status=500)
                            break
                    else:
                        resp = Response({"error": "not found"}, status=404)
                    self._send(resp)
                finally:
                    if on_sent is not None:
                        on_sent()
                    cb = getattr(resp, "on_sent", None)
                    if cb is not None:
                        cb()

            def _send(self, resp):
                try:
                    self.send_response(resp.status)
                    self.send_header("Content-Type", resp.content_type)
                    if "Content-Length" not in resp.headers:
                        # HEAD handlers set it to the entity size; the
                        # wire body is still suppressed below
                        self.send_header("Content-Length",
                                         str(len(resp.body)))
                    for k, v in resp.headers.items():
                        self.send_header(k, v)
                    self.end_headers()
                    if self.command != "HEAD":
                        self.wfile.write(resp.body)
                except (BrokenPipeError, ConnectionResetError):
                    pass

            do_GET = do_POST = do_PUT = do_DELETE = do_HEAD = _dispatch
            # WebDAV verbs
            do_OPTIONS = do_PROPFIND = do_PROPPATCH = _dispatch
            do_MKCOL = do_MOVE = do_COPY = do_LOCK = do_UNLOCK = _dispatch

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


class HttpError(Exception):
    def __init__(self, status: int, body: bytes):
        self.status = status
        self.body = body
        super().__init__(f"HTTP {status}: {body[:200]!r}")


def http_call(method: str, url: str, body: Optional[bytes] = None,
              json_body: Any = None, timeout: float = 30.0,
              headers: Optional[dict] = None) -> tuple[int, bytes, dict]:
    if json_body is not None:
        body = json.dumps(json_body).encode()
        headers = dict(headers or {})
        headers["Content-Type"] = "application/json"
    req = urllib.request.Request(url, data=body, method=method.upper(),
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)
    except (urllib.error.URLError, socket.timeout, ConnectionError) as e:
        raise ConnectionError(f"{method} {url}: {e}") from e


def http_json(method: str, url: str, json_body: Any = None,
              timeout: float = 30.0) -> Any:
    status, body, _ = http_call(method, url, json_body=json_body,
                                timeout=timeout)
    if status >= 400:
        raise HttpError(status, body)
    return json.loads(body) if body else None
