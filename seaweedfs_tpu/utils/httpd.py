"""Minimal threaded HTTP/JSON server + client helpers.

The control plane speaks HTTP/JSON end to end (the reference speaks
gRPC + HTTP; we keep one wire format for the whole plane — long-lived
streams become periodic POSTs / long-polls). Data paths (uploads, shard
copy) use raw bodies with query params.
"""

from __future__ import annotations

import json
import re
import socket
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Optional

from seaweedfs_tpu.qos import classes as qos_classes
from seaweedfs_tpu.utils import clockctl, glog, resilience, tracing

# route-family derivation for the RED histogram: a closed, low-
# cardinality set so (server, route_family, class, status_family)
# never explodes. Needle fids ("/3,0101f2") collapse to one family;
# anything not in the control-plane set is user namespace ("fs" —
# filer paths, S3 objects, DAV trees).
_NEEDLE_RE = re.compile(r"^/\d+,")
_CONTROL_FAMILIES = frozenset((
    "dir", "vol", "col", "cluster", "admin", "metrics", "status",
    "debug", "ui", "heartbeat", "raft", "scrub", "ec", "delete",
    "batch"))


def route_family(path: str) -> str:
    if not path or path == "/":
        return "root"
    if _NEEDLE_RE.match(path):
        return "needle"
    seg = path.split("/", 2)[1]
    if seg == "__api":
        return "api"
    if seg in _CONTROL_FAMILIES:
        return seg
    return "fs"


class Request:
    def __init__(self, handler: BaseHTTPRequestHandler, match: re.Match,
                 body: bytes):
        self.handler = handler
        self.method = handler.command
        parsed = urllib.parse.urlparse(handler.path)
        # percent-decode like every mainstream HTTP server: a client
        # PUTting /a%20b and one GETting "/a b" name the same resource.
        # raw_path keeps the wire form (SigV4 canonical URIs sign it).
        self.path = urllib.parse.unquote(parsed.path)
        self.raw_path = parsed.path
        self.query = {k: v[0] for k, v in
                      urllib.parse.parse_qs(
                          parsed.query, keep_blank_values=True).items()}
        self.match = match
        self.body = body
        self.headers = handler.headers

    def json(self) -> Any:
        return json.loads(self.body) if self.body else None


class LocalRequest:
    """Duck-typed Request for in-process dispatch (the gRPC planes reuse
    the HTTP handler bodies without a socket)."""

    def __init__(self, body: Any = None, query: Optional[dict] = None,
                 method: str = "POST", path: str = "/",
                 headers: Optional[dict] = None):
        self.method = method
        self.path = path
        self.raw_path = path
        self.query = query or {}
        self.body = (json.dumps(body).encode()
                     if isinstance(body, (dict, list)) else (body or b""))
        self.headers = headers or {}
        self.match = None
        self.handler = None

    def json(self) -> Any:
        return json.loads(self.body) if self.body else None


class Response:
    def __init__(self, body: Any = None, status: int = 200,
                 content_type: str = "application/json",
                 headers: Optional[dict] = None):
        self.status = status
        self.headers = headers or {}
        # invoked after the response hits the wire (in-flight accounting)
        self.on_sent = None
        if isinstance(body, (dict, list)):
            self.body = json.dumps(body).encode()
            self.content_type = "application/json"
        elif isinstance(body, str):
            self.body = body.encode()
            self.content_type = content_type
        elif body is None:
            self.body = b""
            self.content_type = content_type
        else:
            self.body = bytes(body)
            self.content_type = content_type


class HeaderDict:
    """Case-insensitive header mapping that preserves wire-case keys —
    a lean stand-in for email.message.Message on the hot path (the
    stdlib parse_headers routes every message through the full email
    parser, which costs more than our entire dispatch)."""

    __slots__ = ("_d",)

    def __init__(self):
        self._d: dict[str, tuple[str, str]] = {}

    def add(self, key: str, value: str) -> None:
        lk = key.lower()
        old = self._d.get(lk)
        if old is not None:  # duplicate header: RFC 7230 comma-join
            self._d[lk] = (old[0], old[1] + ", " + value)
        else:
            self._d[lk] = (key, value)

    def get(self, key: str, default=None):
        hit = self._d.get(key.lower())
        return hit[1] if hit is not None else default

    def __getitem__(self, key: str) -> str:
        return self._d[key.lower()][1]

    def __contains__(self, key) -> bool:
        return str(key).lower() in self._d

    def items(self):
        return list(self._d.values())

    def __iter__(self):
        return iter(k for k, _ in self._d.values())


Route = tuple[str, re.Pattern, Callable[[Request], Response]]


class HttpServer:
    """Route table + ThreadingHTTPServer. Routes are (METHOD, regex)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.routes: list[Route] = []
        self.host = host
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # body_gate(path, content_length) is consulted BEFORE the request
        # body is read from the socket: it returns a Response to reject
        # the request unread (413/429 load shedding), a callable to be
        # invoked once the response is fully sent (in-flight byte
        # accounting), or None to proceed unthrottled (reference
        # weed/server/volume_server_handlers.go inFlight*DataLimitCond).
        self.body_gate = None
        # admission_gate(method, path, headers, client_ip) runs first,
        # for EVERY method: the QoS governor's hook. Same verdict
        # contract as body_gate — a Response sheds the request (503 +
        # Retry-After) before its body is buffered, a callable releases
        # the admission slot once the response is fully sent, None
        # passes. See seaweedfs_tpu/qos/governor.py.
        self.admission_gate = None
        # tracing.Tracer wired by the owning server: _dispatch mints a
        # server span per request (continuing an inbound X-Weed-Trace)
        # and records it into the node's flight recorder. None -> the
        # shared NOOP span, zero allocation.
        self.tracer = None
        # metrics.RedRecorder wired by the owning server: ONE
        # observation site covers every edge's rate/errors/duration,
        # including requests the gates shed. None -> one attribute
        # check per request.
        self.red = None
        # graceful-drain state: once draining, new requests (including
        # ones arriving on kept-alive connections) are answered 503 +
        # Connection: close while in-flight requests run to completion;
        # drain() waits on the in-flight counter.
        self.draining = False
        self._inflight = 0
        self._inflight_lock = threading.Lock()

    def route(self, method: str, pattern: str):
        compiled = re.compile("^" + pattern + "$")

        def deco(fn):
            self.routes.append((method.upper(), compiled, fn))
            return fn
        return deco

    def add(self, method: str, pattern: str, fn) -> None:
        self.routes.append((method.upper(), re.compile("^" + pattern + "$"),
                            fn))

    def start(self) -> None:
        routes = self.routes
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # buffered response writes + no Nagle: headers and body
            # coalesce into one segment instead of trickling out in
            # tiny writes that collide with delayed ACKs (a flat
            # +40ms/request on keep-alive connections otherwise)
            wbufsize = 64 * 1024
            disable_nagle_algorithm = True

            def log_message(self, *args):
                pass  # request lines are emitted via glog at -v=2

            def parse_request(self) -> bool:
                """Minimal HTTP/1.1 request parse replacing the stdlib
                email-parser path (which dominates per-request CPU on
                the 1KB data path). Sets the same attributes the base
                class would: command/path/request_version/headers/
                close_connection, incl. Expect: 100-continue."""
                self.command = None
                self.request_version = version = "HTTP/0.9"
                self.close_connection = True
                raw = str(self.raw_requestline, "latin-1").rstrip("\r\n")
                self.requestline = raw
                parts = raw.split()
                if len(parts) == 3:
                    command, path, version = parts
                    if not version.startswith("HTTP/"):
                        self.send_error(400,
                                        f"Bad request version {version!r}")
                        return False
                elif len(parts) == 2:
                    command, path = parts
                else:
                    self.send_error(400, f"Bad request syntax {raw!r}")
                    return False
                self.command, self.path = command, path
                self.request_version = version
                headers = HeaderDict()
                n_headers = 0
                while True:
                    line = self.rfile.readline(65537)
                    if len(line) > 65536:
                        self.send_error(431, "header line too long")
                        return False
                    if line in (b"\r\n", b"\n", b"", b"\r"):
                        break
                    n_headers += 1
                    if n_headers > 100:  # stdlib _MAXHEADERS parity
                        self.send_error(431, "too many headers")
                        return False
                    k, sep, v = line.decode("latin-1").partition(":")
                    if sep:
                        headers.add(k.strip(), v.strip())
                self.headers = headers
                conn = (headers.get("Connection") or "").lower()
                if version >= "HTTP/1.1":
                    self.close_connection = conn == "close"
                else:
                    self.close_connection = conn != "keep-alive"
                if version >= "HTTP/1.1" and \
                        headers.get("Expect", "").lower() == "100-continue":
                    if not self.handle_expect_100():
                        return False
                return True

            def _reject(self, verdict, length):
                # reject WITHOUT buffering the body: drain it in
                # discarded 64KB chunks (bounded memory) so the
                # client finishes sending and can actually read
                # the 413/429/503; truly huge payloads are cut off
                # after a few MB like Go's http server does
                remaining = min(length, 8 << 20)
                try:
                    while remaining > 0:
                        got = self.rfile.read(min(remaining, 65536))
                        if not got:
                            break
                        remaining -= len(got)
                except OSError:
                    pass
                verdict.headers.setdefault("Connection", "close")
                self.close_connection = True
                self._send(verdict)

            def _dispatch(self):
                length = int(self.headers.get("Content-Length") or 0)
                if server.draining:
                    # a draining server takes no NEW work; kept-alive
                    # clients get a clean 503 + close so their retry
                    # lands on another replica immediately
                    self._reject(Response(
                        {"error": "draining"}, status=503,
                        headers={"Retry-After": "1"}), length)
                    return
                with server._inflight_lock:
                    server._inflight += 1
                try:
                    self._dispatch_traced(length)
                finally:
                    with server._inflight_lock:
                        server._inflight -= 1

            def _dispatch_traced(self, length):
                path = urllib.parse.unquote(
                    urllib.parse.urlparse(self.path).path)
                # server span: continue an inbound X-Weed-Trace or mint
                # a fresh trace at this edge. Ambient BEFORE the gates
                # so QoS verdicts annotate it, and around the handler so
                # nested http_calls inject the header downstream. With
                # no tracer (or disabled) this is one attribute check
                # plus the shared NOOP span — no allocation.
                tracer = server.tracer
                span = (tracer.server_span(f"{self.command} {path}",
                                           self.headers)
                        if tracer is not None else tracing.NOOP)
                tok = tracing.attach(span)
                try:
                    self._dispatch_inner(path, length, span)
                finally:
                    tracing.detach(tok)

            def _dispatch_inner(self, path, length, span):
                # RED edge observation brackets EVERYTHING — admission
                # sheds, gate rejects, 404s, handler 500s — so the
                # duration histogram is the true edge view. clockctl
                # timing: under the sim's virtual clock the same
                # histograms elapse in virtual seconds.
                t_red = clockctl.monotonic()
                red = server.red

                def red_observe(status):
                    if red is None:
                        return
                    cls = qos_classes.from_headers(self.headers) \
                        or qos_classes.classify(self.command, path)
                    red.observe(route_family(path), cls, status,
                                clockctl.monotonic() - t_red,
                                exemplar=span.trace_id
                                if span.sampled else None)

                release = None
                agate = server.admission_gate
                if agate is not None:
                    verdict = agate(self.command, path, self.headers,
                                    self.client_address[0])
                    if isinstance(verdict, Response):
                        self._reject(verdict, length)
                        red_observe(verdict.status)
                        span.finish(status=verdict.status)
                        return
                    release = verdict
                on_sent = None
                resp = None
                out_status = 500
                t0 = clockctl.monotonic()
                try:
                    gate = server.body_gate
                    if gate is not None and length and \
                            self.command in ("POST", "PUT"):
                        verdict = gate(path, length)
                        if isinstance(verdict, Response):
                            out_status = verdict.status
                            self._reject(verdict, length)
                            return
                        on_sent = verdict
                    body = self.rfile.read(length) if length else b""
                    # propagated traffic class becomes ambient for the
                    # handler, so its nested http_calls re-inject it
                    cls = qos_classes.from_headers(self.headers)
                    for method, pattern, fn in routes:
                        if method != self.command:
                            continue
                        m = pattern.match(path)
                        if m:
                            try:
                                with qos_classes.class_scope(cls):
                                    resp = fn(Request(self, m, body))
                            except Exception as e:  # surface as 500 JSON
                                glog.exception(
                                    "handler error: %s %s -> %s",
                                    self.command, path,
                                    type(e).__name__)
                                resp = Response(
                                    {"error": f"{type(e).__name__}: {e}"},
                                    status=500)
                            break
                    else:
                        resp = Response({"error": "not found"}, status=404)
                    out_status = resp.status
                    self._send(resp)
                    glog.vlog(2, "%s %s %d %dB %.1fms",
                              self.command, self.path, resp.status,
                              len(resp.body),
                              (clockctl.monotonic() - t0) * 1e3)
                finally:
                    if on_sent is not None:
                        on_sent()
                    cb = getattr(resp, "on_sent", None)
                    if cb is not None:
                        cb()
                    if release is not None:
                        release()
                    red_observe(out_status)
                    span.finish(status=out_status)

            def _send(self, resp):
                try:
                    self.send_response(resp.status)
                    self.send_header("Content-Type", resp.content_type)
                    if "Content-Length" not in resp.headers:
                        # HEAD handlers set it to the entity size; the
                        # wire body is still suppressed below
                        self.send_header("Content-Length",
                                         str(len(resp.body)))
                    for k, v in resp.headers.items():
                        self.send_header(k, v)
                    self.end_headers()
                    if self.command != "HEAD":
                        self.wfile.write(resp.body)
                except (BrokenPipeError, ConnectionResetError):
                    pass

            do_GET = do_POST = do_PUT = do_DELETE = do_HEAD = _dispatch
            # WebDAV verbs
            do_OPTIONS = do_PROPFIND = do_PROPPATCH = _dispatch
            do_MKCOL = do_MOVE = do_COPY = do_LOCK = do_UNLOCK = _dispatch

        class Server(ThreadingHTTPServer):
            """Tracks live per-connection sockets so stop() can sever
            them. Without this, keep-alive clients (the pooled
            http_call) keep riding ESTABLISHED sockets into a server
            whose listener is closed but whose handler threads live on
            — a stopped in-process master would keep answering
            heartbeats like a zombie."""
            daemon_threads = True

            def __init__(self, *a, **k):
                self.live_conns: set = set()
                self._conn_lock = threading.Lock()
                super().__init__(*a, **k)

            def process_request(self, request, client_address):
                with self._conn_lock:
                    self.live_conns.add(request)
                super().process_request(request, client_address)

            def shutdown_request(self, request):
                with self._conn_lock:
                    self.live_conns.discard(request)
                super().shutdown_request(request)

            def handle_error(self, request, client_address):
                # severed-at-stop connections die with broken pipes in
                # their handler threads; that's expected, not a crash.
                # ONLY connection-class errors are quieted — other
                # OSErrors (fd exhaustion etc.) must stay visible.
                import sys
                exc = sys.exc_info()[1]
                if isinstance(exc, ConnectionError):
                    return
                super().handle_error(request, client_address)

            def close_all_connections(self):
                with self._conn_lock:
                    conns = list(self.live_conns)
                for sock in conns:
                    try:
                        sock.shutdown(2)  # SHUT_RDWR: unblock handlers
                    except OSError:
                        pass

        self._httpd = Server((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def drain(self, timeout: float = 10.0) -> bool:
        """Graceful-stop phase one: refuse new requests (503 + close),
        stop accepting connections, and wait for in-flight requests to
        finish.  Returns True when the server went idle within
        ``timeout``; the caller then runs stop() for the hard close.
        Idempotent, and safe before start()."""
        self.draining = True
        if self._httpd:
            self._httpd.shutdown()
        deadline = clockctl.monotonic() + timeout
        while clockctl.monotonic() < deadline:
            with self._inflight_lock:
                if self._inflight == 0:
                    return True
            clockctl.sleep(0.02)
        with self._inflight_lock:
            return self._inflight == 0

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.close_all_connections()
            self._httpd.server_close()
            self._httpd = None


class RangeNotSatisfiable(Exception):
    """Raise-to-416: the range is well-formed but outside the entity
    (RFC 7233 §4.4; S3 answers InvalidRange). Callers respond 416 with
    'Content-Range: bytes */<total>' — serving a 200 full body instead
    would corrupt resuming downloaders that append the response."""


def parse_byte_range(spec: str, total: int) -> Optional[tuple[int, int]]:
    """RFC 7233 single-range parse: 'bytes=a-b' / 'bytes=a-' /
    'bytes=-n' (suffix: the LAST n bytes). Returns (lo, hi) inclusive;
    None when no/malformed range (serve the full body, per RFC);
    raises RangeNotSatisfiable when lo lies beyond the entity."""
    if not spec or not spec.startswith("bytes="):
        return None
    lo_s, _, hi_s = spec[6:].partition("-")
    try:
        if not lo_s:  # suffix form
            n = int(hi_s)
            if n <= 0:
                return None
            if total == 0:
                # no last-N bytes of an empty entity (AWS: 416)
                raise RangeNotSatisfiable(spec)
            return max(0, total - n), total - 1
        lo = int(lo_s)
        hi = int(hi_s) if hi_s else total - 1
    except ValueError:
        return None
    if lo >= total:
        # beyond EOF — includes the open-ended 'bytes=<past-end>-'
        # form, whose default hi (total-1) is < lo and must not be
        # mistaken for a malformed spec
        raise RangeNotSatisfiable(spec)
    if hi < lo:
        return None
    return lo, min(hi, total - 1)


class HttpError(Exception):
    def __init__(self, status: int, body: bytes,
                 retry_after: Optional[float] = None):
        self.status = status
        self.body = body
        # server-sent pacing hint (429/503): RetryPolicy sleeps this
        # instead of its own computed backoff
        self.retry_after = retry_after
        super().__init__(f"HTTP {status}: {body[:200]!r}")


def retry_after_hint(status: int, resp_headers) -> Optional[float]:
    """Seconds from a Retry-After header on a shed response (429/503
    only — the statuses the limiters emit); None otherwise. Only the
    delta-seconds form is parsed (what this codebase sends); an
    HTTP-date or garbage value degrades to None, not an error."""
    if status not in (429, 503) or not resp_headers:
        return None
    for k, v in resp_headers.items():
        if k.lower() == "retry-after":
            try:
                return max(0.0, float(v))
            except (TypeError, ValueError):
                return None
    return None


# Thread-local keep-alive connection pool: one persistent HTTP/1.1
# connection per (thread, host). The data path makes millions of tiny
# requests; per-request TCP setup/teardown (urllib's behavior) costs
# more than the request itself and floods TIME_WAIT. The reference
# leans on Go's pooled http.Transport the same way
# (weed/util/http_util.go).
_conn_local = threading.local()


class RawHttpConnection:
    """Minimal pooled HTTP/1.1 client connection. Replaces
    http.client on the hot data path: no email-parser response
    headers, no per-response makefile, one buffered reader for the
    connection's lifetime. Handles Content-Length, chunked and
    read-to-close bodies, keep-alive, and 1xx skipping."""

    def __init__(self, netloc: str, timeout: float):
        self.netloc = netloc
        host, port = netloc, 80
        if netloc.startswith("["):  # IPv6 literal [::1]:8080
            host, _, rest = netloc[1:].partition("]")
            if rest.startswith(":"):
                port = int(rest[1:])
        elif ":" in netloc:
            host, _, p = netloc.rpartition(":")
            port = int(p)
        # weedlint: disable=persistent-socket-timeout — _pooled_conn
        # re-arms settimeout() per request with the caller's deadline
        self.sock = socket.create_connection((host or "127.0.0.1", port),
                                             timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._rfile = self.sock.makefile("rb", buffering=65536)

    def close(self) -> None:
        sock, self.sock = self.sock, None
        if sock is None:
            return  # already closed
        for closer in (self._rfile.close, sock.close):
            try:
                closer()
            except OSError:
                pass

    def _read_exact(self, n: int) -> bytes:
        data = self._rfile.read(n)
        if data is None or len(data) < n:
            raise ConnectionError("short HTTP body")
        return data

    def _read_chunked(self) -> bytes:
        out = bytearray()
        while True:
            size_line = self._rfile.readline(1026)
            if not size_line:
                raise ConnectionError("EOF in chunked body")
            n = int(size_line.split(b";")[0].strip() or b"0", 16)
            if n == 0:
                while self._rfile.readline(65537) not in (b"\r\n", b"\n",
                                                          b""):
                    pass  # discard trailers
                return bytes(out)
            out += self._read_exact(n)
            self._rfile.readline(3)  # chunk CRLF

    def send_request(self, method: str, target: str,
                     body: Optional[bytes],
                     headers: Optional[dict]) -> None:
        buf = [f"{method} {target} HTTP/1.1\r\n"]
        has_len = has_host = False
        for k, v in (headers or {}).items():
            lk = k.lower()
            if lk == "content-length":
                has_len = True
            elif lk == "host":
                has_host = True  # caller-set (SigV4 signs it): no dup
            buf.append(f"{k}: {v}\r\n")
        if not has_host:
            buf.append(f"Host: {self.netloc}\r\n")
        if not has_len and (body or method not in ("GET", "HEAD")):
            buf.append(f"Content-Length: {len(body or b'')}\r\n")
        buf.append("\r\n")
        msg = "".join(buf).encode("latin-1")
        self.sock.sendall(msg + body if body else msg)

    def read_response(self, method: str) -> tuple[int, bytes, dict, bool]:
        """Returns (status, body, headers, will_close)."""
        while True:  # skip 1xx interim responses
            line = self._rfile.readline(65537)
            if not line:
                raise ConnectionError("no HTTP status line")
            parts = line.decode("latin-1").split(None, 2)
            if len(parts) < 2 or not parts[0].startswith("HTTP/"):
                raise ConnectionError(f"bad status line {line!r}")
            version, status = parts[0], int(parts[1])
            resp = HeaderDict()
            n_headers = 0
            while True:
                hl = self._rfile.readline(65537)
                if hl in (b"\r\n", b"\n", b""):
                    break
                n_headers += 1
                if n_headers > 100:  # stdlib _MAXHEADERS parity
                    raise ConnectionError("too many response headers")
                k, sep, v = hl.decode("latin-1").partition(":")
                if sep:
                    resp.add(k.strip(), v.strip())
            if status >= 200:
                break
        conn_hdr = (resp.get("Connection") or "").lower()
        will_close = (conn_hdr == "close"
                      or (version == "HTTP/1.0"
                          and conn_hdr != "keep-alive"))
        te = (resp.get("Transfer-Encoding") or "").lower()
        if method == "HEAD" or status in (204, 304):
            data = b""
        elif "chunked" in te:
            data = self._read_chunked()
        elif resp.get("Content-Length") is not None:
            data = self._read_exact(int(resp["Content-Length"]))
        else:  # body delimited by connection close (HTTP/1.0 style)
            data = self._rfile.read()
            will_close = True
        return status, data, dict(resp.items()), will_close


def _make_conn(netloc: str, timeout: float) -> RawHttpConnection:
    return RawHttpConnection(netloc, timeout)


def _pooled_conn(netloc: str, timeout: float):
    """Returns (conn, reused): `reused` is True when the socket was
    already open from a previous request — the only case where an
    automatic retry is safe (a stale kept-alive socket fails before the
    server sees anything; a fresh connection that dies mid-response may
    have EXECUTED the request, so replaying it is the caller's call).

    A pooled socket is liveness-checked before reuse (urllib3's
    is_connection_dropped): a peer that closed shows readable-EOF, and
    sending into it would "succeed" into the kernel buffer and only
    fail at response time — un-retryable for non-idempotent methods.
    This matters when a server restarts on a reused port."""
    import select
    pool = getattr(_conn_local, "conns", None)
    if pool is None:
        pool = _conn_local.conns = {}
    conn = pool.get(netloc)
    if conn is None:
        conn = _make_conn(netloc, timeout)
        pool[netloc] = conn
        return conn, False
    if conn.sock is None:
        return conn, False
    try:
        readable, _, _ = select.select([conn.sock], [], [], 0)
    except (OSError, ValueError):
        readable = [conn.sock]
    if readable:
        # EOF or unsolicited bytes: the peer is gone (or the stream is
        # desynced) — replace with a fresh connection
        conn.close()
        conn = _make_conn(netloc, timeout)
        pool[netloc] = conn
        return conn, False
    conn.sock.settimeout(timeout)
    return conn, True


def _drop_conn(netloc: str) -> None:
    pool = getattr(_conn_local, "conns", None)
    if pool is not None:
        conn = pool.pop(netloc, None)
        if conn is not None:
            conn.close()


def http_call(method: str, url: str, body: Optional[bytes] = None,
              json_body: Any = None, timeout: float = 30.0,
              headers: Optional[dict] = None,
              deadline=None) -> tuple[int, bytes, dict]:
    # Trace propagation: when a trace is ambient, this outbound RPC
    # becomes a client child span and its ids ride X-Weed-Trace so the
    # callee's server span nests under it. No ambient trace (or tracing
    # disabled) costs one ContextVar read — no span allocation.
    amb = tracing.current_span()
    if amb is None:
        return _http_call_impl(method, url, body, json_body, timeout,
                               headers, deadline)
    span = amb.child(f"{method.upper()} {url.split('?', 1)[0]}")
    headers = dict(headers or {})
    headers.setdefault(tracing.TRACE_HEADER, span.header_value())
    status, err = 0, ""
    try:
        out = _http_call_impl(method, url, body, json_body, timeout,
                              headers, deadline)
        status = out[0]
        return out
    except BaseException as e:
        status, err = 599, f"{type(e).__name__}: {e}"
        raise
    finally:
        span.finish(status=status, error=err)


def _http_call_impl(method: str, url: str, body: Optional[bytes] = None,
                    json_body: Any = None, timeout: float = 30.0,
                    headers: Optional[dict] = None,
                    deadline=None) -> tuple[int, bytes, dict]:
    # Deadline propagation: `timeout` becomes a CAP under the caller's
    # remaining budget (explicit `deadline` arg, else the ambient
    # request-scope one), and the remaining seconds ride along in the
    # X-Weed-Deadline header so the callee inherits the same budget.
    # An already-expired deadline raises DeadlineExceeded (a
    # ConnectionError) before any bytes hit the wire.
    if deadline is None:
        deadline = resilience.current_deadline()
    if deadline is not None:
        timeout = deadline.timeout(cap=timeout)
        headers = dict(headers or {})
        headers.setdefault(resilience.DEADLINE_HEADER,
                           deadline.header_value())
    # traffic class rides along exactly like the deadline: ambient
    # scope -> X-Weed-Class header -> callee re-enters the scope
    cls = qos_classes.current_class()
    if cls is not None:
        headers = dict(headers or {})
        headers.setdefault(qos_classes.CLASS_HEADER, cls)
    if json_body is not None:
        body = json.dumps(json_body).encode()
        headers = dict(headers or {})
        headers["Content-Type"] = "application/json"
    parsed = urllib.parse.urlsplit(url)
    if parsed.scheme == "https":  # rare path: no pooling, plain urllib
        req = urllib.request.Request(url, data=body, method=method.upper(),
                                     headers=headers or {})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, r.read(), dict(r.headers)
        except urllib.error.HTTPError as e:
            return e.code, e.read(), dict(e.headers)
        except (urllib.error.URLError, socket.timeout, ConnectionError) as e:
            raise ConnectionError(f"{method} {url}: {e}") from e
    target = parsed.path or "/"
    if parsed.query:
        target += "?" + parsed.query
    method = method.upper()
    last_err = None
    for attempt in (0, 1):
        sent = False
        reused = False
        try:
            # inside the try: connection setup itself can raise
            # (SYN timeout, DNS failure, bad netloc) and must surface
            # as ConnectionError like every other transport failure
            conn, reused = _pooled_conn(parsed.netloc, timeout)
            conn.send_request(method, target, body, headers)
            sent = True
            status, data, resp_headers, will_close = \
                conn.read_response(method)
            if will_close:
                _drop_conn(parsed.netloc)
            return status, data, resp_headers
        except (BrokenPipeError, ConnectionResetError,
                ConnectionRefusedError, ConnectionAbortedError,
                ConnectionError, socket.timeout, ValueError,
                OSError) as e:
            _drop_conn(parsed.netloc)
            last_err = e
            # Replay rules (Go http.Transport's): only on a REUSED
            # kept-alive socket, and only when the request either
            # failed during SEND (server closed it idle; it never
            # executed) or is idempotent (GET/HEAD). A non-idempotent
            # POST that died mid-response may have executed — surface
            # the error rather than silently running it twice.
            idempotent = method in ("GET", "HEAD")
            if not reused or (sent and not idempotent) or \
                    isinstance(e, (ConnectionRefusedError,
                                   socket.timeout)):
                break
    raise ConnectionError(f"{method} {url}: {last_err}") from last_err


def http_json(method: str, url: str, json_body: Any = None,
              timeout: float = 30.0, deadline=None) -> Any:
    status, body, resp_headers = http_call(method, url, json_body=json_body,
                                           timeout=timeout,
                                           deadline=deadline)
    if status >= 400:
        raise HttpError(status, body,
                        retry_after=retry_after_hint(status, resp_headers))
    return json.loads(body) if body else None
