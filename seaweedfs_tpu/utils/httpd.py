"""Minimal selector-core HTTP/JSON server + client helpers.

The control plane speaks HTTP/JSON end to end (the reference speaks
gRPC + HTTP; we keep one wire format for the whole plane — long-lived
streams become periodic POSTs / long-polls). Data paths (uploads, shard
copy) use raw bodies with query params.

Serving model (reference: Go's netpoller + goroutine-per-request, here
selectors + a bounded worker pool): ONE selector thread owns the
listener and every parked keep-alive socket; a connection costs a
thread only while a request is actually being served. Ready sockets are
handed to a bounded, demand-grown worker pool, so 10k mostly-idle
connections hold 10k fds but ~0 threads. Ambient context (Deadline,
QoS class, trace span, RED observation) is entered per DISPATCHED
REQUEST inside ``_dispatch`` — never per connection — so a parked
socket holds no scope and a worker thread never leaks one request's
scope into the next.
"""

from __future__ import annotations

import collections
import json
import os
import queue
import re
import select
import selectors
import socket
import stat
import threading
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler
from typing import Any, Callable, Optional

from seaweedfs_tpu.qos import classes as qos_classes
from seaweedfs_tpu.utils import (clockctl, glog, profiler, resilience,
                                 tracing)

# route-family derivation for the RED histogram: a closed, low-
# cardinality set so (server, route_family, class, status_family)
# never explodes. Needle fids ("/3,0101f2") collapse to one family;
# anything not in the control-plane set is user namespace ("fs" —
# filer paths, S3 objects, DAV trees).
_NEEDLE_RE = re.compile(r"^/\d+,")
_CONTROL_FAMILIES = frozenset((
    "dir", "vol", "col", "cluster", "admin", "metrics", "status",
    "debug", "ui", "heartbeat", "raft", "scrub", "ec", "delete",
    "batch"))


def route_family(path: str) -> str:
    if not path or path == "/":
        return "root"
    if _NEEDLE_RE.match(path):
        return "needle"
    seg = path.split("/", 2)[1]
    if seg == "__api":
        return "api"
    if seg in _CONTROL_FAMILIES:
        return seg
    return "fs"


class Request:
    def __init__(self, handler: BaseHTTPRequestHandler, match: re.Match,
                 body: Optional[bytes] = None, stream=None):
        self.handler = handler
        self.method = handler.command
        parsed = urllib.parse.urlparse(handler.path)
        # percent-decode like every mainstream HTTP server: a client
        # PUTting /a%20b and one GETting "/a b" name the same resource.
        # raw_path keeps the wire form (SigV4 canonical URIs sign it).
        self.path = urllib.parse.unquote(parsed.path)
        self.raw_path = parsed.path
        self.query = {k: v[0] for k, v in
                      urllib.parse.parse_qs(
                          parsed.query, keep_blank_values=True).items()}
        self.match = match
        self._body = body
        # incremental body reader (BodyStream). Handlers that consume
        # it chunk-at-a-time (filer streaming ingest) never pay
        # whole-body memory; handlers that touch .body instead get the
        # old buffered semantics lazily.
        self.stream = stream
        self.headers = handler.headers

    @property
    def body(self) -> bytes:
        if self._body is None:
            self._body = (self.stream.readall()
                          if self.stream is not None else b"")
        return self._body

    @body.setter
    def body(self, value: bytes) -> None:
        self._body = value

    def json(self) -> Any:
        return json.loads(self.body) if self.body else None


class LocalRequest:
    """Duck-typed Request for in-process dispatch (the gRPC planes reuse
    the HTTP handler bodies without a socket)."""

    def __init__(self, body: Any = None, query: Optional[dict] = None,
                 method: str = "POST", path: str = "/",
                 headers: Optional[dict] = None):
        self.method = method
        self.path = path
        self.raw_path = path
        self.query = query or {}
        self.body = (json.dumps(body).encode()
                     if isinstance(body, (dict, list)) else (body or b""))
        self.headers = headers or {}
        self.match = None
        self.handler = None

    def json(self) -> Any:
        return json.loads(self.body) if self.body else None


class FileSlice:
    """A ``(fd, offset, count)`` window of a regular file standing in
    for a response body — the zero-copy read-plane descriptor. The
    payload never enters userspace on the common path: ``_send`` hands
    the window to ``os.sendfile`` and the kernel moves pages straight
    from the page cache to the socket. ``__len__`` is the window size,
    so Content-Length, access-log byte counts, and the ledger all work
    unchanged.

    Owns its fd (``send_file`` dups the caller's): the transport closes
    it after the send, win or lose, so a descriptor response stays
    valid even if the producing volume is compacted or closed while the
    bytes are in flight — the dup'd fd pins the old inode."""

    __slots__ = ("fd", "offset", "count", "_closed")

    def __init__(self, fd: int, offset: int, count: int):
        self.fd = fd
        self.offset = int(offset)
        self.count = int(count)
        self._closed = False

    def __len__(self) -> int:
        return self.count

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            os.close(self.fd)
        except OSError:
            pass

    def read_all(self) -> bytes:
        """Materialize the window (in-process LocalRequest dispatch and
        tests — NOT the wire path, which sendfiles it)."""
        out = bytearray()
        off, end = self.offset, self.offset + self.count
        while off < end:
            piece = os.pread(self.fd, min(1 << 20, end - off), off)
            if not piece:
                raise OSError(
                    f"FileSlice: EOF at {off}, wanted {end - off} more")
            out += piece
            off += len(piece)
        return bytes(out)


def send_file(fd: int, offset: int, count: int, *, status: int = 200,
              content_type: str = "application/octet-stream",
              headers: Optional[dict] = None) -> Response:
    """Descriptor response primitive: serve ``count`` bytes of the
    regular file behind ``fd`` starting at ``offset`` without reading
    them into Python. The fd is dup'd here (the response owns the dup;
    the caller keeps its handle) and closed by the transport after the
    payload is on the wire. Callers that may fail between building and
    returning the Response must close ``resp.body`` on that error
    path."""
    return Response(FileSlice(os.dup(fd), offset, count), status=status,
                    content_type=content_type, headers=headers)


class Response:
    def __init__(self, body: Any = None, status: int = 200,
                 content_type: str = "application/json",
                 headers: Optional[dict] = None):
        self.status = status
        self.headers = headers or {}
        # invoked after the response hits the wire (in-flight accounting)
        self.on_sent = None
        if isinstance(body, (dict, list)):
            self.body = json.dumps(body).encode()
            self.content_type = "application/json"
        elif isinstance(body, str):
            self.body = body.encode()
            self.content_type = content_type
        elif body is None:
            self.body = b""
            self.content_type = content_type
        elif isinstance(body, (memoryview, FileSlice)):
            # zero-copy bodies ride through uncoerced: a memoryview is
            # written to the socket as-is, a FileSlice is sendfile'd
            self.body = body
            self.content_type = content_type
        else:
            self.body = bytes(body)
            self.content_type = content_type


class HeaderDict:
    """Case-insensitive header mapping that preserves wire-case keys —
    a lean stand-in for email.message.Message on the hot path (the
    stdlib parse_headers routes every message through the full email
    parser, which costs more than our entire dispatch)."""

    __slots__ = ("_d",)

    def __init__(self):
        self._d: dict[str, tuple[str, str]] = {}

    def add(self, key: str, value: str) -> None:
        lk = key.lower()
        old = self._d.get(lk)
        if old is not None:  # duplicate header: RFC 7230 comma-join
            self._d[lk] = (old[0], old[1] + ", " + value)
        else:
            self._d[lk] = (key, value)

    def get(self, key: str, default=None):
        hit = self._d.get(key.lower())
        return hit[1] if hit is not None else default

    def __getitem__(self, key: str) -> str:
        return self._d[key.lower()][1]

    def __contains__(self, key) -> bool:
        return str(key).lower() in self._d

    def items(self):
        return list(self._d.values())

    def __iter__(self):
        return iter(k for k, _ in self._d.values())


Route = tuple[str, re.Pattern, Callable[[Request], Response]]


class _BufferedReader:
    """Buffered reader owned by the connection (replaces ``makefile``).
    Exposes ``has_buffered()`` so the dispatch loop can see pipelined
    bytes that are already in user space — those would never make the
    parked socket readable again, so parking on them would strand the
    request."""

    __slots__ = ("_sock", "_buf", "_pos", "_eof")
    _CHUNK = 65536

    def __init__(self, sock):
        self._sock = sock
        self._buf = b""
        self._pos = 0
        self._eof = False

    def has_buffered(self) -> bool:
        return self._pos < len(self._buf)

    def _compact(self) -> None:
        if self._pos >= len(self._buf):
            self._buf = b""
            self._pos = 0

    def readline(self, limit: int = -1) -> bytes:
        while True:
            i = self._buf.find(b"\n", self._pos)
            if i != -1:
                i += 1
                if 0 <= limit < i - self._pos:
                    i = self._pos + limit
                line = self._buf[self._pos:i]
                self._pos = i
                self._compact()
                return line
            if 0 <= limit <= len(self._buf) - self._pos:
                line = self._buf[self._pos:self._pos + limit]
                self._pos += limit
                self._compact()
                return line
            if self._eof:
                line = self._buf[self._pos:]
                self._buf = b""
                self._pos = 0
                return line
            data = self._sock.recv(self._CHUNK)
            if not data:
                self._eof = True
                continue
            if self._pos:
                self._buf = self._buf[self._pos:] + data
                self._pos = 0
            else:
                self._buf += data

    def read(self, n: int = -1) -> bytes:
        if n < 0:  # read to EOF (not on the server hot path)
            chunks = [self._buf[self._pos:]]
            self._buf = b""
            self._pos = 0
            while not self._eof:
                data = self._sock.recv(self._CHUNK)
                if not data:
                    self._eof = True
                    break
                chunks.append(data)
            return b"".join(chunks)
        avail = len(self._buf) - self._pos
        if avail >= n:
            out = self._buf[self._pos:self._pos + n]
            self._pos += n
            self._compact()
            return out
        chunks = [self._buf[self._pos:]] if avail else []
        self._buf = b""
        self._pos = 0
        got = avail
        while got < n and not self._eof:
            data = self._sock.recv(min(self._CHUNK, n - got))
            if not data:
                self._eof = True
                break
            chunks.append(data)
            got += len(data)
        return b"".join(chunks)


class BodyStream:
    """Incremental request-body reader handed to handlers as
    ``Request.stream`` — the home of every body read in the process
    (the weedlint ``unbounded-body-read`` rule points here).

    Content-Length mode hands out at most the declared length and
    raises ConnectionError when the client hangs up short — a lying
    Content-Length must surface as an error, never a silently
    truncated object. Chunked mode decodes Transfer-Encoding: chunked
    incrementally as chunks arrive. Never holds more than one read()'s
    worth of bytes, so body memory is the CALLER's budget."""

    __slots__ = ("_rfile", "_remaining", "_chunked", "_chunk_left",
                 "_done", "consumed", "broken")

    def __init__(self, rfile, length: int = 0, chunked: bool = False):
        self._rfile = rfile
        self._remaining = max(0, length)
        self._chunked = chunked
        self._chunk_left = 0
        self._done = not chunked and length <= 0
        self.consumed = 0
        # a transport error mid-body desyncs HTTP framing: the
        # connection must close, not serve another request
        self.broken = False

    @property
    def exhausted(self) -> bool:
        return self._done

    def read(self, n: int) -> bytes:
        """Up to n body bytes; b'' at end of body. Chunked mode may
        return less than n with more still coming (one wire chunk at
        a time) — loop until b'' for exact counts."""
        if self._done or n <= 0:
            return b""
        try:
            data = (self._read_chunked(n) if self._chunked
                    else self._read_plain(n))
        except (OSError, ConnectionError):
            self.broken = True
            raise
        self.consumed += len(data)
        return data

    def _read_plain(self, n: int) -> bytes:
        want = min(n, self._remaining)
        data = self._rfile.read(want)
        if len(data) < want:
            raise ConnectionError(
                f"short request body: got {self.consumed + len(data)} "
                f"of a declared {self.consumed + self._remaining}")
        self._remaining -= want
        if self._remaining <= 0:
            self._done = True
        return data

    def _read_chunked(self, n: int) -> bytes:
        if self._chunk_left == 0:
            size_line = self._rfile.readline(1026)
            if not size_line:
                raise ConnectionError("EOF in chunked request body")
            try:
                self._chunk_left = int(
                    size_line.split(b";")[0].strip() or b"0", 16)
            except ValueError:
                raise ConnectionError(
                    f"bad chunk size {size_line[:32]!r}") from None
            if self._chunk_left == 0:
                while self._rfile.readline(65537) not in (b"\r\n", b"\n",
                                                          b""):
                    pass  # discard trailers
                self._done = True
                return b""
        take = min(n, self._chunk_left)
        data = self._rfile.read(take)
        if len(data) < take:
            raise ConnectionError("EOF mid-chunk in request body")
        self._chunk_left -= take
        if self._chunk_left == 0:
            self._rfile.readline(3)  # chunk-terminating CRLF
        return data

    def readall(self) -> bytes:
        out = bytearray()
        while True:
            piece = self.read(1 << 20)
            if not piece:
                return bytes(out)
            out += piece

    def drain(self, limit: int = 8 << 20) -> bool:
        """Discard the unread remainder so the next keep-alive request
        starts at a frame boundary. False (caller must close the
        connection) when the transport already broke or more than
        ``limit`` bytes would be thrown away — reading out a huge
        ignored body is worse than a reconnect (Go's server draws the
        same line)."""
        if self.broken:
            return False
        thrown = 0
        try:
            while not self._done:
                piece = self.read(65536)
                thrown += len(piece)
                if thrown > limit:
                    return False
        except (OSError, ConnectionError):
            return False
        return True


# worker-loop verdicts for one service() slice of a connection
_PARK = "park"
_CLOSE = "close"


def _fd_readable(sock) -> bool:
    """Zero-timeout readability probe. poll() where available:
    select.select() raises ValueError for fds >= FD_SETSIZE (1024),
    which an edge holding thousands of parked sockets crosses early."""
    if hasattr(select, "poll"):
        p = select.poll()
        p.register(sock.fileno(), select.POLLIN)
        return bool(p.poll(0))
    r, _, _ = select.select([sock], [], [], 0)
    return bool(r)


def _fd_writable(sock, timeout: Optional[float]) -> bool:
    """Block until the socket's send buffer drains (or timeout). The
    sendfile loop lands here on EAGAIN: service() armed a socket
    timeout, which puts the fd in non-blocking mode internally, so a
    full send buffer surfaces as BlockingIOError instead of blocking
    inside the syscall."""
    if hasattr(select, "poll"):
        p = select.poll()
        p.register(sock.fileno(), select.POLLOUT)
        return bool(p.poll(None if timeout is None else timeout * 1000))
    _, w, _ = select.select([], [sock], [], timeout)
    return bool(w)


_BUSY_BODY = b'{"error": "server busy"}'


class _ConnHandler(BaseHTTPRequestHandler):
    """Per-connection handler object; lives as long as the connection
    (parked or active) and is re-entered by worker threads one request
    at a time. Subclasses BaseHTTPRequestHandler for its response
    helpers (send_response/send_error/handle_expect_100) but owns its
    read loop: ``service()`` runs zero-or-more pipelined requests and
    reports whether to park the socket back on the selector or close.
    """

    protocol_version = "HTTP/1.1"
    # buffered response writes + no Nagle: headers and body coalesce
    # into one segment instead of trickling out in tiny writes that
    # collide with delayed ACKs (a flat +40ms/request on keep-alive
    # connections otherwise)
    wbufsize = 64 * 1024
    disable_nagle_algorithm = True

    def __init__(self, sock, addr, srv: "HttpServer"):
        # deliberately NOT calling super().__init__ — socketserver's
        # constructor runs the whole request loop inline
        self.srv = srv
        self.connection = self.request = sock
        self.client_address = addr
        self.server = None
        self.command = ""
        self.requestline = ""
        self.request_version = self.default_request_version
        self.close_connection = True
        if self.disable_nagle_algorithm:
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
        self.rfile = _BufferedReader(sock)
        self.wfile = sock.makefile("wb", self.wbufsize)

    def log_message(self, *args):
        pass  # request lines are emitted via glog at -v=2

    # ------------------------------------------------ connection loop

    def service(self) -> str:
        """Serve requests until the connection goes idle (-> park),
        closes, or errors. Runs on a worker thread; every request
        re-enters the ambient scopes inside _dispatch, so nothing
        leaks across requests or across the park/resume boundary."""
        try:
            # weedlint: disable=persistent-socket-timeout — re-armed
            # per service slice; parked sockets idle under the
            # selector, not under a timeout
            self.connection.settimeout(self.srv.io_timeout)
        except OSError:
            return _CLOSE
        try:
            while True:
                self.close_connection = True
                self.raw_requestline = self.rfile.readline(65537)
                if not self.raw_requestline:
                    return _CLOSE
                if len(self.raw_requestline) > 65536:
                    self.requestline = ""
                    self.request_version = self.default_request_version
                    self.command = ""
                    self.send_error(414)
                    self.wfile.flush()
                    return _CLOSE
                if not self.parse_request():
                    self.wfile.flush()
                    return _CLOSE
                if not hasattr(self, "do_" + self.command):
                    self.send_error(
                        501, f"Unsupported method ({self.command!r})")
                    self.wfile.flush()
                    return _CLOSE
                self._dispatch()
                self.wfile.flush()
                if self.close_connection:
                    return _CLOSE
                if not self._pending():
                    return _PARK
        except (TimeoutError, socket.timeout, ConnectionError):
            return _CLOSE
        except OSError:
            return _CLOSE
        except Exception as e:
            # parity with socketserver.handle_error, minus the spew for
            # severed connections
            glog.exception("connection handler error: %s",
                           type(e).__name__)
            return _CLOSE

    def _pending(self) -> bool:
        """True when another request's bytes are already available:
        buffered in user space (pipelined), buffered inside the TLS
        record layer, or readable on the socket. Parking such a
        connection would never wake the selector for it."""
        if self.rfile.has_buffered():
            return True
        try:
            pending = getattr(self.connection, "pending", None)
            if pending is not None and pending():
                return True
            return _fd_readable(self.connection)
        except (OSError, ValueError):
            return True  # let the read loop surface the error

    def handle_expect_100(self):
        ok = super().handle_expect_100()
        try:
            self.wfile.flush()  # interim 100 must hit the wire NOW
        except OSError:
            return False
        return ok

    def shed_busy(self, retry_after: float = 1.0) -> None:
        """Best-effort canned 503 when the worker queue is full. Runs
        on the selector thread, so it must never block: one
        non-blocking send, then close."""
        try:
            self.connection.setblocking(False)
            msg = ("HTTP/1.1 503 Service Unavailable\r\n"
                   "Content-Type: application/json\r\n"
                   f"Content-Length: {len(_BUSY_BODY)}\r\n"
                   f"Retry-After: {retry_after:g}\r\n"
                   "Connection: close\r\n\r\n").encode("latin-1")
            self.connection.send(msg + _BUSY_BODY)
        except OSError:
            pass
        self.close_conn()

    def close_conn(self) -> None:
        try:
            self.wfile.close()
        except OSError:
            pass
        try:
            self.connection.shutdown(socket.SHUT_WR)
        except OSError:
            pass
        try:
            self.connection.close()
        except OSError:
            pass

    # ------------------------------------------------ request handling

    def parse_request(self) -> bool:
        """Minimal HTTP/1.1 request parse replacing the stdlib
        email-parser path (which dominates per-request CPU on
        the 1KB data path). Sets the same attributes the base
        class would: command/path/request_version/headers/
        close_connection, incl. Expect: 100-continue."""
        self.command = None
        self.request_version = version = "HTTP/0.9"
        self.close_connection = True
        raw = str(self.raw_requestline, "latin-1").rstrip("\r\n")
        self.requestline = raw
        parts = raw.split()
        if len(parts) == 3:
            command, path, version = parts
            if not version.startswith("HTTP/"):
                self.send_error(400,
                                f"Bad request version {version!r}")
                return False
        elif len(parts) == 2:
            command, path = parts
        else:
            self.send_error(400, f"Bad request syntax {raw!r}")
            return False
        self.command, self.path = command, path
        self.request_version = version
        headers = HeaderDict()
        n_headers = 0
        while True:
            line = self.rfile.readline(65537)
            if len(line) > 65536:
                self.send_error(431, "header line too long")
                return False
            if line in (b"\r\n", b"\n", b"", b"\r"):
                break
            n_headers += 1
            if n_headers > 100:  # stdlib _MAXHEADERS parity
                self.send_error(431, "too many headers")
                return False
            k, sep, v = line.decode("latin-1").partition(":")
            if sep:
                headers.add(k.strip(), v.strip())
        self.headers = headers
        conn = (headers.get("Connection") or "").lower()
        if version >= "HTTP/1.1":
            self.close_connection = conn == "close"
        else:
            self.close_connection = conn != "keep-alive"
        if version >= "HTTP/1.1" and \
                headers.get("Expect", "").lower() == "100-continue":
            if not self.handle_expect_100():
                return False
        return True

    def _reject(self, verdict, length):
        # reject WITHOUT buffering the body: drain it in
        # discarded 64KB chunks (bounded memory) so the
        # client finishes sending and can actually read
        # the 413/429/503; truly huge payloads are cut off
        # after a few MB like Go's http server does
        remaining = min(length, 8 << 20)
        try:
            while remaining > 0:
                got = self.rfile.read(min(remaining, 65536))
                if not got:
                    break
                remaining -= len(got)
        except OSError:
            pass
        verdict.headers.setdefault("Connection", "close")
        self.close_connection = True
        self._send(verdict)

    def _dispatch(self):
        server = self.srv
        length = int(self.headers.get("Content-Length") or 0)
        if server.draining:
            # a draining server takes no NEW work; kept-alive
            # clients get a clean 503 + close so their retry
            # lands on another replica immediately
            self._reject(Response(
                {"error": "draining"}, status=503,
                headers={"Retry-After": "1"}), length)
            return
        with server._inflight_lock:
            server._inflight += 1
        try:
            self._dispatch_traced(length)
        finally:
            with server._inflight_lock:
                server._inflight -= 1

    def _dispatch_traced(self, length):
        server = self.srv
        path = urllib.parse.unquote(
            urllib.parse.urlparse(self.path).path)
        # server span: continue an inbound X-Weed-Trace or mint
        # a fresh trace at this edge. Ambient BEFORE the gates
        # so QoS verdicts annotate it, and around the handler so
        # nested http_calls inject the header downstream. With
        # no tracer (or disabled) this is one attribute check
        # plus the shared NOOP span — no allocation.
        tracer = server.tracer
        span = (tracer.server_span(f"{self.command} {path}",
                                   self.headers)
                if tracer is not None else tracing.NOOP)
        tok = tracing.attach(span)
        try:
            self._dispatch_inner(path, length, span)
        finally:
            tracing.detach(tok)

    def _dispatch_inner(self, path, length, span):
        server = self.srv
        fam = route_family(path)
        eff_cls = qos_classes.from_headers(self.headers) \
            or qos_classes.classify(self.command, path)
        # continuous-profiling scope: the wall sampler attributes this
        # thread's stacks to (class, route) while the request runs.
        # With no sampler active tag() is one global check.
        ptok = profiler.tag(eff_cls, fam,
                            span.trace_id if span.sampled else None)
        ledger = server.ledger
        t_cpu = clockctl.thread_time() if ledger is not None else 0.0
        status, bytes_in, bytes_out = 500, 0, 0
        try:
            status, bytes_in, bytes_out = self._dispatch_gated(
                path, length, span, fam, eff_cls)
        finally:
            profiler.untag(ptok)
            if ledger is not None:
                # the handler ran on THIS thread, so the per-thread
                # CPU clock delta is exactly the request's burn
                tenant = (server.tenant_fn(self.headers,
                                           self.client_address[0])
                          if server.tenant_fn is not None
                          else self.client_address[0])
                ledger.observe_request(
                    eff_cls, tenant,
                    cpu_s=clockctl.thread_time() - t_cpu,
                    bytes_in=bytes_in, bytes_out=bytes_out)

    def _dispatch_gated(self, path, length, span, fam, eff_cls):
        server = self.srv
        # RED edge observation brackets EVERYTHING — admission
        # sheds, gate rejects, 404s, handler 500s — so the
        # duration histogram is the true edge view. clockctl
        # timing: under the sim's virtual clock the same
        # histograms elapse in virtual seconds.
        t_red = clockctl.monotonic()
        red = server.red

        def red_observe(status):
            if red is None:
                return
            red.observe(fam, eff_cls, status,
                        clockctl.monotonic() - t_red,
                        exemplar=span.trace_id
                        if span.sampled else None)

        release = None
        agate = server.admission_gate
        if agate is not None:
            verdict = agate(self.command, path, self.headers,
                            self.client_address[0])
            if isinstance(verdict, Response):
                self._reject(verdict, length)
                red_observe(verdict.status)
                span.finish(status=verdict.status)
                return verdict.status, 0, 0
            release = verdict
        on_sent = None
        resp = None
        stream = None
        out_status = 500
        t0 = clockctl.monotonic()
        try:
            gate = server.body_gate
            if gate is not None and length and \
                    self.command in ("POST", "PUT"):
                verdict = gate(path, length)
                if isinstance(verdict, Response):
                    out_status = verdict.status
                    self._reject(verdict, length)
                    return out_status, 0, 0
                on_sent = verdict
            # the body stays ON THE WIRE until the handler asks for
            # it: streaming handlers pull req.stream a chunk at a
            # time (bounded memory regardless of object size), the
            # rest materialize lazily via req.body
            chunked = "chunked" in (
                self.headers.get("Transfer-Encoding") or "").lower()
            stream = BodyStream(self.rfile, length, chunked)
            # the effective class (propagated header, else edge
            # classification) becomes ambient for the handler, so
            # nested http_calls re-inject it and ledger disk charges
            # land in the same (class, tenant) row as the request
            for method, pattern, fn in server.routes:
                if method != self.command:
                    continue
                m = pattern.match(path)
                if m:
                    try:
                        with qos_classes.class_scope(eff_cls):
                            resp = fn(Request(self, m, stream=stream))
                    except Exception as e:  # surface as 500 JSON
                        glog.exception(
                            "handler error: %s %s -> %s",
                            self.command, path,
                            type(e).__name__)
                        resp = Response(
                            {"error": f"{type(e).__name__}: {e}"},
                            status=500)
                    break
            else:
                resp = Response({"error": "not found"}, status=404)
            # keep-alive framing: whatever body the handler left
            # unread must come off the wire before the next request
            # can parse; a broken or oversized remainder closes
            if not stream.exhausted and not stream.drain():
                resp.headers.setdefault("Connection", "close")
                self.close_connection = True
            out_status = resp.status
            self._send(resp)
            glog.vlog(2, "%s %s %d %dB %.1fms",
                      self.command, self.path, resp.status,
                      len(resp.body),
                      (clockctl.monotonic() - t0) * 1e3)
        finally:
            if on_sent is not None:
                on_sent()
            cb = getattr(resp, "on_sent", None)
            if cb is not None:
                cb()
            if release is not None:
                release()
            red_observe(out_status)
            span.finish(status=out_status)
        return (out_status,
                stream.consumed if stream is not None else 0,
                len(resp.body) if resp is not None else 0)

    def _send(self, resp):
        body = resp.body
        try:
            self.send_response(resp.status)
            self.send_header("Content-Type", resp.content_type)
            if "Content-Length" not in resp.headers:
                # HEAD handlers set it to the entity size; the
                # wire body is still suppressed below
                self.send_header("Content-Length",
                                 str(len(body)))
            for k, v in resp.headers.items():
                self.send_header(k, v)
            self.end_headers()
            if self.command == "HEAD":
                return
            if isinstance(body, FileSlice):
                self._send_file_slice(body)
            else:
                self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass
        finally:
            if isinstance(body, FileSlice):
                body.close()

    # pread granularity for the buffered descriptor fallback
    _FILE_CHUNK = 1 << 20

    def _send_file_slice(self, fs: FileSlice) -> None:
        """Payload of a descriptor response. The headers are sitting in
        wfile's buffer: flush them, then hand the file window to
        ``os.sendfile`` so the kernel streams page-cache pages to the
        socket with zero userspace copies. A short write (EAGAIN — the
        fd is non-blocking under the service() socket timeout) parks on
        writability for the same io_timeout budget and resumes at the
        short-write offset; sendfile with an explicit offset never
        moves the fd position, so concurrent descriptor sends off one
        volume fd don't interfere. TLS connections (payload must cross
        the record layer), non-regular files, and platforms without
        os.sendfile take the buffered pread loop instead."""
        if fs.count <= 0:
            return
        use_sendfile = (hasattr(os, "sendfile")
                        and getattr(self.connection, "pending",
                                    None) is None)
        if use_sendfile:
            try:
                if not stat.S_ISREG(os.fstat(fs.fd).st_mode):
                    use_sendfile = False
            except OSError:
                use_sendfile = False
        if not use_sendfile:
            self._send_file_buffered(fs)
            return
        self.wfile.flush()  # response head precedes the payload
        off, end = fs.offset, fs.offset + fs.count
        timeout = self.connection.gettimeout()
        while off < end:
            try:
                sent = os.sendfile(self.connection.fileno(), fs.fd,
                                   off, end - off)
            except BlockingIOError:
                if not _fd_writable(self.connection, timeout):
                    raise socket.timeout(
                        "sendfile: send buffer stayed full past "
                        "io_timeout")
                continue
            except OSError:
                if off == fs.offset:
                    # first call refused (EINVAL/ENOTSOCK class):
                    # this transport can't sendfile — buffered loop
                    self._send_file_buffered(fs)
                    return
                raise  # mid-payload failure: framing is unrecoverable
            if sent == 0:
                raise ConnectionError("sendfile: peer gone mid-file")
            off += sent

    def _send_file_buffered(self, fs: FileSlice) -> None:
        off, end = fs.offset, fs.offset + fs.count
        while off < end:
            piece = os.pread(fs.fd, min(self._FILE_CHUNK, end - off),
                             off)
            if not piece:
                # under-delivering Content-Length corrupts framing —
                # close the connection rather than serve a truncation
                raise OSError(
                    f"descriptor read hit EOF at {off}, "
                    f"{end - off} bytes short")
            self.wfile.write(piece)
            off += len(piece)

    do_GET = do_POST = do_PUT = do_DELETE = do_HEAD = _dispatch
    # WebDAV verbs
    do_OPTIONS = do_PROPFIND = do_PROPPATCH = _dispatch
    do_MKCOL = do_MOVE = do_COPY = do_LOCK = do_UNLOCK = _dispatch


class _WorkerPool:
    """Bounded, demand-grown request worker pool. Threads spawn only
    when a task arrives and no worker is idle, and exit after sitting
    idle — a node serving six HttpServers doesn't pay six full pools.
    submit() never blocks: a full queue returns False and the caller
    sheds (the selector thread must stay responsive)."""

    def __init__(self, max_workers: int, queue_depth: int,
                 idle_exit: float = 10.0):
        self.max_workers = max(1, int(max_workers))
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, queue_depth))
        self._idle_exit = idle_exit
        self._lock = threading.Lock()
        self._threads = 0
        self._idle = 0
        self._stopping = False

    def submit(self, fn) -> bool:
        try:
            self._q.put_nowait(fn)
        except queue.Full:
            return False
        spawn = False
        with self._lock:
            if not self._stopping and self._idle == 0 \
                    and self._threads < self.max_workers:
                self._threads += 1
                spawn = True
        if spawn:
            threading.Thread(target=self._work, daemon=True,
                             name="httpd-worker").start()
        return True

    def _work(self):
        while True:
            with self._lock:
                self._idle += 1
            try:
                fn = self._q.get(timeout=self._idle_exit)
            except queue.Empty:
                try:  # one last sweep before shrinking away
                    fn = self._q.get_nowait()
                except queue.Empty:
                    fn = None
            finally:
                with self._lock:
                    self._idle -= 1
            if fn is None or self._stopping:
                break
            try:
                fn()
            except Exception:
                glog.exception("httpd worker task error")
        respawn = False
        with self._lock:
            self._threads -= 1
            # a task enqueued during our shutdown window must not
            # strand until the next submit
            if not self._stopping and not self._q.empty() \
                    and self._idle == 0 \
                    and self._threads < self.max_workers:
                self._threads += 1
                respawn = True
        if respawn:
            threading.Thread(target=self._work, daemon=True,
                             name="httpd-worker").start()

    def stats(self) -> dict:
        with self._lock:
            return {"threads": self._threads, "idle": self._idle,
                    "queued": self._q.qsize(),
                    "max_workers": self.max_workers}

    def stop(self):
        self._stopping = True
        for _ in range(self.max_workers):
            try:
                self._q.put_nowait(None)
            except queue.Full:
                break


# selector registration tags for the two non-connection fds
_ACCEPT = object()
_WAKE = object()


class _SelectorCore:
    """The connection core: one thread multiplexing the listener +
    every parked keep-alive socket through a selector; request
    servicing happens on the bounded worker pool. Exposes ``.socket``
    (tls.wrap_http_server swaps it for an SSLSocket in place — same
    fd, so the selector registration survives) and ``server_address``
    for ThreadingHTTPServer drop-in parity."""

    def __init__(self, srv: "HttpServer", host: str, port: int,
                 workers: int, queue_depth: int):
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind((host, port))
        sock.listen(1024)
        sock.setblocking(False)
        self.socket = sock
        self.server_address = sock.getsockname()
        self.srv = srv
        self._sel = selectors.DefaultSelector()
        # register the raw fd, not the socket object: a later TLS wrap
        # detaches the fd into a new SSLSocket and the old object goes
        # invalid, but the fd (and this registration) live on
        self._sel.register(sock.fileno(), selectors.EVENT_READ, _ACCEPT)
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, _WAKE)
        self._pool = _WorkerPool(workers, queue_depth)
        self._lock = threading.Lock()
        self._parked: dict = {}          # handler -> parked_at
        self._inbox: collections.deque = collections.deque()
        self._conns: set = set()         # every live handler
        self._accepting = True
        self._running = True
        self._accepted = 0
        self._shed = 0
        self._thread: Optional[threading.Thread] = None

    # ---- lifecycle ---------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="httpd-selector")
        self._thread.start()

    def stop_accepting(self) -> None:
        """Drain phase one: stop taking new connections while the loop
        keeps serving parked ones (their next request gets the 503 +
        close from _dispatch's draining check)."""
        self._accepting = False
        self._wakeup()

    def shutdown(self) -> None:
        self._running = False
        self._wakeup()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._pool.stop()
        with self._lock:
            conns = list(self._conns)
            self._conns.clear()
            self._parked.clear()
        for h in conns:
            try:
                h.connection.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            h.close_conn()
        try:
            self.socket.close()
        except OSError:
            pass
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass
        self._sel.close()

    def _wakeup(self) -> None:
        try:
            self._wake_w.send(b"x")
        except (BlockingIOError, OSError):
            pass  # a pending wake byte already does the job

    # ---- stats -------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            out = {"connections": len(self._conns),
                   "parked": len(self._parked),
                   "accepted": self._accepted,
                   "shed_busy": self._shed}
        out.update(self._pool.stats())
        return out

    # ---- selector loop (single thread) -------------------------------

    def _run(self) -> None:
        last_sweep = clockctl.monotonic()
        while self._running:
            try:
                events = self._sel.select(timeout=1.0)
            except OSError:
                continue
            if not self._running:
                break
            for key, _ in events:
                tag = key.data
                if tag is _WAKE:
                    try:
                        while self._wake_r.recv(4096):
                            pass
                    except (BlockingIOError, OSError):
                        pass
                elif tag is _ACCEPT:
                    if self._accepting:
                        self._accept_burst()
                else:  # a parked connection became readable (or EOF'd)
                    h = tag
                    try:
                        self._sel.unregister(key.fileobj)
                    except (KeyError, ValueError, OSError):
                        pass
                    with self._lock:
                        self._parked.pop(h, None)
                    self._submit(h)
            self._drain_inbox()
            now = clockctl.monotonic()
            if now - last_sweep >= 5.0:
                last_sweep = now
                self._sweep_idle(now)

    def _accept_burst(self) -> None:
        for _ in range(128):
            try:
                # via self.socket, not a captured local: tls.py may
                # have swapped in an SSLSocket (handshake-in-accept,
                # same as the threaded server's behavior)
                conn, addr = self.socket.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError as e:
                # TLS handshake failures arrive here (ssl.SSLError is
                # an OSError): that connection is dead, the listener
                # is fine — keep draining the backlog
                if type(e).__name__.startswith("SSL"):
                    continue
                glog.vlog(1, "accept error: %s", e)
                return
            try:
                conn.setblocking(True)
            except OSError:
                continue
            self._accepted += 1
            h = _ConnHandler(conn, addr, self.srv)
            with self._lock:
                self._conns.add(h)
            self._submit(h)

    def _submit(self, h) -> None:
        if self._pool.submit(lambda: self._service(h)):
            return
        # worker queue saturated: canned 503 + close, never blocking
        # the selector thread. Retry-After stretches with governor
        # pressure so clients back off harder the hotter we run.
        self._shed += 1
        gov = self.srv.governor
        retry = 1.0
        if gov is not None:
            try:
                retry = round(0.5 + 2.0 * gov.pressure(), 1)
            except Exception:
                pass
        h.shed_busy(retry)
        with self._lock:
            self._conns.discard(h)

    def _service(self, h) -> None:
        outcome = h.service()
        if outcome == _PARK and self._running:
            with self._lock:
                self._inbox.append(h)
            self._wakeup()
        else:
            h.close_conn()
            with self._lock:
                self._conns.discard(h)

    def _drain_inbox(self) -> None:
        while True:
            with self._lock:
                if not self._inbox:
                    return
                h = self._inbox.popleft()
            if not self._running:
                h.close_conn()
                with self._lock:
                    self._conns.discard(h)
                continue
            try:
                self._sel.register(h.connection, selectors.EVENT_READ, h)
            except (KeyError, ValueError, OSError):
                h.close_conn()
                with self._lock:
                    self._conns.discard(h)
                continue
            with self._lock:
                self._parked[h] = clockctl.monotonic()

    def _sweep_idle(self, now: float) -> None:
        timeout = self.srv.idle_timeout
        with self._lock:
            stale = [h for h, t in self._parked.items()
                     if now - t > timeout]
            for h in stale:
                self._parked.pop(h, None)
                self._conns.discard(h)
        for h in stale:
            try:
                self._sel.unregister(h.connection)
            except (KeyError, ValueError, OSError):
                pass
            h.close_conn()


class HttpServer:
    """Route table + selector connection core. Routes are
    (METHOD, regex); see the module docstring for the serving model."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 workers: Optional[int] = None, queue_depth: int = 2048,
                 idle_timeout: float = 75.0, io_timeout: float = 60.0):
        self.routes: list[Route] = []
        self.host = host
        self.port = port
        # worker-pool knobs: `workers` bounds service threads (None ->
        # sized at start(), QoS-aware when a governor is wired);
        # `queue_depth` bounds dispatch backlog before canned-503 shed;
        # `idle_timeout` reaps parked keep-alive sockets; `io_timeout`
        # bounds per-syscall progress on an ACTIVE request (parked
        # sockets carry no timeout — the selector owns their idleness).
        self.workers = workers
        self.queue_depth = queue_depth
        self.idle_timeout = idle_timeout
        self.io_timeout = io_timeout
        # QosGovernor wired by the owning server (like tracer/red):
        # sizes the worker pool and shapes shed Retry-After hints
        self.governor = None
        self._httpd: Optional[_SelectorCore] = None
        self._thread: Optional[threading.Thread] = None
        # body_gate(path, content_length) is consulted BEFORE the request
        # body is read from the socket: it returns a Response to reject
        # the request unread (413/429 load shedding), a callable to be
        # invoked once the response is fully sent (in-flight byte
        # accounting), or None to proceed unthrottled (reference
        # weed/server/volume_server_handlers.go inFlight*DataLimitCond).
        self.body_gate = None
        # admission_gate(method, path, headers, client_ip) runs first,
        # for EVERY method: the QoS governor's hook. Same verdict
        # contract as body_gate — a Response sheds the request (503 +
        # Retry-After) before its body is buffered, a callable releases
        # the admission slot once the response is fully sent, None
        # passes. See seaweedfs_tpu/qos/governor.py.
        self.admission_gate = None
        # tracing.Tracer wired by the owning server: _dispatch mints a
        # server span per request (continuing an inbound X-Weed-Trace)
        # and records it into the node's flight recorder. None -> the
        # shared NOOP span, zero allocation.
        self.tracer = None
        # metrics.RedRecorder wired by the owning server: ONE
        # observation site covers every edge's rate/errors/duration,
        # including requests the gates shed. None -> one attribute
        # check per request.
        self.red = None
        # stats.ledger.ResourceLedger wired by the owning server: the
        # dispatch bracket bills each request's thread-CPU delta and
        # wire bytes to (class, tenant). None -> one attribute check.
        self.ledger = None
        # tenant_fn(headers, client_ip) -> str names the ledger row's
        # tenant; None -> client ip (the filer/volume tier's identity;
        # the S3 gateway overrides with the request's access key).
        self.tenant_fn = None
        # graceful-drain state: once draining, new requests (including
        # ones arriving on kept-alive connections) are answered 503 +
        # Connection: close while in-flight requests run to completion;
        # drain() waits on the in-flight counter.
        self.draining = False
        self._inflight = 0
        self._inflight_lock = threading.Lock()

    def route(self, method: str, pattern: str):
        compiled = re.compile("^" + pattern + "$")

        def deco(fn):
            self.routes.append((method.upper(), compiled, fn))
            return fn
        return deco

    def add(self, method: str, pattern: str, fn) -> None:
        self.routes.append((method.upper(), re.compile("^" + pattern + "$"),
                            fn))

    def start(self) -> None:
        workers = self.workers
        if workers is None:
            # QoS-aware sizing: with a governor wired, the pool tracks
            # the adaptive limiter's ceiling (every admitted request
            # deserves a thread); without one, a fixed bound
            gov = self.governor
            if gov is not None:
                workers = max(16, min(128, gov.limiter.max_limit))
            else:
                workers = 64
        core = _SelectorCore(self, self.host, self.port,
                             workers=workers, queue_depth=self.queue_depth)
        self._httpd = core
        self.port = core.server_address[1]
        core.start()
        self._thread = core._thread

    def conn_stats(self) -> dict:
        """Connection-core counters for metrics / the conn bench:
        open + parked connections, worker threads, queue depth, busy
        sheds, in-flight requests."""
        core = self._httpd
        out = core.stats() if core is not None else {
            "connections": 0, "parked": 0, "accepted": 0,
            "shed_busy": 0, "threads": 0, "idle": 0, "queued": 0,
            "max_workers": 0}
        with self._inflight_lock:
            out["inflight"] = self._inflight
        return out

    def drain(self, timeout: float = 10.0) -> bool:
        """Graceful-stop phase one: refuse new requests (503 + close),
        stop accepting connections, and wait for in-flight requests to
        finish.  Returns True when the server went idle within
        ``timeout``; the caller then runs stop() for the hard close.
        Idempotent, and safe before start(). Parked keep-alive
        connections stay serviced (their next request gets the 503 +
        Connection: close) until stop() severs them."""
        self.draining = True
        if self._httpd:
            self._httpd.stop_accepting()
        deadline = clockctl.monotonic() + timeout
        while clockctl.monotonic() < deadline:
            with self._inflight_lock:
                if self._inflight == 0:
                    return True
            clockctl.sleep(0.02)
        with self._inflight_lock:
            return self._inflight == 0

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd = None


class RangeNotSatisfiable(Exception):
    """Raise-to-416: the range is well-formed but outside the entity
    (RFC 7233 §4.4; S3 answers InvalidRange). Callers respond 416 with
    'Content-Range: bytes */<total>' — serving a 200 full body instead
    would corrupt resuming downloaders that append the response."""


def parse_byte_range(spec: str, total: int) -> Optional[tuple[int, int]]:
    """RFC 7233 single-range parse: 'bytes=a-b' / 'bytes=a-' /
    'bytes=-n' (suffix: the LAST n bytes). Returns (lo, hi) inclusive;
    None when no/malformed range (serve the full body, per RFC);
    raises RangeNotSatisfiable when lo lies beyond the entity."""
    if not spec or not spec.startswith("bytes="):
        return None
    lo_s, _, hi_s = spec[6:].partition("-")
    try:
        if not lo_s:  # suffix form
            n = int(hi_s)
            if n <= 0:
                return None
            if total == 0:
                # no last-N bytes of an empty entity (AWS: 416)
                raise RangeNotSatisfiable(spec)
            return max(0, total - n), total - 1
        lo = int(lo_s)
        hi = int(hi_s) if hi_s else total - 1
    except ValueError:
        return None
    if lo >= total:
        # beyond EOF — includes the open-ended 'bytes=<past-end>-'
        # form, whose default hi (total-1) is < lo and must not be
        # mistaken for a malformed spec
        raise RangeNotSatisfiable(spec)
    if hi < lo:
        return None
    return lo, min(hi, total - 1)


class HttpError(Exception):
    def __init__(self, status: int, body: bytes,
                 retry_after: Optional[float] = None):
        self.status = status
        self.body = body
        # server-sent pacing hint (429/503): RetryPolicy sleeps this
        # instead of its own computed backoff
        self.retry_after = retry_after
        super().__init__(f"HTTP {status}: {body[:200]!r}")


def retry_after_hint(status: int, resp_headers) -> Optional[float]:
    """Seconds from a Retry-After header on a shed response (429/503
    only — the statuses the limiters emit); None otherwise. Only the
    delta-seconds form is parsed (what this codebase sends); an
    HTTP-date or garbage value degrades to None, not an error."""
    if status not in (429, 503) or not resp_headers:
        return None
    for k, v in resp_headers.items():
        if k.lower() == "retry-after":
            try:
                return max(0.0, float(v))
            except (TypeError, ValueError):
                return None
    return None


# Process-wide keep-alive connection pool (below, after
# RawHttpConnection). The data path makes millions of tiny requests;
# per-request TCP setup/teardown (urllib's behavior) costs more than
# the request itself and floods TIME_WAIT. The reference leans on Go's
# pooled http.Transport the same way (weed/util/http_util.go).


class RawHttpConnection:
    """Minimal pooled HTTP/1.1 client connection. Replaces
    http.client on the hot data path: no email-parser response
    headers, no per-response makefile, one buffered reader for the
    connection's lifetime. Handles Content-Length, chunked and
    read-to-close bodies, keep-alive, and 1xx skipping."""

    def __init__(self, netloc: str, timeout: float):
        self.netloc = netloc
        host, port = netloc, 80
        if netloc.startswith("["):  # IPv6 literal [::1]:8080
            host, _, rest = netloc[1:].partition("]")
            if rest.startswith(":"):
                port = int(rest[1:])
        elif ":" in netloc:
            host, _, p = netloc.rpartition(":")
            port = int(p)
        # weedlint: disable=persistent-socket-timeout — _pooled_conn
        # re-arms settimeout() per request with the caller's deadline
        self.sock = socket.create_connection((host or "127.0.0.1", port),
                                             timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._rfile = self.sock.makefile("rb", buffering=65536)

    def close(self) -> None:
        sock, self.sock = self.sock, None
        if sock is None:
            return  # already closed
        for closer in (self._rfile.close, sock.close):
            try:
                closer()
            except OSError:
                pass

    def _read_exact(self, n: int) -> bytes:
        data = self._rfile.read(n)
        if data is None or len(data) < n:
            raise ConnectionError("short HTTP body")
        return data

    def _read_chunked(self) -> bytes:
        out = bytearray()
        while True:
            size_line = self._rfile.readline(1026)
            if not size_line:
                raise ConnectionError("EOF in chunked body")
            n = int(size_line.split(b";")[0].strip() or b"0", 16)
            if n == 0:
                while self._rfile.readline(65537) not in (b"\r\n", b"\n",
                                                          b""):
                    pass  # discard trailers
                return bytes(out)
            out += self._read_exact(n)
            self._rfile.readline(3)  # chunk CRLF

    def send_request(self, method: str, target: str,
                     body: Optional[bytes],
                     headers: Optional[dict]) -> None:
        buf = [f"{method} {target} HTTP/1.1\r\n"]
        has_len = has_host = False
        for k, v in (headers or {}).items():
            lk = k.lower()
            if lk == "content-length":
                has_len = True
            elif lk == "host":
                has_host = True  # caller-set (SigV4 signs it): no dup
            buf.append(f"{k}: {v}\r\n")
        if not has_host:
            buf.append(f"Host: {self.netloc}\r\n")
        if not has_len and (body or method not in ("GET", "HEAD")):
            buf.append(f"Content-Length: {len(body or b'')}\r\n")
        buf.append("\r\n")
        msg = "".join(buf).encode("latin-1")
        self.sock.sendall(msg + body if body else msg)

    def read_response(self, method: str) -> tuple[int, bytes, dict, bool]:
        """Returns (status, body, headers, will_close)."""
        while True:  # skip 1xx interim responses
            line = self._rfile.readline(65537)
            if not line:
                raise ConnectionError("no HTTP status line")
            parts = line.decode("latin-1").split(None, 2)
            if len(parts) < 2 or not parts[0].startswith("HTTP/"):
                raise ConnectionError(f"bad status line {line!r}")
            version, status = parts[0], int(parts[1])
            resp = HeaderDict()
            n_headers = 0
            while True:
                hl = self._rfile.readline(65537)
                if hl in (b"\r\n", b"\n", b""):
                    break
                n_headers += 1
                if n_headers > 100:  # stdlib _MAXHEADERS parity
                    raise ConnectionError("too many response headers")
                k, sep, v = hl.decode("latin-1").partition(":")
                if sep:
                    resp.add(k.strip(), v.strip())
            if status >= 200:
                break
        conn_hdr = (resp.get("Connection") or "").lower()
        will_close = (conn_hdr == "close"
                      or (version == "HTTP/1.0"
                          and conn_hdr != "keep-alive"))
        te = (resp.get("Transfer-Encoding") or "").lower()
        if method == "HEAD" or status in (204, 304):
            data = b""
        elif "chunked" in te:
            data = self._read_chunked()
        elif resp.get("Content-Length") is not None:
            data = self._read_exact(int(resp["Content-Length"]))
        else:  # body delimited by connection close (HTTP/1.0 style)
            data = self._rfile.read()
            will_close = True
        return status, data, dict(resp.items()), will_close


def _make_conn(netloc: str, timeout: float) -> RawHttpConnection:
    return RawHttpConnection(netloc, timeout)


def _conn_alive(conn: RawHttpConnection) -> bool:
    """Liveness check before reuse (urllib3's is_connection_dropped):
    a peer that closed shows readable-EOF, and sending into it would
    "succeed" into the kernel buffer and only fail at response time —
    un-retryable for non-idempotent methods. This matters when a
    server restarts on a reused port."""
    if conn.sock is None:
        return False
    try:
        readable = _fd_readable(conn.sock)
    except (OSError, ValueError):
        return False
    # EOF or unsolicited bytes: the peer is gone (or the stream is
    # desynced) — not reusable
    return not readable


class HttpConnectionPool:
    """Process-wide keep-alive pool: per-destination bounded idle
    stacks under one lock. Replaces the per-thread pool, whose idle
    socket count scaled with threads x destinations (a filer with 64
    workers kept 64 sockets per volume server alive).

    Checkout/checkin model: acquire() pops a live idle connection (or
    dials), release() parks it back unless the destination stack or
    the global cap is full — overflow closes the NEWLY returned socket
    and a breached global cap also evicts the globally oldest idle one
    (LRU across destinations). Eviction is breaker-aware twice over:
    any transport failure drops the whole destination (its siblings
    share the dead peer), and a circuit breaker opening anywhere in
    the process evicts that peer's idles via resilience's
    on_breaker_open hook."""

    def __init__(self, per_dest: int = 4, max_idle: int = 128,
                 idle_ttl: float = 30.0):
        self.per_dest = per_dest
        self.max_idle = max_idle
        self.idle_ttl = idle_ttl
        self._lock = threading.Lock()
        self._idle: dict[str, list] = {}  # netloc -> [(conn, parked_at)]
        self._total = 0
        self.dials = 0
        self.reuses = 0
        self.evictions = 0

    def acquire(self, netloc: str,
                timeout: float) -> tuple[RawHttpConnection, bool]:
        """Returns (conn, reused): `reused` is True when the socket was
        already open from a previous request — the only case where an
        automatic retry is safe (a stale kept-alive socket fails before
        the server sees anything; a fresh connection that dies
        mid-response may have EXECUTED the request, so replaying it is
        the caller's call)."""
        now = clockctl.monotonic()
        while True:
            with self._lock:
                stack = self._idle.get(netloc)
                if not stack:
                    break
                conn, parked_at = stack.pop()
                if not stack:
                    del self._idle[netloc]
                self._total -= 1
            if now - parked_at > self.idle_ttl or not _conn_alive(conn):
                self.evictions += 1
                conn.close()
                continue
            # weedlint: disable=persistent-socket-timeout — re-armed
            # per request with the caller's deadline-capped timeout
            conn.sock.settimeout(timeout)
            self.reuses += 1
            return conn, True
        self.dials += 1
        return _make_conn(netloc, timeout), False

    def release(self, conn: RawHttpConnection) -> None:
        if conn.sock is None:
            return
        evicted = None
        with self._lock:
            stack = self._idle.get(conn.netloc)
            if stack is not None and len(stack) >= self.per_dest:
                self.evictions += 1
                evicted = conn  # destination stack full: close this one
            else:
                if self._total >= self.max_idle:
                    evicted = self._evict_oldest_locked()
                if stack is None:
                    stack = self._idle.setdefault(conn.netloc, [])
                stack.append((conn, clockctl.monotonic()))
                self._total += 1
        if evicted is not None:
            evicted.close()

    def _evict_oldest_locked(self):
        """Drop the globally least-recently-parked idle connection
        (LRU destination eviction). Caller holds the lock."""
        oldest_key, oldest_i, oldest_t = None, -1, None
        for key, stack in self._idle.items():
            # index 0 is the oldest entry of each destination stack
            t = stack[0][1]
            if oldest_t is None or t < oldest_t:
                oldest_key, oldest_i, oldest_t = key, 0, t
        if oldest_key is None:
            return None
        conn, _ = self._idle[oldest_key].pop(oldest_i)
        if not self._idle[oldest_key]:
            del self._idle[oldest_key]
        self._total -= 1
        self.evictions += 1
        return conn

    def drop(self, netloc: str) -> None:
        """Evict every idle connection to `netloc` — called on any
        transport failure and when the peer's breaker opens (the
        siblings ride the same dead peer)."""
        with self._lock:
            stack = self._idle.pop(netloc, None)
            if stack:
                self._total -= len(stack)
                self.evictions += len(stack)
        for conn, _ in stack or ():
            conn.close()

    def stats(self) -> dict:
        with self._lock:
            return {"idle": self._total,
                    "destinations": len(self._idle),
                    "dials": self.dials, "reuses": self.reuses,
                    "evictions": self.evictions}


_POOL = HttpConnectionPool()


def _breaker_evict(peer: str) -> None:
    # peer keys are 'ip:port' or a full URL; the pool keys by netloc
    _POOL.drop(urllib.parse.urlsplit(peer).netloc
               if "//" in peer else peer)


resilience.on_breaker_open(_breaker_evict)


def _pooled_conn(netloc: str, timeout: float):
    return _POOL.acquire(netloc, timeout)


def _drop_conn(netloc: str) -> None:
    _POOL.drop(netloc)


def http_call(method: str, url: str, body: Optional[bytes] = None,
              json_body: Any = None, timeout: float = 30.0,
              headers: Optional[dict] = None, deadline=None,
              follow_redirects: bool = True) -> tuple[int, bytes, dict]:
    # Trace propagation: when a trace is ambient, this outbound RPC
    # becomes a client child span and its ids ride X-Weed-Trace so the
    # callee's server span nests under it. No ambient trace (or tracing
    # disabled) costs one ContextVar read — no span allocation.
    amb = tracing.current_span()
    if amb is None:
        return _http_call_following(method, url, body, json_body,
                                    timeout, headers, deadline,
                                    follow_redirects)
    span = amb.child(f"{method.upper()} {url.split('?', 1)[0]}")
    headers = dict(headers or {})
    headers.setdefault(tracing.TRACE_HEADER, span.header_value())
    status, err = 0, ""
    try:
        out = _http_call_following(method, url, body, json_body,
                                   timeout, headers, deadline,
                                   follow_redirects)
        status = out[0]
        return out
    except BaseException as e:
        status, err = 599, f"{type(e).__name__}: {e}"
        raise
    finally:
        span.finish(status=status, error=err)


# Data-plane redirects (the filer/S3 read path answers eligible GETs
# with a 302 volume-direct URL) are followed transparently for safe
# methods, re-sending the original headers (Range, class, deadline) at
# the target. 307 is deliberately NOT in this set: that status is the
# filer namespace-shard redirect protocol, consumed by
# wdclient.filer_call with its own ring-epoch bookkeeping.
_REDIRECT_STATUSES = (301, 302, 303)
_MAX_REDIRECT_HOPS = 4


def _http_call_following(method, url, body, json_body, timeout,
                         headers, deadline,
                         follow: bool) -> tuple[int, bytes, dict]:
    out = _http_call_impl(method, url, body, json_body, timeout,
                          headers, deadline)
    if not follow or method.upper() not in ("GET", "HEAD"):
        return out
    hops = 0
    while out[0] in _REDIRECT_STATUSES and hops < _MAX_REDIRECT_HOPS:
        loc = next((v for k, v in out[2].items()
                    if k.lower() == "location"), None)
        if not loc:
            break
        url = urllib.parse.urljoin(url, loc)
        out = _http_call_impl(method, url, None, None, timeout,
                              headers, deadline)
        hops += 1
    return out


def _http_call_impl(method: str, url: str, body: Optional[bytes] = None,
                    json_body: Any = None, timeout: float = 30.0,
                    headers: Optional[dict] = None,
                    deadline=None) -> tuple[int, bytes, dict]:
    # Deadline propagation: `timeout` becomes a CAP under the caller's
    # remaining budget (explicit `deadline` arg, else the ambient
    # request-scope one), and the remaining seconds ride along in the
    # X-Weed-Deadline header so the callee inherits the same budget.
    # An already-expired deadline raises DeadlineExceeded (a
    # ConnectionError) before any bytes hit the wire.
    if deadline is None:
        deadline = resilience.current_deadline()
    if deadline is not None:
        timeout = deadline.timeout(cap=timeout)
        headers = dict(headers or {})
        headers.setdefault(resilience.DEADLINE_HEADER,
                           deadline.header_value())
    # traffic class rides along exactly like the deadline: ambient
    # scope -> X-Weed-Class header -> callee re-enters the scope
    cls = qos_classes.current_class()
    if cls is not None:
        headers = dict(headers or {})
        headers.setdefault(qos_classes.CLASS_HEADER, cls)
    if json_body is not None:
        body = json.dumps(json_body).encode()
        headers = dict(headers or {})
        headers["Content-Type"] = "application/json"
    parsed = urllib.parse.urlsplit(url)
    if parsed.scheme == "https":  # rare path: no pooling, plain urllib
        req = urllib.request.Request(url, data=body, method=method.upper(),
                                     headers=headers or {})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, r.read(), dict(r.headers)
        except urllib.error.HTTPError as e:
            return e.code, e.read(), dict(e.headers)
        except (urllib.error.URLError, socket.timeout, ConnectionError) as e:
            raise ConnectionError(f"{method} {url}: {e}") from e
    target = parsed.path or "/"
    if parsed.query:
        target += "?" + parsed.query
    method = method.upper()
    last_err = None
    for attempt in (0, 1):
        sent = False
        reused = False
        conn = None
        try:
            # inside the try: connection setup itself can raise
            # (SYN timeout, DNS failure, bad netloc) and must surface
            # as ConnectionError like every other transport failure
            conn, reused = _POOL.acquire(parsed.netloc, timeout)
            conn.send_request(method, target, body, headers)
            sent = True
            status, data, resp_headers, will_close = \
                conn.read_response(method)
            if will_close:
                conn.close()
            else:
                _POOL.release(conn)
            return status, data, resp_headers
        except (BrokenPipeError, ConnectionResetError,
                ConnectionRefusedError, ConnectionAbortedError,
                ConnectionError, socket.timeout, ValueError,
                OSError) as e:
            if conn is not None:
                conn.close()
            # the destination's idle siblings share the dead peer
            _POOL.drop(parsed.netloc)
            last_err = e
            # Replay rules (Go http.Transport's): only on a REUSED
            # kept-alive socket, and only when the request either
            # failed during SEND (server closed it idle; it never
            # executed) or is idempotent (GET/HEAD). A non-idempotent
            # POST that died mid-response may have executed — surface
            # the error rather than silently running it twice.
            idempotent = method in ("GET", "HEAD")
            if not reused or (sent and not idempotent) or \
                    isinstance(e, (ConnectionRefusedError,
                                   socket.timeout)):
                break
    raise ConnectionError(f"{method} {url}: {last_err}") from last_err


def http_json(method: str, url: str, json_body: Any = None,
              timeout: float = 30.0, deadline=None) -> Any:
    status, body, resp_headers = http_call(method, url, json_body=json_body,
                                           timeout=timeout,
                                           deadline=deadline)
    if status >= 400:
        raise HttpError(status, body,
                        retry_after=retry_after_hint(status, resp_headers))
    return json.loads(body) if body else None
