"""Leveled logging — the glog analogue.

Functional equivalent of reference weed/glog/glog.go:1 (vendored google
glog: severities, -v verbosity, -vmodule per-module gating, size-based
log-file rotation). API mirrors the call sites the reference uses:

    from seaweedfs_tpu.utils import glog
    glog.info("volume %d mounted", vid)
    glog.warningf("slow peer %s", addr)        # *f aliases, go-style
    glog.error("read failed: %s", err)
    if glog.v(2):                               # guarded verbose path
        glog.info("raw request %r", payload)

Severity lines always reach stderr (and the rotating file when
configured); v-level lines print only when `-v` (or a -vmodule
override for the calling module) admits them. Line format matches
glog: `I0730 14:03:02.123456 140395 file.py:42] message`.
"""

from __future__ import annotations

import os
import re
import sys
import threading
import time
from typing import Optional

INFO, WARNING, ERROR, FATAL = 0, 1, 2, 3
_SEV_CHAR = "IWEF"

_lock = threading.Lock()
_verbosity = 0
_vmodule: dict[str, int] = {}
_log_file: Optional["_RotatingFile"] = None
_also_stderr = True
_context_provider = None  # e.g. tracing's "[t=abcd1234] " prefix hook
MAX_SIZE = 64 << 20  # rotation threshold, reference glog.MaxSize


class _RotatingFile:
    def __init__(self, path: str, max_bytes: int):
        self.path = path
        self.max_bytes = max_bytes
        self._fh = open(path, "a", buffering=1)

    def write(self, line: str) -> None:
        self._fh.write(line)
        if self._fh.tell() >= self.max_bytes:
            self._rotate()

    def _rotate(self) -> None:
        self._fh.close()
        stamp = time.strftime("%Y%m%d-%H%M%S")
        rotated = f"{self.path}.{stamp}"
        try:
            os.replace(self.path, rotated)
        except OSError:
            pass
        self._fh = open(self.path, "a", buffering=1)

    def close(self) -> None:
        try:
            self._fh.close()
        except OSError:
            pass


# ---- configuration (the -v / -vmodule / -logdir flag surface) ----

def set_verbosity(level: int) -> None:
    global _verbosity
    _verbosity = int(level)


def set_vmodule(spec: str) -> None:
    """Per-module verbosity overrides: "volume_server=3,master=1"
    (reference glog -vmodule; patterns may use * wildcards)."""
    global _vmodule
    parsed = {}
    for part in (spec or "").split(","):
        if not part.strip():
            continue
        mod, _, lvl = part.partition("=")
        parsed[mod.strip()] = int(lvl or 0)
    with _lock:
        _vmodule = parsed


def set_log_file(path: str, max_bytes: int = MAX_SIZE,
                 also_stderr: bool = True) -> None:
    global _log_file, _also_stderr
    with _lock:
        if _log_file is not None:
            _log_file.close()
        _log_file = _RotatingFile(path, max_bytes)
        _also_stderr = also_stderr


def set_context_provider(fn) -> None:
    """Register a callable returning a per-line prefix (e.g. the active
    trace id) inserted between the glog head and the message. Must be
    cheap and return "" when it has nothing to add; any exception it
    raises is swallowed. Survives reset(): the provider is ambient
    wiring (tracing installs it at import), not test-local state."""
    global _context_provider
    _context_provider = fn


def reset() -> None:
    """Back to defaults (tests)."""
    global _log_file, _verbosity, _vmodule, _also_stderr
    with _lock:
        if _log_file is not None:
            _log_file.close()
        _log_file = None
    _verbosity = 0
    _vmodule = {}
    _also_stderr = True


# ---- emit ----

def _caller(depth: int) -> tuple[str, int]:
    frame = sys._getframe(depth)
    return os.path.basename(frame.f_code.co_filename), frame.f_lineno


def _fmt(msg: str, args: tuple) -> str:
    if not args:
        return msg
    try:
        return msg % args
    except (TypeError, ValueError):
        return f"{msg} {args!r}"


def _emit(sev: int, depth: int, msg: str, args: tuple) -> None:
    msg = _fmt(msg, args)
    fname, lineno = _caller(depth)
    now = time.time()
    frac = int((now % 1) * 1e6)
    head = (f"{_SEV_CHAR[sev]}"
            f"{time.strftime('%m%d %H:%M:%S', time.localtime(now))}"
            f".{frac:06d} {threading.get_native_id():>6d} "
            f"{fname}:{lineno}] ")
    if _context_provider is not None:
        try:
            head += _context_provider()
        except Exception:
            pass
    line = head + msg + "\n"
    with _lock:
        if _log_file is not None:
            try:
                _log_file.write(line)
            except OSError:
                pass
        if _log_file is None or _also_stderr:
            try:
                sys.stderr.write(line)
            except (OSError, ValueError):
                pass


def info(msg: str, *args) -> None:
    _emit(INFO, 3, msg, args)


def warning(msg: str, *args) -> None:
    _emit(WARNING, 3, msg, args)


def error(msg: str, *args) -> None:
    _emit(ERROR, 3, msg, args)


def fatal(msg: str, *args) -> None:
    """Log at FATAL and raise (the Go original exits the process; a
    library raise keeps tests and embedded servers controllable)."""
    _emit(FATAL, 3, msg, args)
    raise SystemExit(_fmt(msg, args))


def exception(msg: str, *args) -> None:
    """error() plus the current exception's traceback. Args are
    substituted BEFORE the traceback is appended — tracebacks routinely
    contain % characters that must not reach the formatter."""
    import traceback
    _emit(ERROR, 3, _fmt(msg, args) + "\n" + traceback.format_exc(), ())


def v(level: int, depth: int = 2) -> bool:
    """True when verbose lines at `level` are admitted for the calling
    module (its -vmodule override wins over the global -v)."""
    if _vmodule:
        fname, _ = _caller(depth)
        mod = fname[:-3] if fname.endswith(".py") else fname
        with _lock:
            for pat, lvl in _vmodule.items():
                if pat == mod or ("*" in pat and re.fullmatch(
                        pat.replace("*", ".*"), mod)):
                    return level <= lvl
    return level <= _verbosity


def vlog(level: int, msg: str, *args) -> None:
    """glog.V(level).Info(...) in one call."""
    if v(level, depth=3):
        _emit(INFO, 3, msg, args)


# go-style *f aliases (the reference writes glog.Infof/Warningf/...)
infof = info
warningf = warning
errorf = error
fatalf = fatal
