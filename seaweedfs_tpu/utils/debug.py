"""Debug/profiling endpoints (reference util/grace/pprof.go +
net/http/pprof wired into every server): thread stack dumps and on-demand
CPU profiles, mounted under /debug/ on our HTTP servers."""

from __future__ import annotations

import cProfile
import io
import pstats
import sys
import threading
import traceback

from seaweedfs_tpu.utils.httpd import HttpServer, Request, Response


def install_debug_routes(http: HttpServer) -> None:
    http.add("GET", "/debug/stacks", _handle_stacks)
    http.add("GET", "/debug/profile", _handle_profile)
    http.add("GET", "/debug/vars", _handle_vars)
    # flight recorder: reads whatever tracer is wired onto this server
    # at request time (servers set http.tracer after construction)
    http.add("GET", "/debug/traces", lambda req: _handle_traces(req, http))


def _handle_traces(req: Request, http: HttpServer) -> Response:
    """Dump the node's span flight recorder. Filters: ?trace=<id>,
    ?min_ms=<float>, ?limit=<n>. tools/trace_collect.py and the
    cluster.trace shell command stitch these across nodes."""
    tracer = http.tracer
    if tracer is None:
        return Response({"enabled": False, "spans": []})
    return Response(tracer.snapshot(
        trace_id=req.query.get("trace", ""),
        min_ms=float(req.query.get("min_ms", 0) or 0),
        limit=int(req.query.get("limit", 512) or 512)))


def _handle_stacks(req: Request) -> Response:
    """All thread stacks (the goroutine-dump analogue)."""
    out = io.StringIO()
    frames = sys._current_frames()
    for t in threading.enumerate():
        out.write(f"--- thread {t.name} (daemon={t.daemon}) ---\n")
        frame = frames.get(t.ident)
        if frame is not None:
            traceback.print_stack(frame, file=out)
        out.write("\n")
    return Response(out.getvalue(), content_type="text/plain")


def _handle_profile(req: Request) -> Response:
    """CPU-profile the process for ?seconds=N (default 2)."""
    seconds = float(req.query.get("seconds", 2))
    prof = cProfile.Profile()
    prof.enable()
    threading.Event().wait(min(seconds, 30))
    prof.disable()
    out = io.StringIO()
    pstats.Stats(prof, stream=out).sort_stats("cumulative").print_stats(50)
    return Response(out.getvalue(), content_type="text/plain")


def _handle_vars(req: Request) -> Response:
    import gc
    return Response({
        "threads": len(threading.enumerate()),
        "gc_counts": gc.get_count(),
    })
