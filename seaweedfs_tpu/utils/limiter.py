"""Byte-accounted in-flight transfer limiter.

Redesign of reference weed/server/volume_server.go:23-30
(inFlightUploadDataSize / inFlightDownloadDataSize + their sync.Cond
backpressure, applied in volume_server_handlers.go): concurrent
request payload bytes are accounted against a cap; a request that
would exceed it waits until others drain, up to a timeout, after
which the caller sheds load (HTTP 429)."""

from __future__ import annotations

import threading

from seaweedfs_tpu.utils import clockctl


class TokenBucket:
    """Bytes/sec token bucket for background work (the scrubber's read
    throttle). rate <= 0 means unlimited, matching InFlightLimiter.

    The bucket starts EMPTY (initial=0) so a consumer of T total bytes
    is guaranteed to take >= T/rate seconds — the property the scrub
    rate-limit contract is stated in — instead of getting a free burst
    up front. A request larger than the capacity is allowed to drive
    the balance negative (debt), which later consumers pay off, so the
    long-run rate still holds for any chunk size."""

    def __init__(self, rate_bytes_per_sec: float, capacity: float = None,
                 initial: float = 0.0):
        self.rate = float(rate_bytes_per_sec)
        self.capacity = float(capacity if capacity is not None
                              else max(self.rate, 1.0))
        self._tokens = float(initial)
        self._ts = clockctl.monotonic()
        self._lock = threading.Lock()

    def set_rate(self, rate_bytes_per_sec: float) -> None:
        with self._lock:
            self._refill()
            self.rate = float(rate_bytes_per_sec)

    def _refill(self) -> None:
        now = clockctl.monotonic()
        if self.rate > 0:
            self._tokens = min(self.capacity,
                               self._tokens + (now - self._ts) * self.rate)
        self._ts = now

    def peek(self) -> float:
        """Current token balance (bytes) after refill; negative when in
        debt. Status/observability only — does not take tokens."""
        with self._lock:
            self._refill()
            return self._tokens

    def consume(self, n: int, stop: "threading.Event" = None) -> bool:
        """Block until n tokens are available (or the debt is payable),
        then take them. Returns False only if `stop` was set while
        waiting."""
        if self.rate <= 0 or n <= 0:
            return True
        need = min(float(n), self.capacity)
        while True:
            with self._lock:
                self._refill()
                if self._tokens >= need:
                    self._tokens -= float(n)
                    return True
                wait = (need - self._tokens) / self.rate
            wait = min(wait, 0.2)
            if stop is not None:
                if stop.wait(wait):
                    return False
            else:
                clockctl.sleep(wait)


class InFlightLimiter:
    def __init__(self, limit_bytes: int, timeout: float = 30.0):
        self.limit = limit_bytes  # <= 0 means unlimited
        self.timeout = timeout
        self._used = 0
        self._waiters = 0
        self._cond = threading.Condition()

    def try_acquire(self, n: int, timeout: float = None) -> bool:
        """Reserve n bytes; block while the cap is exceeded. Returns
        False on timeout. A single request larger than the whole cap is
        admitted once the pipe is empty (matching the reference, which
        compares BEFORE adding: volume_server_handlers.go:62-75)."""
        if self.limit <= 0 or n <= 0:
            with self._cond:
                self._used += max(n, 0)
            return True
        deadline = clockctl.monotonic() + (self.timeout if timeout is None
                                       else timeout)
        with self._cond:
            while self._used > 0 and self._used + n > self.limit:
                remaining = deadline - clockctl.monotonic()
                if remaining <= 0:
                    return False
                self._waiters += 1
                try:
                    self._cond.wait(remaining)
                finally:
                    self._waiters -= 1
            self._used += n
            return True

    def release(self, n: int) -> None:
        if n <= 0:
            return
        with self._cond:
            self._used = max(0, self._used - n)
            self._cond.notify_all()

    @property
    def in_flight(self) -> int:
        with self._cond:
            return self._used

    @property
    def waiters(self) -> int:
        with self._cond:
            return self._waiters
