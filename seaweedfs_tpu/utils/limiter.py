"""Byte-accounted in-flight transfer limiter.

Redesign of reference weed/server/volume_server.go:23-30
(inFlightUploadDataSize / inFlightDownloadDataSize + their sync.Cond
backpressure, applied in volume_server_handlers.go): concurrent
request payload bytes are accounted against a cap; a request that
would exceed it waits until others drain, up to a timeout, after
which the caller sheds load (HTTP 429)."""

from __future__ import annotations

import threading
import time


class InFlightLimiter:
    def __init__(self, limit_bytes: int, timeout: float = 30.0):
        self.limit = limit_bytes  # <= 0 means unlimited
        self.timeout = timeout
        self._used = 0
        self._waiters = 0
        self._cond = threading.Condition()

    def try_acquire(self, n: int, timeout: float = None) -> bool:
        """Reserve n bytes; block while the cap is exceeded. Returns
        False on timeout. A single request larger than the whole cap is
        admitted once the pipe is empty (matching the reference, which
        compares BEFORE adding: volume_server_handlers.go:62-75)."""
        if self.limit <= 0 or n <= 0:
            with self._cond:
                self._used += max(n, 0)
            return True
        deadline = time.monotonic() + (self.timeout if timeout is None
                                       else timeout)
        with self._cond:
            while self._used > 0 and self._used + n > self.limit:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._waiters += 1
                try:
                    self._cond.wait(remaining)
                finally:
                    self._waiters -= 1
            self._used += n
            return True

    def release(self, n: int) -> None:
        if n <= 0:
            return
        with self._cond:
            self._used = max(0, self._used - n)
            self._cond.notify_all()

    @property
    def in_flight(self) -> int:
        with self._cond:
            return self._used

    @property
    def waiters(self) -> int:
        with self._cond:
            return self._waiters
