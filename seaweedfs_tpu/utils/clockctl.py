"""Process-wide monotonic-clock indirection for virtual-time testing.

Every timer in the resilience and QoS layers (breaker open windows,
deadline budgets, retry sleeps, token-bucket refills, pressure decay)
reads the clock through this module instead of ``time`` directly.  In
production nothing changes: the default hooks ARE ``time.monotonic`` /
``time.sleep`` and the indirection costs one module-attribute load.

The macro-scale simulation harness (``seaweedfs_tpu/sim``) installs a
VirtualClock here so O(100) in-process actors share one deterministic
compressed timeline: a breaker's 5s open window elapses when the sim
kernel advances 5 virtual seconds, not 5 wall seconds.  ``install()``
returns a restore handle and is also usable as a context manager, so a
test can never leak a virtual clock into the rest of the suite.

Deliberately NOT thread-aware: the simulator is single-threaded by
construction (that is what makes it bit-reproducible), and production
never installs anything.  Histogram/metrics timing routes through
here too (weedlint's raw-histogram-timer rule enforces it), so
latency telemetry elapses in virtual time under the sim.  Only span
wall-timestamps (absolute epochs that leave the process) keep
``time.time`` with an inline suppression.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Callable, Optional

_monotonic: Callable[[], float] = time.monotonic
_sleep: Callable[[float], None] = time.sleep
_now: Callable[[], float] = time.time
_thread_time: Callable[[], float] = time.thread_time


def monotonic() -> float:
    """The behavioral clock: wall monotonic unless a virtual clock is
    installed."""
    return _monotonic()


def now() -> float:
    """Behavioral wall clock (epoch seconds in production).  For
    timestamps the code later compares against itself — backoff
    next_attempt, drain-grace expiry, last-seen ages.  Under a virtual
    clock this follows the sim timeline (so a 30s grace elapses in 30
    virtual seconds); values that leave the process as ABSOLUTE epochs
    (HTTP Date, SigV4 signing, TLS validity) must keep ``time.time``
    with an inline suppression."""
    return _now()


def sleep(seconds: float) -> None:
    """Behavioral sleep (retry backoff etc.); virtual clocks make this
    raise — simulated actors must yield to the kernel instead of
    blocking the one real thread."""
    _sleep(seconds)


def thread_time() -> float:
    """Per-thread CPU clock, for resource accounting (the ledger's
    dispatch-boundary deltas).  Unlike the behavioral clocks, install()
    does NOT redirect this onto the virtual timeline by default: CPU
    burned under a sim is still real CPU, and attributing virtual
    seconds as CPU-milliseconds would fabricate chargeback rows.  A
    deterministic test that wants synthetic CPU deltas passes
    ``thread_time_fn`` explicitly."""
    return _thread_time()


def is_virtual() -> bool:
    return _monotonic is not time.monotonic


def _no_real_sleep(seconds: float) -> None:
    raise RuntimeError(
        "blocking sleep under a virtual clock — simulated code must "
        "yield to the sim kernel instead")


@contextmanager
def install(monotonic_fn: Callable[[], float],
            sleep_fn: Optional[Callable[[float], None]] = None,
            now_fn: Optional[Callable[[], float]] = None,
            thread_time_fn: Optional[Callable[[], float]] = None):
    """Install a clock override for the duration of a with-block.
    Nested installs restore correctly (LIFO).  ``now_fn`` defaults to
    ``monotonic_fn``: the virtual timeline serves both clocks, which
    keeps now()-vs-now() comparisons coherent inside the sim.
    ``thread_time_fn`` defaults to staying REAL (see thread_time)."""
    global _monotonic, _sleep, _now, _thread_time
    prev = (_monotonic, _sleep, _now, _thread_time)
    _monotonic = monotonic_fn
    _sleep = sleep_fn if sleep_fn is not None else _no_real_sleep
    _now = now_fn if now_fn is not None else monotonic_fn
    if thread_time_fn is not None:
        _thread_time = thread_time_fn
    try:
        yield
    finally:
        _monotonic, _sleep, _now, _thread_time = prev
