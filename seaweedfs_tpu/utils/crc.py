"""CRC32-C (Castagnoli) — the needle checksum algorithm
(reference weed/storage/needle/crc.go:13 uses Go hash/crc32 Castagnoli).

Uses the native C++ kernel when available, else a numpy table-driven
fallback. Both accept any byte-shaped buffer (bytes / bytearray /
memoryview) WITHOUT copying it, and both chain through ``crc=``:
``crc32c(b, crc32c(a))`` equals ``crc32c(a + b)``, which is what lets
the read plane verify a payload window-by-window over memoryview
slices of a cached record instead of materializing a contiguous copy.
"""

from __future__ import annotations

import numpy as np

_POLY = 0x82F63B78  # reflected Castagnoli


def _make_table() -> np.ndarray:
    tab = np.zeros(256, dtype=np.uint32)
    for i in range(256):
        c = i
        for _ in range(8):
            c = (_POLY ^ (c >> 1)) if (c & 1) else (c >> 1)
        tab[i] = c
    return tab


_TAB = _make_table()


def _crc32c_py(data: bytes | bytearray | memoryview | np.ndarray,
               crc: int = 0) -> int:
    if isinstance(data, np.ndarray):
        buf = np.ascontiguousarray(data, dtype=np.uint8)
    else:  # zero-copy view of the caller's buffer
        buf = np.frombuffer(data, dtype=np.uint8)
    c = np.uint32(crc ^ 0xFFFFFFFF)
    tab = _TAB
    for b in buf.tolist():
        c = tab[(int(c) ^ b) & 0xFF] ^ (int(c) >> 8)
        c = np.uint32(c)
    return int(c) ^ 0xFFFFFFFF


def crc32c(data: bytes | bytearray | memoryview | np.ndarray,
           crc: int = 0) -> int:
    try:
        from seaweedfs_tpu.native import rs_native
        if rs_native.available():
            return rs_native.crc32c(data, crc)
    except ImportError:
        pass
    return _crc32c_py(data, crc)
