"""Tiered chunk cache (reference weed/util/chunk_cache: in-memory + on-disk
tiers in front of volume-server chunk fetches, used by filer and mount)."""

from __future__ import annotations

import collections
import hashlib
import os
import threading
from typing import Optional


class MemChunkCache:
    def __init__(self, capacity_bytes: int = 64 * 1024 * 1024):
        self.capacity = capacity_bytes
        self._used = 0
        self._data: "collections.OrderedDict[str, bytes]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            if key in self._data:
                self._data.move_to_end(key)
                self.hits += 1
                return self._data[key]
            self.misses += 1
            return None

    def put(self, key: str, value: bytes) -> None:
        if len(value) > self.capacity:
            return
        with self._lock:
            if key in self._data:
                self._used -= len(self._data.pop(key))
            while self._used + len(value) > self.capacity and self._data:
                _, evicted = self._data.popitem(last=False)
                self._used -= len(evicted)
            self._data[key] = value
            self._used += len(value)

    def contains(self, key: str) -> bool:
        """Presence probe that does NOT touch LRU order or counters
        (prefetch planning must not look like traffic)."""
        with self._lock:
            return key in self._data


class DiskChunkCache:
    def __init__(self, directory: str,
                 capacity_bytes: int = 1024 * 1024 * 1024):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.capacity = capacity_bytes
        self._lock = threading.Lock()

    def _path(self, key: str) -> str:
        h = hashlib.sha1(key.encode()).hexdigest()
        return os.path.join(self.directory, h[:2], h)

    def get(self, key: str) -> Optional[bytes]:
        p = self._path(key)
        try:
            with open(p, "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def contains(self, key: str) -> bool:
        return os.path.exists(self._path(key))

    def put(self, key: str, value: bytes) -> None:
        p = self._path(key)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        with self._lock:
            with open(p + ".tmp", "wb") as f:
                f.write(value)
            os.replace(p + ".tmp", p)
            self._evict_if_needed()

    def _evict_if_needed(self) -> None:
        total = 0
        files = []
        for root, _dirs, names in os.walk(self.directory):
            for n in names:
                p = os.path.join(root, n)
                try:
                    st = os.stat(p)
                except FileNotFoundError:
                    continue
                total += st.st_size
                files.append((st.st_atime, st.st_size, p))
        if total <= self.capacity:
            return
        files.sort()
        for _, size, p in files:
            try:
                os.remove(p)
            except FileNotFoundError:
                continue
            total -= size
            if total <= self.capacity:
                break


class TieredChunkCache:
    """Memory in front of disk (reference chunk_cache.NewTieredChunkCache)."""

    def __init__(self, mem_bytes: int = 64 * 1024 * 1024,
                 disk_dir: Optional[str] = None,
                 disk_bytes: int = 1024 * 1024 * 1024):
        self.mem = MemChunkCache(mem_bytes)
        self.disk = DiskChunkCache(disk_dir, disk_bytes) if disk_dir else None

    def get(self, key: str) -> Optional[bytes]:
        hit = self.mem.get(key)
        if hit is not None:
            return hit
        if self.disk is not None:
            hit = self.disk.get(key)
            if hit is not None:
                self.mem.put(key, hit)
        return hit

    def put(self, key: str, value: bytes) -> None:
        self.mem.put(key, value)
        if self.disk is not None and len(value) >= 1024:
            self.disk.put(key, value)

    def contains(self, key: str) -> bool:
        if self.mem.contains(key):
            return True
        return self.disk is not None and self.disk.contains(key)
