"""TOML configuration loading + scaffold defaults.

Functional equivalent of reference weed/util/config.go (viper search in
./, ~/.seaweedfs, /etc/seaweedfs) and weed/command/scaffold (embedded
default tomls). Python 3.11+ tomllib reads; scaffold emits the defaults.
"""

from __future__ import annotations

import os
from typing import Any, Optional

try:
    import tomllib  # Python 3.11+
except ModuleNotFoundError:  # 3.10 container: bundled subset reader
    from seaweedfs_tpu.utils import toml_compat as tomllib

SEARCH_PATHS = [".", os.path.expanduser("~/.seaweedfs-tpu"),
                "/etc/seaweedfs-tpu"]

DEFAULTS = {
    "security": """\
# security.toml — JWT signing + TLS + whitelists
[jwt.signing]
key = ""
expires_after_seconds = 10

[access]
ui = false
# ip whitelist, e.g. ["10.0.0.0/8", "127.0.0.1"]
white_list = []

# Mutual TLS for every gRPC plane + HTTP admin (reference security/tls.go).
# Set all three to enable; per-role sections ([grpc.master], [grpc.volume],
# [grpc.filer], [grpc.client]) override.
[grpc]
ca = ""
cert = ""
key = ""
""",
    "master": """\
# master.toml
[master.volume_growth]
copy_1 = 7
copy_2 = 6
copy_3 = 3
copy_other = 1

[master.maintenance]
garbage_threshold = 0.3
""",
    "filer": """\
# filer.toml — filer store selection
[memory]
enabled = true

[sqlite]
enabled = false
dbFile = "./filer.db"
""",
    "replication": """\
# replication.toml — sink for filer.sync / filer.replicate
[sink.filer]
enabled = false
url = "localhost:8888"
directory = ""            # destination path prefix

[sink.local]
enabled = false
directory = "/data/backup"

[sink.s3]
enabled = false
endpoint = "http://localhost:8333"
bucket = "backup"
directory = ""            # key prefix
aws_access_key_id = ""
aws_secret_access_key = ""
region = "us-east-1"

[sink.azure]
enabled = false
endpoint = ""             # https://<account>.blob.core.windows.net
container = "backup"
account_name = ""
account_key = ""          # base64 SharedKey
directory = ""
""",
    "notification": """\
# notification.toml — filer event publishing
[notification.log]
enabled = false

[notification.file]
enabled = false
path = "./notifications.jsonl"

[notification.kafka]
enabled = false
address = "127.0.0.1:9092"   # any Kafka-wire broker
topic = "seaweedfs_meta"

[notification.aws_sqs]
enabled = false
sqs_queue_url = ""           # any SQS-wire endpoint (AWS/localstack/elasticmq)
access_key = ""
secret_key = ""
region = "us-east-1"

[notification.google_pub_sub]
enabled = false
endpoint = "https://pubsub.googleapis.com"   # or an emulator
project_id = ""
topic = "seaweedfs_meta"
token = ""                   # static bearer token (emulators accept any)
""",
    "shell": """\
# shell.toml
[cluster]
default = "default"

[cluster.default]
master = "localhost:9333"
filer = "localhost:8888"
""",
}


def load_configuration(name: str, required: bool = False) -> dict[str, Any]:
    """Find <name>.toml in the search paths (reference LoadConfiguration)."""
    for base in SEARCH_PATHS:
        path = os.path.join(base, f"{name}.toml")
        if os.path.exists(path):
            with open(path, "rb") as f:
                return tomllib.load(f)
    if required:
        raise FileNotFoundError(
            f"{name}.toml not found in {SEARCH_PATHS}; "
            f"run `weed-tpu scaffold -config {name}` to generate one")
    return {}


def get(conf: dict, dotted: str, default: Any = None) -> Any:
    cur: Any = conf
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return default
        cur = cur[part]
    return cur


def scaffold(name: str) -> str:
    if name not in DEFAULTS:
        raise KeyError(f"unknown config {name!r}; have {sorted(DEFAULTS)}")
    return DEFAULTS[name]
