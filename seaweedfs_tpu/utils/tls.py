"""Mutual TLS for the control plane (reference weed/security/tls.go).

The reference secures every gRPC plane with mTLS when security.toml's
[grpc] section names a CA + per-role cert/key; this module is the same
contract for the three gRPC planes here (master/volume/filer) plus the
HTTP admin listener. Loading precedence mirrors the reference: the
per-role section ([grpc.master], [grpc.volume], ...) overrides [grpc].

Also ships a self-signed chain generator (CA + per-role leaf certs,
`cryptography` backed) used by tests and `weed-tpu scaffold -tls`.
"""

from __future__ import annotations

import dataclasses
import datetime
import ipaddress
import os
from typing import Optional

from seaweedfs_tpu.utils import config as config_mod


@dataclasses.dataclass
class TlsConfig:
    ca_file: str
    cert_file: str
    key_file: str

    def read(self) -> tuple[bytes, bytes, bytes]:
        with open(self.ca_file, "rb") as f:
            ca = f.read()
        with open(self.cert_file, "rb") as f:
            cert = f.read()
        with open(self.key_file, "rb") as f:
            key = f.read()
        return ca, cert, key


def load_tls_config(role: str = "") -> Optional[TlsConfig]:
    """TlsConfig from security.toml ([grpc] / [grpc.<role>]), or None when
    mTLS is not configured (reference util.LoadSecurityConfiguration +
    security.LoadServerTLS)."""
    conf = config_mod.load_configuration("security")
    base = conf.get("grpc", {}) if conf else {}
    section = dict(base)
    if role and isinstance(base.get(role), dict):
        section.update(base[role])
    ca = section.get("ca", "")
    cert = section.get("cert", "")
    key = section.get("key", "")
    if not (ca and cert and key):
        return None
    return TlsConfig(ca_file=ca, cert_file=cert, key_file=key)


def make_channel(address: str, role: str = "client",
                 tls="auto"):
    """grpc channel honoring security.toml mTLS config ("auto"), an
    explicit TlsConfig, or None for insecure."""
    import grpc
    cfg = load_tls_config(role) if tls == "auto" else tls
    if cfg is not None:
        return grpc.secure_channel(address, channel_credentials(cfg))
    return grpc.insecure_channel(address)


def server_credentials(cfg: TlsConfig):
    """grpc server credentials REQUIRING a client cert signed by the CA
    (reference tls.go: ClientAuth: tls.RequireAndVerifyClientCert)."""
    import grpc
    ca, cert, key = cfg.read()
    return grpc.ssl_server_credentials(
        [(key, cert)], root_certificates=ca, require_client_auth=True)


def channel_credentials(cfg: TlsConfig):
    import grpc
    ca, cert, key = cfg.read()
    return grpc.ssl_channel_credentials(
        root_certificates=ca, private_key=key, certificate_chain=cert)


def wrap_http_server(http_server, cfg: TlsConfig) -> None:
    """Upgrade an HttpServer's listening socket to mTLS (client cert
    required) — the HTTP admin plane equivalent of the gRPC credentials."""
    import ssl
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cfg.cert_file, cfg.key_file)
    ctx.load_verify_locations(cfg.ca_file)
    ctx.verify_mode = ssl.CERT_REQUIRED
    http_server._httpd.socket = ctx.wrap_socket(
        http_server._httpd.socket, server_side=True)


def generate_self_signed(out_dir: str, roles: tuple[str, ...] = (
        "master", "volume", "filer", "client"),
        host: str = "127.0.0.1") -> dict[str, TlsConfig]:
    """Write ca.crt + <role>.crt/<role>.key under out_dir; returns a
    TlsConfig per role. Test/dev helper (the reference documents using
    openssl/easyrsa; same output shape). Uses `cryptography` when
    installed, else the openssl CLI."""
    try:
        from cryptography import x509  # noqa: F401
    except ModuleNotFoundError:
        return _generate_via_openssl_cli(out_dir, roles, host)
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    os.makedirs(out_dir, exist_ok=True)
    now = datetime.datetime.now(datetime.timezone.utc)
    one_day = datetime.timedelta(days=1)

    ca_key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    ca_name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "seaweedfs-tpu-test-ca")])
    ca_cert = (x509.CertificateBuilder()
               .subject_name(ca_name).issuer_name(ca_name)
               .public_key(ca_key.public_key())
               .serial_number(x509.random_serial_number())
               .not_valid_before(now - one_day)
               .not_valid_after(now + 30 * one_day)
               .add_extension(x509.BasicConstraints(ca=True,
                                                    path_length=None),
                              critical=True)
               .sign(ca_key, hashes.SHA256()))
    ca_path = os.path.join(out_dir, "ca.crt")
    with open(ca_path, "wb") as f:
        f.write(ca_cert.public_bytes(serialization.Encoding.PEM))

    out: dict[str, TlsConfig] = {}
    san = x509.SubjectAlternativeName([
        x509.DNSName("localhost"),
        x509.IPAddress(ipaddress.ip_address(host))])
    for role in roles:
        key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
        cert = (x509.CertificateBuilder()
                .subject_name(x509.Name([x509.NameAttribute(
                    NameOID.COMMON_NAME, f"seaweedfs-tpu-{role}")]))
                .issuer_name(ca_name)
                .public_key(key.public_key())
                .serial_number(x509.random_serial_number())
                .not_valid_before(now - one_day)
                .not_valid_after(now + 30 * one_day)
                .add_extension(san, critical=False)
                .sign(ca_key, hashes.SHA256()))
        cert_path = os.path.join(out_dir, f"{role}.crt")
        key_path = os.path.join(out_dir, f"{role}.key")
        with open(cert_path, "wb") as f:
            f.write(cert.public_bytes(serialization.Encoding.PEM))
        with open(key_path, "wb") as f:
            f.write(key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.TraditionalOpenSSL,
                serialization.NoEncryption()))
        out[role] = TlsConfig(ca_file=ca_path, cert_file=cert_path,
                              key_file=key_path)
    return out


def _generate_via_openssl_cli(out_dir: str, roles: tuple[str, ...],
                              host: str) -> dict[str, TlsConfig]:
    """Same chain via the openssl binary (always present in this
    container; `cryptography` is not)."""
    import subprocess

    def run(*args: str) -> None:
        subprocess.run(["openssl", *args], check=True, capture_output=True)

    os.makedirs(out_dir, exist_ok=True)
    ca_path = os.path.join(out_dir, "ca.crt")
    ca_key = os.path.join(out_dir, "ca.key")
    # note: req -x509 already emits basicConstraints critical,CA:TRUE;
    # adding it again via -addext duplicates the extension and OpenSSL
    # then refuses to chain to the CA (verify error 20)
    run("req", "-x509", "-newkey", "rsa:2048", "-nodes", "-sha256",
        "-keyout", ca_key, "-out", ca_path, "-days", "30",
        "-subj", "/CN=seaweedfs-tpu-test-ca")
    ext_path = os.path.join(out_dir, "san.cnf")
    with open(ext_path, "w") as f:
        f.write(f"subjectAltName=DNS:localhost,IP:{host}\n")
    out: dict[str, TlsConfig] = {}
    for role in roles:
        key_path = os.path.join(out_dir, f"{role}.key")
        cert_path = os.path.join(out_dir, f"{role}.crt")
        csr_path = os.path.join(out_dir, f"{role}.csr")
        run("req", "-newkey", "rsa:2048", "-nodes", "-sha256",
            "-keyout", key_path, "-out", csr_path,
            "-subj", f"/CN=seaweedfs-tpu-{role}")
        run("x509", "-req", "-in", csr_path, "-CA", ca_path,
            "-CAkey", ca_key, "-CAcreateserial", "-sha256",
            "-out", cert_path, "-days", "30", "-extfile", ext_path)
        os.remove(csr_path)
        out[role] = TlsConfig(ca_file=ca_path, cert_file=cert_path,
                              key_file=key_path)
    os.remove(ext_path)
    return out
