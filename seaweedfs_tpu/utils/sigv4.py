"""AWS Signature Version 4 canonicalization — THE single copy.

Both halves of the protocol import this: the S3 gateway verifies with it
(gateway/s3_server.py, reference weed/s3api/auth_signature_v4.go) and the
S3 remote-storage client signs with it (remote_storage/s3_client.py).
One implementation means the two can never drift apart on
canonicalization details (quote alphabet, header folding, scope order).
"""

from __future__ import annotations

import hashlib
import hmac
import urllib.parse


def signing_key(secret: str, date: str, region: str,
                service: str) -> bytes:
    k = ("AWS4" + secret).encode()
    for msg in (date, region, service, "aws4_request"):
        k = hmac.new(k, msg.encode(), hashlib.sha256).digest()
    return k


def signature(secret: str, date: str, region: str, service: str,
              amz_date: str, method: str, path: str, query: dict,
              headers, signed_headers: list[str],
              payload_hash: str) -> str:
    """Hex SigV4 over a canonical request. `path` is the WIRE path,
    still percent-encoded exactly as the signer sent it (re-quoting
    would double-encode); `headers` is any mapping with .get()."""
    cq = "&".join(
        f"{urllib.parse.quote(k, safe='~')}="
        f"{urllib.parse.quote(str(v), safe='~')}"
        for k, v in sorted(query.items()))
    ch = "".join(f"{h}:{headers.get(h, '').strip()}\n"
                 for h in signed_headers)
    creq = "\n".join([method, path, cq, ch, ";".join(signed_headers),
                      payload_hash])
    scope = f"{date}/{region}/{service}/aws4_request"
    sts = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                     hashlib.sha256(creq.encode()).hexdigest()])
    k = signing_key(secret, date, region, service)
    return hmac.new(k, sts.encode(), hashlib.sha256).hexdigest()
