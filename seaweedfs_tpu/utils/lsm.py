"""Generic embedded LSM key-value engine.

The storage engine behind both the filer's durable metadata store
(filer/lsm_store.py) and the disk-backed needle map
(storage/needle_map_ldb.py) — the roles the reference delegates to the
LevelDB library (weed/filer/leveldb*, weed/storage/needle_map_leveldb.go).
Structure: write-ahead log for the active memtable, sorted immutable
SSTable segments, size-tiered full compaction, point reads newest-first,
range scans as a merged view.

Record framing (WAL and SSTable share it):
  <key_len:u32 LE> <val_len:u32 LE | 0xFFFFFFFF = tombstone> <key> <val>
"""

from __future__ import annotations

import bisect
import os
import struct
import threading
from typing import Iterator, Optional

_TOMB = 0xFFFFFFFF
_REC = struct.Struct("<II")  # key_len, val_len (or _TOMB)

MEMTABLE_FLUSH_KEYS = 4096
COMPACT_AT_SEGMENTS = 6


def _pack(key: bytes, val: Optional[bytes]) -> bytes:
    if val is None:
        return _REC.pack(len(key), _TOMB) + key
    return _REC.pack(len(key), len(val)) + key + val


def _iter_records(blob: bytes) -> Iterator[tuple[bytes, Optional[bytes]]]:
    for key, val, _end in _iter_records_pos(blob):
        yield key, val


def _iter_records_pos(blob: bytes
                      ) -> Iterator[tuple[bytes, Optional[bytes], int]]:
    """Yields (key, val, end_offset); stops before a torn tail record
    (crash mid-append) so replay can truncate at the last good byte."""
    pos, n = 0, len(blob)
    while pos + _REC.size <= n:
        klen, vlen = _REC.unpack_from(blob, pos)
        body = klen + (0 if vlen == _TOMB else vlen)
        if pos + _REC.size + body > n:
            break  # torn tail record — drop it
        pos += _REC.size
        key = blob[pos:pos + klen]
        pos += klen
        if vlen == _TOMB:
            yield key, None, pos
        else:
            yield key, blob[pos:pos + vlen], pos + vlen
            pos += vlen


class _SSTable:
    """Immutable sorted segment; full key index kept in memory (the
    segments hold metadata-scale records, so a sparse index buys
    nothing here)."""

    def __init__(self, path: str):
        self.path = path
        self.keys: list[bytes] = []
        self.vals: list[Optional[bytes]] = []
        with open(path, "rb") as f:
            blob = f.read()
        for key, val in _iter_records(blob):
            self.keys.append(key)
            self.vals.append(val)

    def get(self, key: bytes) -> tuple[bool, Optional[bytes]]:
        i = bisect.bisect_left(self.keys, key)
        if i < len(self.keys) and self.keys[i] == key:
            return True, self.vals[i]
        return False, None

    def scan(self, lo: bytes, hi: Optional[bytes]
             ) -> Iterator[tuple[bytes, Optional[bytes]]]:
        i = bisect.bisect_left(self.keys, lo)
        while i < len(self.keys) and (hi is None or self.keys[i] < hi):
            yield self.keys[i], self.vals[i]
            i += 1


class LsmKv:
    """The engine: open a directory, get/put/delete/scan bytes keys."""

    def __init__(self, path: str, fsync: bool = True,
                 flush_keys: int = MEMTABLE_FLUSH_KEYS,
                 compact_at: int = COMPACT_AT_SEGMENTS):
        self.dir = path
        self.fsync = fsync
        self.flush_keys = flush_keys
        self.compact_at = compact_at
        os.makedirs(path, exist_ok=True)
        self._lock = threading.RLock()
        self._mem: dict[bytes, Optional[bytes]] = {}
        self._mem_sorted: list[bytes] = []
        self._tables: list[_SSTable] = []  # oldest first
        self._next_seg = 0
        for name in sorted(os.listdir(path)):
            if name.endswith(".sst"):
                self._tables.append(_SSTable(os.path.join(path, name)))
                self._next_seg = max(self._next_seg,
                                     int(name.split(".")[0]) + 1)
        self._wal_path = os.path.join(path, "wal.log")
        self._replay_wal()
        self._wal = open(self._wal_path, "ab")

    # ---- WAL / memtable / segments ----
    def _replay_wal(self) -> None:
        try:
            with open(self._wal_path, "rb") as f:
                blob = f.read()
        except OSError:
            return
        good = 0
        for key, val, end in _iter_records_pos(blob):
            self._mem_put(key, val)
            good = end
        if good < len(blob):
            # cut the torn tail NOW: the WAL reopens in append mode, and
            # appending after torn bytes would let the dropped record
            # resurrect (half-merged with the new one) on a later replay
            with open(self._wal_path, "r+b") as f:
                f.truncate(good)

    def _mem_put(self, key: bytes, val: Optional[bytes]) -> None:
        if key not in self._mem:
            bisect.insort(self._mem_sorted, key)
        self._mem[key] = val

    def put(self, key: bytes, val: Optional[bytes]) -> None:
        """val=None writes a tombstone."""
        with self._lock:
            self._wal.write(_pack(key, val))
            self._wal.flush()
            if self.fsync:
                os.fsync(self._wal.fileno())
            self._mem_put(key, val)
            if len(self._mem) >= self.flush_keys:
                self._flush_memtable()

    def _flush_memtable(self) -> None:
        if not self._mem:
            return
        seg = os.path.join(self.dir, f"{self._next_seg:08d}.sst")
        self._next_seg += 1
        with open(seg + ".tmp", "wb") as f:
            for key in self._mem_sorted:
                f.write(_pack(key, self._mem[key]))
            f.flush()
            os.fsync(f.fileno())
        os.rename(seg + ".tmp", seg)
        self._tables.append(_SSTable(seg))
        self._mem.clear()
        self._mem_sorted.clear()
        self._wal.close()
        self._wal = open(self._wal_path, "wb")
        if len(self._tables) >= self.compact_at:
            self._compact()

    def _compact(self) -> None:
        """Merge every segment into one; newest value wins, tombstones
        dropped (nothing older than a full merge can resurrect)."""
        merged: dict[bytes, Optional[bytes]] = {}
        for table in self._tables:  # oldest -> newest
            for key, val in zip(table.keys, table.vals):
                merged[key] = val
        seg = os.path.join(self.dir, f"{self._next_seg:08d}.sst")
        self._next_seg += 1
        with open(seg + ".tmp", "wb") as f:
            for key in sorted(merged):
                if merged[key] is not None:
                    f.write(_pack(key, merged[key]))
            f.flush()
            os.fsync(f.fileno())
        os.rename(seg + ".tmp", seg)
        old = self._tables
        self._tables = [_SSTable(seg)]
        for t in old:
            try:
                os.remove(t.path)
            except OSError:
                pass

    def get(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            if key in self._mem:
                return self._mem[key]
            for table in reversed(self._tables):
                hit, val = table.get(key)
                if hit:
                    return val
        return None

    def scan(self, lo: bytes = b"",
             hi: Optional[bytes] = None) -> list[tuple[bytes, bytes]]:
        """Merged live view of [lo, hi) (hi=None -> unbounded): memtable
        shadows newer tables shadow older ones; tombstones omitted."""
        with self._lock:
            merged: dict[bytes, Optional[bytes]] = {}
            for table in self._tables:
                for key, val in table.scan(lo, hi):
                    merged[key] = val
            i = bisect.bisect_left(self._mem_sorted, lo)
            while i < len(self._mem_sorted) and (
                    hi is None or self._mem_sorted[i] < hi):
                key = self._mem_sorted[i]
                merged[key] = self._mem[key]
                i += 1
        return sorted((k, v) for k, v in merged.items() if v is not None)

    def __len__(self) -> int:
        """Live key count (scans everything; debugging/stats use)."""
        return len(self.scan())

    def close(self) -> None:
        with self._lock:
            self._flush_memtable()
            self._wal.close()
