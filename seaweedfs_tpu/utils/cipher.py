"""Chunk encryption: AES-256-GCM with a random per-chunk key.

Functional equivalent of reference weed/util/cipher.go (Encrypt/Decrypt):
each encrypted chunk gets its own random 256-bit key, stored in the
chunk's metadata (FileChunk.cipher_key) in the filer — volume servers
only ever see ciphertext. The 12-byte nonce is prepended to the
ciphertext, as in the reference.
"""

from __future__ import annotations

import os

try:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ModuleNotFoundError:  # container has OpenSSL but not the wheel
    from seaweedfs_tpu.utils.aesgcm_compat import AESGCM

KEY_SIZE = 32
NONCE_SIZE = 12


def encrypt(data: bytes) -> tuple[bytes, bytes]:
    """Returns (nonce + ciphertext+tag, key)."""
    key = os.urandom(KEY_SIZE)
    nonce = os.urandom(NONCE_SIZE)
    sealed = AESGCM(key).encrypt(nonce, data, None)
    return nonce + sealed, key


def decrypt(blob: bytes, key: bytes) -> bytes:
    nonce, sealed = blob[:NONCE_SIZE], blob[NONCE_SIZE:]
    return AESGCM(key).decrypt(nonce, sealed, None)
