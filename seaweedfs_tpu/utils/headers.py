"""The X-Weed-* header namespace — every cross-node protocol header,
in one place.

These names ARE the wire protocol for the cluster's ambient request
scope (deadline budget, QoS class, trace context) and its side-channel
metadata (replica mtimes, sync signatures, partial-repair state).  A
typo in an inline literal fails open — the header silently doesn't
match and the contract quietly stops propagating at that hop, which is
exactly how the S3 gateway lost replication for four call sites in
PR 7.  weedlint's ``header-literal`` rule therefore bans inline
``"X-Weed-*"`` strings everywhere but here; import the constant.

Adding a header: define it here with a comment naming its
producer/consumer pair, then use it via this module.  The domain
modules (resilience/tracing/qos.classes) re-export their own header
for their callers' convenience; both spellings are the same object.
"""

from __future__ import annotations

# ---- ambient request scope (injected by http_call, re-entered by
#      HttpServer._dispatch on the far side) ----

# remaining deadline budget, decimal seconds (utils/resilience.py)
DEADLINE = "X-Weed-Deadline"
# traffic class: interactive | write | background (qos/classes.py)
CLASS = "X-Weed-Class"
# trace context: <trace_id>:<span_id>:<flags> (utils/tracing.py)
TRACE = "X-Weed-Trace"

# ---- replication & sync ----

# replica-copy source mtime: a copy must not restart a TTL volume's
# expiry clock (volume server /admin/copy)
FILE_MTIME = "X-Weed-File-Mtime"
# replicator signature so the reverse sync direction can exclude its
# own writes from the event stream (replication/sink.py <-> filer)
SYNC_SIGNATURE = "X-Weed-Sync-Signature"

# ---- control plane ----

# loop guard on follower->leader proxying during elections (master)
PROXIED = "X-Weed-Proxied"
# filer namespace sharding: "<ring_epoch>:<owner_url>" on 307
# redirects / forwarded responses for mis-routed namespace ops
# (server/filer_server.py); clients compare the epoch against their
# cached ring and re-pull /cluster/filers on drift
# (client/wdclient.py, filer/shard_ring.py owns the format)
SHARD = "X-Weed-Shard"
# loop guard on shard-to-shard forwarding of mis-routed mutations: a
# forwarded op that still looks mis-routed (ring disagreement between
# shards mid-epoch-change) is served locally instead of bouncing
SHARD_FORWARDED = "X-Weed-Shard-Forwarded"

# ---- cache-aware read routing ----

# set "1" on a volume read served while the needle sits in that
# replica's hot-needle record cache (server/volume_server.py); clients
# (client/operation.read_data) note the advertising replica and prefer
# it on subsequent reads of the same needle, with a fairness guard so
# affinity can't starve the other replicas of cache warmth
CACHE_HOT = "X-Weed-Cache-Hot"

# set "1" on a volume GET whose payload was served by the zero-copy
# descriptor path (sendfile off the .dat fd, server/volume_server.py);
# tests and the read-plane bench use it to prove which path ran, and
# operators can spot a fleet that silently fell back to buffered serving
ZERO_COPY = "X-Weed-Zero-Copy"

# ---- partial-parallel EC repair (storage/erasure_coding/partial.py) ----

# shard ids folded into a chain hop's pre-reduced column
PARTIAL_SHARDS = "X-Weed-Partial-Shards"
# set when a hop fell back to raw-streaming its members locally
PARTIAL_FALLBACK = "X-Weed-Partial-Fallback"
