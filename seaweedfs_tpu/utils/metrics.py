"""Prometheus-style metrics registry + text exposition.

Functional equivalent of reference weed/stats/metrics.go (Namespace
"SeaweedFS", per-subsystem counters/gauges/histograms exposed on
/metrics). Stdlib-only implementation of the text format.

Histograms are the cluster telemetry plane's building block: they are
*mergeable* (``snapshot()``/``merge_from()`` move per-node series to
the master, which sums bucket counts — histogram merging is exact,
unlike quantile merging) and carry OpenMetrics-style trace exemplars
(each bucket remembers the last sampled ``X-Weed-Trace`` id that
landed in it, closing the metrics->trace loop). All histogram timing
goes through ``clockctl`` so timed sections elapse in virtual time
under the deterministic sim.
"""

from __future__ import annotations

import bisect
import threading
import urllib.parse
from typing import Optional

from seaweedfs_tpu.utils import clockctl


class Counter:
    def __init__(self, name: str, help_: str, label_names: tuple = ()):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def inc(self, *labels, amount: float = 1.0):
        with self._lock:
            self._values[labels] = self._values.get(labels, 0.0) + amount

    def expose(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} counter"]
        with self._lock:  # inc() can add a label key mid-scrape
            items = sorted(self._values.items())
        for labels, v in items:
            out.append(f"{self.name}{_fmt_labels(self.label_names, labels)} {v}")
        return out


class Gauge(Counter):
    def set(self, *labels, value: float = 0.0):
        with self._lock:
            self._values[labels] = value

    def expose(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} gauge"]
        with self._lock:
            items = sorted(self._values.items())
        for labels, v in items:
            out.append(f"{self.name}{_fmt_labels(self.label_names, labels)} {v}")
        return out


class Histogram:
    DEFAULT_BUCKETS = (0.0001, 0.001, 0.01, 0.1, 1, 10)

    def __init__(self, name: str, help_: str, label_names: tuple = (),
                 buckets: tuple = DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self.buckets = sorted(buckets)
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        # labels -> per-bucket [exemplar trace id or None]; only
        # written when observe() is handed a sampled trace, so the
        # common unsampled path costs nothing extra
        self._exemplars: dict[tuple, list] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, *labels,
                exemplar: Optional[str] = None):
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            counts = self._counts.setdefault(
                labels, [0] * (len(self.buckets) + 1))
            counts[idx] += 1
            self._sums[labels] = self._sums.get(labels, 0.0) + value
            if exemplar:
                ex = self._exemplars.setdefault(
                    labels, [None] * (len(self.buckets) + 1))
                ex[idx] = exemplar

    def time(self, *labels):
        return _Timer(self, labels)

    # ---- mergeable snapshots (the telemetry plane's transport) ----
    def snapshot(self) -> dict:
        """JSON-safe copy of every series. Bucket counts are
        per-bucket (NOT cumulative) so merging is plain elementwise
        addition."""
        with self._lock:
            series = [[list(labels), list(counts),
                       self._sums[labels],
                       list(self._exemplars.get(labels, ()) or
                            [None] * (len(self.buckets) + 1))]
                      for labels, counts in self._counts.items()]
        series.sort(key=lambda s: s[0])
        return {"name": self.name, "buckets": list(self.buckets),
                "label_names": list(self.label_names), "series": series}

    def merge_from(self, snap: dict) -> None:
        """Fold another node's ``snapshot()`` into this histogram.
        Bucket layouts must match (all RED histograms share one
        compile-time layout); incoming exemplars win — they are
        samples, not aggregates, so last-writer-wins keeps merging
        commutative enough for a debugging hook."""
        if list(snap.get("buckets", ())) != list(self.buckets):
            raise ValueError(
                f"{self.name}: bucket layout mismatch in merge")
        n = len(self.buckets) + 1
        for raw_labels, counts, total, exemplars in snap["series"]:
            labels = tuple(raw_labels)
            with self._lock:
                mine = self._counts.setdefault(labels, [0] * n)
                for i, c in enumerate(counts):
                    mine[i] += c
                self._sums[labels] = self._sums.get(labels, 0.0) + total
                if exemplars and any(exemplars):
                    ex = self._exemplars.setdefault(labels, [None] * n)
                    for i, e in enumerate(exemplars):
                        if e:
                            ex[i] = e

    def quantile(self, q: float, *labels,
                 label_filter=None) -> Optional[float]:
        """Estimated q-quantile (0..1) from bucket counts, linearly
        interpolated inside the winning bucket. With ``labels`` uses
        that one series; with ``label_filter`` (a predicate over the
        label tuple) sums the matching series; otherwise sums all.
        Returns None with no observations."""
        n = len(self.buckets) + 1
        merged = [0] * n
        with self._lock:
            if labels:
                merged = list(self._counts.get(labels, merged))
            else:
                for lbl, counts in self._counts.items():
                    if label_filter is not None and not label_filter(lbl):
                        continue
                    for i, c in enumerate(counts):
                        merged[i] += c
        total = sum(merged)
        if total == 0:
            return None
        rank = q * total
        cum = 0
        for i, c in enumerate(merged):
            if cum + c >= rank and c > 0:
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = self.buckets[i] if i < len(self.buckets) \
                    else self.buckets[-1]
                frac = (rank - cum) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            cum += c
        return self.buckets[-1]

    def exemplar_for(self, *labels) -> list:
        """[(bucket upper bound, trace id)] for every bucket of one
        series that has captured an exemplar."""
        with self._lock:
            ex = list(self._exemplars.get(labels, ()))
        bounds = [str(b) for b in self.buckets] + ["+Inf"]
        return [(bounds[i], e) for i, e in enumerate(ex) if e]

    def expose(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        with self._lock:
            snapshot = sorted(
                (labels, list(counts), self._sums[labels],
                 list(self._exemplars.get(labels, ())))
                for labels, counts in self._counts.items())
        for labels, counts, total, exemplars in snapshot:
            cum = 0
            bounds = [str(b) for b in self.buckets] + ["+Inf"]
            for i, b in enumerate(bounds):
                cum += counts[i]
                lbl = _fmt_labels(self.label_names + ("le",),
                                  labels + (b,))
                line = f"{self.name}_bucket{lbl} {cum}"
                # OpenMetrics exemplar suffix: the last sampled trace
                # that landed in this bucket (tools/trace_collect.py
                # --exemplar resolves it to a stitched trace)
                if i < len(exemplars) and exemplars[i]:
                    line += f' # {{trace_id="{exemplars[i]}"}} 1'
                out.append(line)
            base = _fmt_labels(self.label_names, labels)
            out.append(f"{self.name}_sum{base} {total}")
            out.append(f"{self.name}_count{base} {cum}")
        return out


class _Timer:
    def __init__(self, hist, labels):
        self.hist = hist
        self.labels = labels

    def __enter__(self):
        self.t0 = clockctl.monotonic()
        return self

    def __exit__(self, *exc):
        self.hist.observe(clockctl.monotonic() - self.t0, *self.labels)


def _fmt_labels(names: tuple, values: tuple) -> str:
    if not names:
        return ""
    pairs = ",".join(f'{n}="{v}"' for n, v in zip(names, values))
    return "{" + pairs + "}"


# RED (rate/errors/duration) edge instrumentation. One histogram,
# one observation site (HttpServer._dispatch), every serving edge —
# master, volume, filer, S3, WebDAV, IAM — covered by construction.
RED_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
               1.0, 2.5, 5.0, 10.0)


class RedRecorder:
    """Owns the per-server RED histogram and adapts it to the
    HttpServer hook: ``http.red = RedRecorder(registry, "filer")``.
    Labels: (server, route_family, class, status_family) — low
    cardinality by construction (route families are a closed set,
    see httpd.route_family)."""

    def __init__(self, registry: "Registry", server: str):
        self.server = server
        self.hist = registry.histogram(
            "http", "red_request_seconds",
            "request duration by edge/route-family/class/status",
            labels=("server", "route_family", "class", "status_family"),
            buckets=RED_BUCKETS)

    def observe(self, route_family: str, cls: str, status: int,
                seconds: float, exemplar: Optional[str] = None) -> None:
        self.hist.observe(seconds, self.server, route_family,
                          cls or "none", f"{status // 100}xx",
                          exemplar=exemplar)

    def snapshot(self) -> dict:
        return self.hist.snapshot()


class Registry:
    def __init__(self, namespace: str = "SeaweedFS_TPU"):
        self.namespace = namespace
        self._metrics: list = []
        self._refreshers: list = []
        self._lock = threading.Lock()

    def on_expose(self, fn) -> None:
        """Register a hook run before every exposition — servers
        refresh scrape-time gauges here so the push-gateway loop and
        /metrics handlers share identical, fresh samples."""
        self._refreshers.append(fn)

    def counter(self, subsystem: str, name: str, help_: str,
                labels: tuple = ()) -> Counter:
        return self._add(Counter(
            f"{self.namespace}_{subsystem}_{name}", help_, labels))

    def gauge(self, subsystem: str, name: str, help_: str,
              labels: tuple = ()) -> Gauge:
        return self._add(Gauge(
            f"{self.namespace}_{subsystem}_{name}", help_, labels))

    def histogram(self, subsystem: str, name: str, help_: str,
                  labels: tuple = (),
                  buckets: tuple = Histogram.DEFAULT_BUCKETS) -> Histogram:
        return self._add(Histogram(
            f"{self.namespace}_{subsystem}_{name}", help_, labels,
            buckets=buckets))

    def _add(self, m):
        # Idempotent by metric name: a component rebuilt mid-process (a
        # comparator bench swapping in a fresh PeerHealth, a reloaded
        # subsystem) gets the already-registered collector back instead
        # of appending a duplicate series to every exposition. A name
        # collision with a different type or label set is a programming
        # error and fails loudly.
        with self._lock:
            for existing in self._metrics:
                if existing.name == m.name:
                    if (type(existing) is not type(m)
                            or existing.label_names != m.label_names):
                        raise ValueError(
                            f"metric {m.name} re-registered with a "
                            f"different type or label set")
                    return existing
            self._metrics.append(m)
        return m

    def expose_text(self) -> str:
        from seaweedfs_tpu.utils import glog
        for fn in list(self._refreshers):
            try:
                fn()
            except Exception as e:
                # a broken refresher must not kill the scrape, but it
                # must not fail silently either — stale gauges look
                # exactly like a healthy idle server
                glog.vlog(1, "metrics refresher %r failed: %s",
                          getattr(fn, "__name__", fn), e)
        lines = []
        with self._lock:
            for m in self._metrics:
                lines.extend(m.expose())
        return "\n".join(lines) + "\n"

    # ---- push gateway (reference stats/metrics.go:226-247 LoopPushingMetric:
    # each process PUTs its whole registry to
    # {addr}/metrics/job/{job}/instance/{instance} every interval) ----
    def start_push(self, address: str, job: str, instance: str,
                   interval_sec: float = 15.0) -> None:
        if not address:
            return
        from seaweedfs_tpu.utils import glog
        from seaweedfs_tpu.utils.httpd import http_call
        # re-pointing the push target mid-process must not orphan the
        # previous loop: stop it (and wait briefly) before replacing
        # the stop event it watches
        self.stop_push()
        old = getattr(self, "_push_thread", None)
        if old is not None and old.is_alive():
            old.join(timeout=1.0)
        self._push_stop = threading.Event()
        stop = self._push_stop
        url = (f"http://{address}/metrics/job/{job}"
               f"/instance/{urllib.parse.quote(instance, safe='')}")

        def loop():
            while not stop.wait(interval_sec):
                try:
                    http_call("PUT", url,
                              body=self.expose_text().encode(),
                              timeout=10,
                              headers={"Content-Type": "text/plain"})
                except Exception as e:
                    glog.vlog(1, "metrics push to %s failed: %s", url, e)

        self._push_thread = threading.Thread(target=loop, daemon=True,
                                             name="metrics-push")
        self._push_thread.start()

    def stop_push(self) -> None:
        if hasattr(self, "_push_stop"):
            self._push_stop.set()
