"""Prometheus-style metrics registry + text exposition.

Functional equivalent of reference weed/stats/metrics.go (Namespace
"SeaweedFS", per-subsystem counters/gauges/histograms exposed on
/metrics). Stdlib-only implementation of the text format.
"""

from __future__ import annotations

import bisect
import threading
import time
import urllib.parse
from typing import Optional


class Counter:
    def __init__(self, name: str, help_: str, label_names: tuple = ()):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def inc(self, *labels, amount: float = 1.0):
        with self._lock:
            self._values[labels] = self._values.get(labels, 0.0) + amount

    def expose(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} counter"]
        with self._lock:  # inc() can add a label key mid-scrape
            items = sorted(self._values.items())
        for labels, v in items:
            out.append(f"{self.name}{_fmt_labels(self.label_names, labels)} {v}")
        return out


class Gauge(Counter):
    def set(self, *labels, value: float = 0.0):
        with self._lock:
            self._values[labels] = value

    def expose(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} gauge"]
        with self._lock:
            items = sorted(self._values.items())
        for labels, v in items:
            out.append(f"{self.name}{_fmt_labels(self.label_names, labels)} {v}")
        return out


class Histogram:
    DEFAULT_BUCKETS = (0.0001, 0.001, 0.01, 0.1, 1, 10)

    def __init__(self, name: str, help_: str, label_names: tuple = (),
                 buckets: tuple = DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self.buckets = sorted(buckets)
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, *labels):
        with self._lock:
            counts = self._counts.setdefault(
                labels, [0] * (len(self.buckets) + 1))
            counts[bisect.bisect_left(self.buckets, value)] += 1
            self._sums[labels] = self._sums.get(labels, 0.0) + value

    def time(self, *labels):
        return _Timer(self, labels)

    def expose(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        with self._lock:
            snapshot = sorted(
                (labels, list(counts), self._sums[labels])
                for labels, counts in self._counts.items())
        for labels, counts, total in snapshot:
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += counts[i]
                lbl = _fmt_labels(self.label_names + ("le",),
                                  labels + (str(b),))
                out.append(f"{self.name}_bucket{lbl} {cum}")
            cum += counts[-1]
            lbl = _fmt_labels(self.label_names + ("le",), labels + ("+Inf",))
            out.append(f"{self.name}_bucket{lbl} {cum}")
            base = _fmt_labels(self.label_names, labels)
            out.append(f"{self.name}_sum{base} {total}")
            out.append(f"{self.name}_count{base} {cum}")
        return out


class _Timer:
    def __init__(self, hist, labels):
        self.hist = hist
        self.labels = labels

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.hist.observe(time.perf_counter() - self.t0, *self.labels)


def _fmt_labels(names: tuple, values: tuple) -> str:
    if not names:
        return ""
    pairs = ",".join(f'{n}="{v}"' for n, v in zip(names, values))
    return "{" + pairs + "}"


class Registry:
    def __init__(self, namespace: str = "SeaweedFS_TPU"):
        self.namespace = namespace
        self._metrics: list = []
        self._refreshers: list = []
        self._lock = threading.Lock()

    def on_expose(self, fn) -> None:
        """Register a hook run before every exposition — servers
        refresh scrape-time gauges here so the push-gateway loop and
        /metrics handlers share identical, fresh samples."""
        self._refreshers.append(fn)

    def counter(self, subsystem: str, name: str, help_: str,
                labels: tuple = ()) -> Counter:
        return self._add(Counter(
            f"{self.namespace}_{subsystem}_{name}", help_, labels))

    def gauge(self, subsystem: str, name: str, help_: str,
              labels: tuple = ()) -> Gauge:
        return self._add(Gauge(
            f"{self.namespace}_{subsystem}_{name}", help_, labels))

    def histogram(self, subsystem: str, name: str, help_: str,
                  labels: tuple = ()) -> Histogram:
        return self._add(Histogram(
            f"{self.namespace}_{subsystem}_{name}", help_, labels))

    def _add(self, m):
        # Idempotent by metric name: a component rebuilt mid-process (a
        # comparator bench swapping in a fresh PeerHealth, a reloaded
        # subsystem) gets the already-registered collector back instead
        # of appending a duplicate series to every exposition. A name
        # collision with a different type or label set is a programming
        # error and fails loudly.
        with self._lock:
            for existing in self._metrics:
                if existing.name == m.name:
                    if (type(existing) is not type(m)
                            or existing.label_names != m.label_names):
                        raise ValueError(
                            f"metric {m.name} re-registered with a "
                            f"different type or label set")
                    return existing
            self._metrics.append(m)
        return m

    def expose_text(self) -> str:
        for fn in list(self._refreshers):
            try:
                fn()
            except Exception:
                pass  # a broken refresher must not kill the scrape
        lines = []
        with self._lock:
            for m in self._metrics:
                lines.extend(m.expose())
        return "\n".join(lines) + "\n"

    # ---- push gateway (reference stats/metrics.go:226-247 LoopPushingMetric:
    # each process PUTs its whole registry to
    # {addr}/metrics/job/{job}/instance/{instance} every interval) ----
    def start_push(self, address: str, job: str, instance: str,
                   interval_sec: float = 15.0) -> None:
        if not address:
            return
        from seaweedfs_tpu.utils import glog
        from seaweedfs_tpu.utils.httpd import http_call
        self._push_stop = threading.Event()
        url = (f"http://{address}/metrics/job/{job}"
               f"/instance/{urllib.parse.quote(instance, safe='')}")

        def loop():
            while not self._push_stop.wait(interval_sec):
                try:
                    http_call("PUT", url,
                              body=self.expose_text().encode(),
                              timeout=10,
                              headers={"Content-Type": "text/plain"})
                except Exception as e:
                    glog.vlog(1, "metrics push to %s failed: %s", url, e)

        self._push_thread = threading.Thread(target=loop, daemon=True)
        self._push_thread.start()

    def stop_push(self) -> None:
        if hasattr(self, "_push_stop"):
            self._push_stop.set()
