"""Space-Saving top-k sketch: bounded-memory hot-key detection.

Metwally/Agrawal/El Abbadi's Space-Saving algorithm (the standard
heavy-hitters sketch; also the one the Facebook warehouse-cluster
study's hot-block analysis presumes): track at most ``capacity``
counters; an untracked key evicts the minimum counter and inherits
its count as its error bound.  Guarantees, with N total offers:

  * every key with true count > N / capacity is tracked
  * for a tracked key:  estimate - error <= true <= estimate
  * error <= N / capacity

Sketches are mergeable (Agarwal et al., "Mergeable Summaries"): for
each key in the union, sum the per-sketch estimates, counting a key
missing from one sketch at that sketch's minimum counter value (its
mass could hide below the eviction floor — charging the floor keeps
the estimate an upper bound), then truncate back to ``capacity``.
Merging is commutative: the combine step is symmetric and the
truncation tie-breaks on the key itself.

Volume servers feed needle fids through this; filer/S3 feed paths and
tenants (stats/hotkeys.py) — the measurement prerequisite for the
hot-needle cache and filer shard routing on the roadmap.
"""

from __future__ import annotations

import threading


class SpaceSaving:
    def __init__(self, capacity: int = 64):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        # key -> [count, error]; lists so offer() mutates in place
        self._entries: dict[str, list] = {}
        self._total = 0
        self._lock = threading.Lock()

    def offer(self, key: str, count: int = 1) -> None:
        with self._lock:
            self._total += count
            e = self._entries.get(key)
            if e is not None:
                e[0] += count
                return
            if len(self._entries) < self.capacity:
                self._entries[key] = [count, 0]
                return
            # evict the minimum counter; deterministic tie-break on the
            # key keeps replays bit-reproducible
            victim = min(self._entries.items(),
                         key=lambda kv: (kv[1][0], kv[0]))
            vmin = victim[1][0]
            del self._entries[victim[0]]
            self._entries[key] = [vmin + count, vmin]

    @property
    def total(self) -> int:
        with self._lock:
            return self._total

    def _min_count(self) -> int:
        # lock held by caller
        if len(self._entries) < self.capacity:
            return 0
        return min(e[0] for e in self._entries.values())

    def top(self, k: int = 0) -> list:
        """[(key, estimate, error)] sorted by estimate desc (key as
        the deterministic tie-break), at most k entries (0 = all)."""
        with self._lock:
            items = [(key, e[0], e[1])
                     for key, e in self._entries.items()]
        items.sort(key=lambda t: (-t[1], t[0]))
        return items[:k] if k else items

    def estimate(self, key: str) -> tuple:
        """(estimate, error) for one key; an untracked key reports the
        eviction floor as both (its true count is at most that)."""
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                return e[0], e[1]
            floor = self._min_count()
            return floor, floor

    # ---- mergeable transport ----
    def snapshot(self) -> dict:
        with self._lock:
            entries = sorted(
                [k, e[0], e[1]] for k, e in self._entries.items())
            return {"capacity": self.capacity, "total": self._total,
                    "entries": entries,
                    "min_count": self._min_count()}

    def merge_from(self, snap: dict) -> None:
        """Fold another sketch's ``snapshot()`` into this one. The
        other sketch's eviction floor is charged to keys it is missing
        (count AND error), preserving the upper-bound property."""
        with self._lock:
            other = {k: (c, err) for k, c, err in snap["entries"]}
            floor_other = int(snap.get("min_count", 0))
            floor_mine = self._min_count()
            merged: dict[str, list] = {}
            for key in set(self._entries) | set(other):
                mc, me = (self._entries[key]
                          if key in self._entries
                          else (floor_mine, floor_mine))
                oc, oe = other.get(key, (floor_other, floor_other))
                merged[key] = [mc + oc, me + oe]
            ranked = sorted(merged.items(),
                            key=lambda kv: (-kv[1][0], kv[0]))
            self._entries = dict(ranked[:self.capacity])
            self._total += int(snap.get("total", 0))

    @classmethod
    def from_snapshot(cls, snap: dict) -> "SpaceSaving":
        s = cls(capacity=int(snap.get("capacity", 64)) or 64)
        s.merge_from(snap)
        return s
