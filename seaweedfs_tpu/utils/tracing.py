"""Distributed request tracing — trace/span ids riding X-Weed-Trace.

The design is the Dapper/Zipkin shape scaled down to this cluster's
existing ambient-context machinery: a trace id is minted at the first
serving edge a request hits (S3 gateway, filer, volume server, master),
the active span rides a ContextVar exactly like the ambient deadline
(X-Weed-Deadline) and traffic class (X-Weed-Class), `http_call` injects
the header on every outbound RPC, and `HttpServer._dispatch` re-enters
the scope on the far side — so replica fan-out, chunk uploads, hedged
reads and partial-repair chain hops nest as child spans with zero
per-call-site plumbing.

Each node keeps a bounded in-memory flight recorder (ring buffer):
head sampling decides at the edge whether a trace is *guaranteed*
retention, but slow and error spans are always kept (tail-based keep),
so the recorder catches the outliers even at a 1% head rate. The
recorder is served at /debug/traces; tools/trace_collect.py stitches a
trace id across nodes into Chrome trace-event JSON.

Zero-cost-when-disabled contract (same as the QoS governor's `_PASS`
path): with the tracer disabled — or no tracer wired at all — the hot
path allocates no span objects; every helper returns the shared NOOP
span whose methods are empty.

Header format: ``X-Weed-Trace: <trace_id>:<span_id>:<flags>`` with
trace_id 16 hex chars, span_id 8 hex chars, flags bit 0 = sampled.

Stdlib-only on purpose: httpd, resilience and the QoS governor all
import this module, so it must sit below them in the import DAG
(it only imports glog, which imports nothing).
"""

from __future__ import annotations

import collections
import contextlib
import os
import random
import threading
import time
from contextvars import ContextVar
from typing import Optional

from seaweedfs_tpu.utils import glog

from seaweedfs_tpu.utils import headers
TRACE_HEADER = headers.TRACE

# ring-buffer + keep-policy defaults; Tracer() callers can override
DEFAULT_CAPACITY = 2048
DEFAULT_SAMPLE_RATE = 0.01
DEFAULT_SLOW_MS = 500.0

_HEX = set("0123456789abcdef")


def _new_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


class _NoopSpan:
    """Shared do-nothing span — the `_PASS` of tracing. Returned
    whenever tracing is off so hot paths never allocate."""
    __slots__ = ()
    sampled = False
    trace_id = ""
    span_id = ""

    def annotate(self, key, value):
        pass

    def finish(self, status=200, error=""):
        pass

    def child(self, name, kind="client"):
        return self

    def header_value(self):
        return None

    def __bool__(self):
        return False


NOOP = _NoopSpan()

# the ambient span: set at the serving edge by HttpServer._dispatch,
# re-entered across thread pools by fan-out sites (which capture it
# alongside the deadline/class, since ContextVars don't cross pools)
_current: ContextVar[Optional["Span"]] = ContextVar("weed_span",
                                                    default=None)


class Span:
    __slots__ = ("tracer", "trace_id", "span_id", "parent_id", "name",
                 "kind", "start", "duration_ms", "status", "error",
                 "sampled", "annotations")

    def __init__(self, tracer: "Tracer", trace_id: str, span_id: str,
                 parent_id: str, name: str, kind: str, sampled: bool):
        self.tracer = tracer
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.kind = kind
        self.sampled = sampled
        self.start = time.time()
        self.duration_ms = 0.0
        self.status = 0
        self.error = ""
        self.annotations: Optional[dict] = None  # lazy — most spans bare

    def annotate(self, key, value) -> None:
        if self.annotations is None:
            self.annotations = {}
        self.annotations[key] = value

    def child(self, name: str, kind: str = "client") -> "Span":
        return Span(self.tracer, self.trace_id, _new_id(4), self.span_id,
                    name, kind, self.sampled)

    def finish(self, status: int = 200, error: str = "") -> None:
        self.duration_ms = (time.time() - self.start) * 1000.0
        self.status = status
        self.error = error
        self.tracer._record(self)

    def header_value(self) -> str:
        return f"{self.trace_id}:{self.span_id}:{1 if self.sampled else 0}"

    def to_dict(self) -> dict:
        d = {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "kind": self.kind,
            "node": self.tracer.node,
            "start": self.start,
            "duration_ms": round(self.duration_ms, 3),
            "status": self.status,
            "sampled": self.sampled,
        }
        if self.error:
            d["error"] = self.error
        if self.annotations:
            d["annotations"] = self.annotations
        return d


def parse_header(value: str) -> Optional[tuple[str, str, bool]]:
    """``trace:span:flags`` -> (trace_id, parent_span_id, sampled), or
    None on anything malformed (a bad header must never 500 a request)."""
    parts = value.split(":")
    if len(parts) != 3:
        return None
    tid, sid, flags = parts
    if not tid or not sid or set(tid) - _HEX or set(sid) - _HEX:
        return None
    try:
        sampled = bool(int(flags) & 1)
    except ValueError:
        return None
    return tid, sid, sampled


class Tracer:
    """Per-server trace recorder: mints edge spans, applies the
    head-sampling decision, and keeps a bounded ring of finished spans
    (sampled ones always; unsampled ones only when slow or errored)."""

    def __init__(self, node: str = "", enabled: bool = True,
                 sample_rate: float = DEFAULT_SAMPLE_RATE,
                 capacity: int = DEFAULT_CAPACITY,
                 slow_ms: float = DEFAULT_SLOW_MS):
        self.node = node
        self.enabled = enabled
        self.sample_rate = float(sample_rate)
        self.slow_ms = float(slow_ms)
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._started = 0
        self._kept = 0

    # ---- edge ----
    def server_span(self, name: str, headers) -> Span:
        """Continue an inbound trace or mint a fresh one. Returns NOOP
        when disabled — callers pay one attribute check, nothing more."""
        if not self.enabled:
            return NOOP
        hdr = headers.get(TRACE_HEADER) if headers is not None else None
        parsed = parse_header(hdr) if hdr else None
        if parsed is not None:
            tid, parent, sampled = parsed
        else:
            tid, parent = _new_id(8), ""
            sampled = random.random() < self.sample_rate
        return Span(self, tid, _new_id(4), parent, name, "server", sampled)

    def root_span(self, name: str, sampled: Optional[bool] = None) -> Span:
        """Fresh root for work with no inbound request (repair jobs,
        daemons). `sampled=None` applies the head rate."""
        if not self.enabled:
            return NOOP
        if sampled is None:
            sampled = random.random() < self.sample_rate
        return Span(self, _new_id(8), _new_id(4), "", name, "internal",
                    sampled)

    # ---- recorder ----
    def _record(self, span: Span) -> None:
        self._started += 1
        if not (span.sampled or span.error or span.status >= 500
                or span.duration_ms >= self.slow_ms):
            return
        with self._lock:
            self._ring.append(span.to_dict())
            self._kept += 1

    def snapshot(self, trace_id: str = "", min_ms: float = 0.0,
                 limit: int = 512) -> dict:
        with self._lock:
            spans = list(self._ring)
        if trace_id:
            spans = [s for s in spans if s["trace_id"] == trace_id]
        if min_ms > 0:
            spans = [s for s in spans if s["duration_ms"] >= min_ms]
        if limit and len(spans) > limit:
            spans = spans[-limit:]
        return {
            "node": self.node,
            "enabled": self.enabled,
            "sample_rate": self.sample_rate,
            "slow_ms": self.slow_ms,
            "started": self._started,
            "kept": self._kept,
            "spans": spans,
        }

    def configure(self, **kw) -> dict:
        if "enabled" in kw:
            self.enabled = bool(kw["enabled"])
        if "sample_rate" in kw:
            self.sample_rate = max(0.0, min(1.0, float(kw["sample_rate"])))
        if "slow_ms" in kw:
            self.slow_ms = float(kw["slow_ms"])
        return {"enabled": self.enabled, "sample_rate": self.sample_rate,
                "slow_ms": self.slow_ms}


# ---- ambient-scope helpers (the class_scope/deadline_scope analogues) ----

def current_span() -> Optional[Span]:
    return _current.get()


@contextlib.contextmanager
def span_scope(span):
    """Make `span` ambient. None / NOOP -> plain yield, so fan-out
    workers can re-enter unconditionally like class_scope(None)."""
    if span is None or span is NOOP:
        yield span
        return
    tok = _current.set(span)
    try:
        yield span
    finally:
        _current.reset(tok)


def attach(span):
    """Low-level scope enter for code that can't afford a context
    manager on the disabled path (HttpServer._dispatch): returns a
    reset token, or None for NOOP/None spans (nothing to undo)."""
    if span is None or span is NOOP:
        return None
    return _current.set(span)


def detach(token) -> None:
    if token is not None:
        _current.reset(token)


@contextlib.contextmanager
def child_scope(name: str, kind: str = "internal"):
    """Open a finished-on-exit child of the ambient span (NOOP when no
    trace is active). The one-liner for annotating a nested stage."""
    parent = _current.get()
    if parent is None:
        yield NOOP
        return
    span = parent.child(name, kind)
    tok = _current.set(span)
    status, error = 200, ""
    try:
        yield span
    except BaseException as e:
        status, error = 500, f"{type(e).__name__}: {e}"
        raise
    finally:
        _current.reset(tok)
        span.finish(status=status, error=error)


def annotate(key, value) -> None:
    """Attach key=value to the ambient span; free when no trace."""
    s = _current.get()
    if s is not None:
        s.annotate(key, value)


def current_trace_id() -> str:
    s = _current.get()
    return s.trace_id if s is not None else ""


# ---- glog cross-referencing (satellite: `[t=abcd1234]` in log lines).
# glog stays import-clean (it cannot import us back), so we register a
# provider it calls per line; "" when no sampled trace is ambient keeps
# the historical line format byte-identical outside traces.

def _log_context() -> str:
    s = _current.get()
    if s is not None and s.sampled:
        return f"[t={s.trace_id[:8]}] "
    return ""


glog.set_context_provider(_log_context)
