"""Shared resilience layer for every inter-node hop.

Four cooperating pieces (reference: the Go SeaweedFS leans on grpc
deadlines + util/retry.go; the policies here follow the standard
distributed-systems playbook):

- ``Deadline``: a remaining-time budget minted once at the request edge
  (HTTP handler, shell command, bench driver) and PROPAGATED through
  nested calls via the ``X-Weed-Deadline`` header, replacing hardcoded
  per-call timeouts. A nested call gets ``min(remaining, cap)`` as its
  socket timeout, so the sum of retries/hops can never exceed what the
  caller is still willing to wait (the gRPC deadline-propagation model).

- ``RetryPolicy``: exponential backoff with FULL jitter
  (``sleep = uniform(0, min(cap, base * 2**attempt))``, the AWS
  architecture-blog result: full jitter desynchronizes retry herds
  better than equal/decorrelated jitter) plus a per-destination retry
  BUDGET (the Finagle/SRE-book rule: each fresh call earns a fraction
  of a retry token, each retry spends one, so retries are bounded to
  ~ratio of traffic and cannot amplify an outage into a storm).

- ``CircuitBreaker``: per-peer closed -> open -> half-open probing on
  consecutive failures, with an EWMA latency estimate and a sliding
  latency window for p95 — the health score callers rank peers by.

- ``hedged()``: tail-tolerant fan-out for idempotent reads (Dean &
  Barroso, "The Tail at Scale"): fire the best candidate, and if it
  hasn't answered within an adaptive delay (the primary's observed
  p95), fire the next-healthiest; first success wins, losers are
  abandoned. Open circuits are skipped unless no other holder exists.

Pure stdlib plus utils.tracing (itself stdlib-only, below us in the
import DAG); imports nothing from the HTTP plane so httpd.py can use
``DeadlineExceeded`` without a cycle. Retries and hedge outcomes
annotate the ambient trace span when one is active.
"""

from __future__ import annotations

import contextvars
import random
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Callable, Iterable, Optional, Sequence

from seaweedfs_tpu.utils import headers
from seaweedfs_tpu.utils import clockctl, tracing

DEADLINE_HEADER = headers.DEADLINE  # remaining seconds, decimal string


def _now() -> float:
    """Behavioral clock: wall monotonic in production, the sim kernel's
    virtual clock when one is installed (utils/clockctl.py) — breaker
    open windows, deadlines and retry sleeps all elapse in sim time."""
    return clockctl.monotonic()

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"

# Process-wide breaker-open listeners: fn(peer_key) runs when any
# PeerHealth-tracked breaker transitions closed/half-open -> open.
# httpd's connection pool registers here to evict the dead peer's idle
# keep-alive sockets (they ride the same host the breaker just
# declared down). Hooks must be cheap and never raise.
_BREAKER_OPEN_HOOKS: list = []


def on_breaker_open(fn) -> None:
    _BREAKER_OPEN_HOOKS.append(fn)


class DeadlineExceeded(ConnectionError):
    """A call's time budget ran out before (or while) it was made.

    Subclasses ConnectionError on purpose: every existing
    ``except ConnectionError`` fail-over/fallback branch treats an
    exhausted deadline like any other transport failure."""


class Deadline:
    """Absolute point on the monotonic clock; all math is 'remaining'."""

    __slots__ = ("_at",)

    def __init__(self, at_monotonic: float):
        self._at = float(at_monotonic)

    @classmethod
    def after(cls, seconds: float) -> "Deadline":
        return cls(_now() + max(0.0, float(seconds)))

    def remaining(self) -> float:
        return max(0.0, self._at - _now())

    def expired(self) -> bool:
        return _now() >= self._at

    def timeout(self, cap: Optional[float] = None) -> float:
        """Socket timeout for one nested call: min(remaining, cap).
        Raises DeadlineExceeded when the budget is already gone, so
        callers fail fast instead of dialing with a 0s timeout."""
        rem = self.remaining()
        if rem <= 0.0:
            raise DeadlineExceeded("deadline exceeded")
        return rem if cap is None else min(rem, float(cap))

    def sub(self, seconds: float) -> "Deadline":
        """A child deadline capped at `seconds` from now — for a step
        that must leave budget for the caller's fallback (e.g. a direct
        remote fetch must not starve degraded reconstruction)."""
        return Deadline(min(self._at, _now() + float(seconds)))

    def header_value(self) -> str:
        return f"{self.remaining():.3f}"

    @classmethod
    def from_headers(cls, headers,
                     default: Optional[float] = None) -> Optional["Deadline"]:
        """Parse a propagated deadline off an incoming request; fall
        back to a fresh `default`-second budget (None -> no deadline)."""
        raw = headers.get(DEADLINE_HEADER) if headers is not None else None
        if raw:
            try:
                return cls.after(float(raw))
            except (TypeError, ValueError):
                pass
        return cls.after(default) if default is not None else None

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Deadline(remaining={self.remaining():.3f}s)"


# The ambient deadline: set once at the request edge, read by every
# nested hop without threading a parameter through each signature.
# contextvars do not cross thread boundaries on their own; pool fan-out
# sites capture current_deadline() and re-enter deadline_scope() in the
# worker (see Store._recover_one_interval).
_current_deadline: contextvars.ContextVar[Optional[Deadline]] = \
    contextvars.ContextVar("seaweedfs_tpu_deadline", default=None)


def current_deadline() -> Optional[Deadline]:
    return _current_deadline.get()


@contextmanager
def deadline_scope(deadline: Optional[Deadline]):
    token = _current_deadline.set(deadline)
    try:
        yield deadline
    finally:
        _current_deadline.reset(token)


class RetryPolicy:
    """Exponential backoff, full jitter, per-destination retry budget.

    Budget semantics (Finagle's RetryBudget): every fresh call to a
    destination deposits ``budget_ratio`` of a token; every retry
    withdraws a whole one. A destination serving healthy traffic
    accrues headroom for the occasional retry; a destination that is
    DOWN stops earning deposits, the balance drains, and retries stop —
    the herd cannot multiply load on an outage."""

    def __init__(self, attempts: int = 3, base: float = 0.1,
                 cap: float = 2.0, budget_ratio: float = 0.1,
                 budget_min: float = 10.0):
        self.attempts = max(1, int(attempts))
        self.base = float(base)
        self.cap = float(cap)
        self.budget_ratio = float(budget_ratio)
        self.budget_min = float(budget_min)
        self._budget: dict[str, float] = {}
        self._lock = threading.Lock()

    def backoff(self, attempt: int) -> float:
        """Full jitter: uniform(0, min(cap, base * 2**attempt))."""
        return random.uniform(
            0.0, min(self.cap, self.base * (2.0 ** max(0, attempt))))

    def record_call(self, dest: str = "") -> None:
        with self._lock:
            tokens = self._budget.get(dest, self.budget_min)
            self._budget[dest] = min(2.0 * self.budget_min,
                                     tokens + self.budget_ratio)

    def allow_retry(self, dest: str = "") -> bool:
        with self._lock:
            tokens = self._budget.get(dest, self.budget_min)
            if tokens < 1.0:
                return False
            self._budget[dest] = tokens - 1.0
            return True

    def budget_remaining(self, dest: str = "") -> float:
        with self._lock:
            return self._budget.get(dest, self.budget_min)

    def call(self, fn: Callable[[], object], dest: str = "",
             deadline: Optional[Deadline] = None,
             retry_on: tuple = (ConnectionError,)):
        """Run fn() with up to `attempts` tries. Sleeps are jittered and
        never overrun the deadline; an exhausted budget stops retrying
        immediately (the whole point)."""
        last: Optional[BaseException] = None
        for attempt in range(self.attempts):
            self.record_call(dest)
            try:
                return fn()
            except retry_on as e:
                last = e
                # cross-reference the retry storm in the trace: the
                # ambient span (if any) ends up carrying the highest
                # attempt number reached and the destination
                tracing.annotate("retry.failed_attempt", attempt + 1)
                if dest:
                    tracing.annotate("retry.dest", dest)
                if isinstance(e, DeadlineExceeded):
                    raise
                if attempt + 1 >= self.attempts \
                        or not self.allow_retry(dest):
                    raise
                delay = self.backoff(attempt)
                # a shed response (429/503 from a limiter) carries the
                # server's own pacing hint — obey it instead of our
                # jitter, so retries land after the load has drained
                ra = getattr(e, "retry_after", None)
                if ra is not None:
                    delay = max(0.0, float(ra))
                if deadline is not None \
                        and delay >= deadline.remaining():
                    # never sleep into (or retry inside) a budget that
                    # cannot fit the server-requested wait
                    raise
                clockctl.sleep(delay)
        raise last  # pragma: no cover - loop always returns/raises


class CircuitBreaker:
    """Per-peer closed/open/half-open breaker + latency health.

    - `failure_threshold` CONSECUTIVE failures trip closed -> open.
    - After `open_for` seconds an open breaker admits `half_open_max`
      probe calls (allow() does the transition); one probe success
      closes it, a probe failure re-opens with a fresh clock.
    - Every successful call feeds an EWMA latency and a sliding window
      the p95 hedge delay is computed from; both stay fresh from
      ordinary traffic and heartbeats alike."""

    WINDOW = 64

    def __init__(self, failure_threshold: int = 5, open_for: float = 5.0,
                 half_open_max: int = 1, ewma_alpha: float = 0.3):
        self.failure_threshold = max(1, int(failure_threshold))
        self.open_for = float(open_for)
        self.half_open_max = max(1, int(half_open_max))
        self.ewma_alpha = float(ewma_alpha)
        self.state = CLOSED
        self.ewma_s: Optional[float] = None
        self.success_total = 0
        self.failure_total = 0
        self.opened_total = 0
        self.last_ok_at = 0.0
        self.last_fail_at = 0.0
        self._consec_failures = 0
        self._opened_at = 0.0
        self._probes = 0
        self._window: deque[float] = deque(maxlen=self.WINDOW)
        self._lock = threading.Lock()

    # -- admission --
    def allow(self) -> bool:
        """May this peer be dialed right now? Transitions open ->
        half-open once `open_for` has elapsed and meters the probes."""
        with self._lock:
            if self.state == CLOSED:
                return True
            if self.state == OPEN:
                if _now() - self._opened_at < self.open_for:
                    return False
                self.state = HALF_OPEN
                self._probes = 0
            # HALF_OPEN: meter the probe slots
            if self._probes < self.half_open_max:
                self._probes += 1
                return True
            return False

    def probe_ripe(self) -> bool:
        """True when the breaker is open and due a half-open probe —
        hedging piggybacks a probe on real traffic (no separate pinger)."""
        with self._lock:
            if self.state == HALF_OPEN:
                return self._probes < self.half_open_max
            return (self.state == OPEN
                    and _now() - self._opened_at >= self.open_for)

    # -- outcomes --
    def record(self, ok: bool, latency_s: Optional[float] = None) -> None:
        with self._lock:
            if ok:
                self.success_total += 1
                self.last_ok_at = _now()
                self._consec_failures = 0
                if self.state != CLOSED:
                    self.state = CLOSED
                    self._probes = 0
                if latency_s is not None:
                    lat = max(0.0, float(latency_s))
                    self._window.append(lat)
                    self.ewma_s = lat if self.ewma_s is None else \
                        (self.ewma_alpha * lat
                         + (1.0 - self.ewma_alpha) * self.ewma_s)
                return
            self.failure_total += 1
            self.last_fail_at = _now()
            self._consec_failures += 1
            if self.state == HALF_OPEN \
                    or (self.state == CLOSED
                        and self._consec_failures >= self.failure_threshold):
                self.state = OPEN
                self._opened_at = _now()
                self.opened_total += 1
                self._probes = 0
            elif self.state == OPEN:
                # a failed ripe probe (or a forced dial on a sole
                # holder) re-arms the open window — the peer proved it
                # is still down, so back off for another `open_for`
                self._opened_at = _now()

    # -- health --
    def p95_s(self) -> Optional[float]:
        with self._lock:
            if not self._window:
                return None
            ordered = sorted(self._window)
            return ordered[min(len(ordered) - 1,
                               int(0.95 * len(ordered)))]

    def score(self) -> float:
        """Lower is healthier. EWMA latency, penalized by breaker state
        so rankings prefer closed < half-open < open; unknown peers get
        a neutral prior so they are tried before known-slow ones but
        after known-fast ones."""
        with self._lock:
            base = self.ewma_s if self.ewma_s is not None else 0.020
            if self.state == CLOSED:
                return base
            if self.state == HALF_OPEN:
                return 10.0 + base
            return 100.0 + base

    def snapshot(self) -> dict:
        with self._lock:
            now = _now()
            return {
                "state": self.state,
                "ewma_ms": (round(self.ewma_s * 1000, 2)
                            if self.ewma_s is not None else None),
                "consecutive_failures": self._consec_failures,
                "success_total": self.success_total,
                "failure_total": self.failure_total,
                "opened_total": self.opened_total,
                "last_ok_s_ago": (round(now - self.last_ok_at, 1)
                                  if self.last_ok_at else None),
                "last_fail_s_ago": (round(now - self.last_fail_at, 1)
                                    if self.last_fail_at else None),
            }


class PeerHealth:
    """Registry of per-peer breakers + the ranking/hedging policy knobs.

    One instance per server process (each volume server, the master,
    clients that want it); peers are keyed by 'ip:port'. Breaker
    parameters are plain attributes so tests and operators can tighten
    them without growing constructor signatures everywhere."""

    def __init__(self, metrics=None, failure_threshold: int = 5,
                 open_for: float = 5.0,
                 hedge_default_s: float = 0.05,
                 hedge_min_s: float = 0.005, hedge_max_s: float = 0.5):
        self.failure_threshold = failure_threshold
        self.open_for = open_for
        self.hedge_default_s = hedge_default_s
        self.hedge_min_s = hedge_min_s
        self.hedge_max_s = hedge_max_s
        self._peers: dict[str, CircuitBreaker] = {}
        self._lock = threading.Lock()
        if metrics is not None:
            self._c_outcomes = metrics.counter(
                "resilience", "peer_calls_total",
                "per-peer call outcomes", ("result",))
            self._c_hedges = metrics.counter(
                "resilience", "hedges_total",
                "hedged backup requests", ("outcome",))
            self._g_state = metrics.gauge(
                "resilience", "breakers", "breakers per state", ("state",))
            metrics.on_expose(self._refresh_gauges)
        else:
            self._c_outcomes = self._c_hedges = self._g_state = None

    def _refresh_gauges(self) -> None:
        counts = {CLOSED: 0, OPEN: 0, HALF_OPEN: 0}
        with self._lock:
            for br in self._peers.values():
                counts[br.state] = counts.get(br.state, 0) + 1
        for state, n in counts.items():
            self._g_state.set(state, value=n)

    def breaker(self, url: str) -> CircuitBreaker:
        with self._lock:
            br = self._peers.get(url)
            if br is None:
                br = CircuitBreaker(
                    failure_threshold=self.failure_threshold,
                    open_for=self.open_for)
                self._peers[url] = br
            return br

    def allow(self, url: str) -> bool:
        return self.breaker(url).allow()

    def record(self, url: str, ok: bool,
               latency_s: Optional[float] = None) -> None:
        br = self.breaker(url)
        was_open = br.state == OPEN
        br.record(ok, latency_s)
        if not ok and br.state == OPEN and not was_open:
            for fn in _BREAKER_OPEN_HOOKS:
                try:
                    fn(url)
                except Exception:
                    pass
        if self._c_outcomes is not None:
            self._c_outcomes.inc("ok" if ok else "error")

    def count_hedge(self, outcome: str) -> None:
        if self._c_hedges is not None:
            self._c_hedges.inc(outcome)

    def rank(self, urls: Iterable[str],
             pressure: Optional[dict] = None) -> list[str]:
        """Healthiest first: closed before half-open before open (open
        circuits sort last — 'skipped unless no other holder exists'),
        ties broken by the EWMA-latency score. Passive: no probe slots
        are consumed here; allow() happens at dial time.

        `pressure` ({url: qos_pressure [0,1]} from heartbeats) breaks
        ties among SIMILARLY healthy peers: latency is quantized into
        20ms buckets so a few ms of EWMA noise can't override a holder
        that is visibly shedding load, while a genuinely slower peer
        still loses to a fast loaded one."""
        def key(u: str):
            br = self.breaker(u)
            state_rank = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}[br.state]
            if br.state == OPEN and br.probe_ripe():
                state_rank = 1  # due a probe: better than hard-open
            if pressure is None:
                return (state_rank, br.score())
            s = br.score()
            return (state_rank, round(s / 0.020),
                    pressure.get(u, 0.0), s)
        return sorted(urls, key=key)

    def hedge_delay(self, primary: Optional[str] = None) -> float:
        """Adaptive hedge trigger: the primary peer's observed p95 (the
        Tail-at-Scale rule — hedge only past the latency you normally
        see), clamped to [hedge_min, hedge_max]; defaults before any
        observation exists."""
        p95 = self.breaker(primary).p95_s() if primary else None
        if p95 is None:
            return self.hedge_default_s
        return max(self.hedge_min_s, min(self.hedge_max_s, 1.5 * p95))

    def snapshot(self) -> dict:
        with self._lock:
            peers = dict(self._peers)
        return {url: br.snapshot() for url, br in sorted(peers.items())}


# Shared daemon pool for hedged fan-out. Bounded: a wedged peer parks a
# worker until its own timeout, it cannot accumulate threads unboundedly.
_hedge_pool = None
_hedge_pool_lock = threading.Lock()


def _get_hedge_pool():
    global _hedge_pool
    if _hedge_pool is None:
        with _hedge_pool_lock:
            if _hedge_pool is None:
                from concurrent.futures import ThreadPoolExecutor
                _hedge_pool = ThreadPoolExecutor(
                    max_workers=32, thread_name_prefix="hedge")
    return _hedge_pool


def hedged(fn: Callable[[str], object], candidates: Sequence[str],
           health: Optional[PeerHealth] = None,
           delay: Optional[float] = None,
           deadline: Optional[Deadline] = None):
    """Tail-tolerant call: try candidates[0]; if it hasn't succeeded
    within `delay` (or it failed), fire the next candidate; first
    not-None result wins and the rest are abandoned. fn must be
    idempotent (reads). Outcomes and latencies are recorded into
    `health`; candidates whose breaker rejects the dial are skipped —
    unless every candidate is rejected, in which case the first is
    forced (an open circuit must not make a sole holder unreachable).
    A candidate due a half-open probe is fired immediately alongside
    the primary, so real traffic doubles as the probe. Returns the
    winning result or None."""
    from concurrent.futures import FIRST_COMPLETED, wait

    if not candidates:
        return None
    order = list(candidates)
    if health is not None:
        # PASSIVE screening — allow() would consume a half-open probe
        # slot for candidates the hedge may never dial, wedging the
        # breaker in half-open; here a dialed ripe candidate IS the
        # probe and record() below does the state transition
        usable = [c for c in order
                  if health.breaker(c).state != OPEN
                  or health.breaker(c).probe_ripe()]
        order = usable if usable else [order[0]]
    if delay is None:
        delay = (health.hedge_delay(order[0])
                 if health is not None else 0.05)
    dl = deadline or current_deadline()
    pool = _get_hedge_pool()
    ctx_dl = dl  # propagate into workers
    # ContextVars don't cross the pool: capture the ambient span here
    # and re-enter it in each worker, so every leg's http_call becomes
    # a child span of the request that hedged
    ctx_sp = tracing.current_span()

    def run_one(c: str):
        t0 = _now()
        try:
            with deadline_scope(ctx_dl), tracing.span_scope(ctx_sp):
                out = fn(c)
        except Exception:
            out = None
        lat = _now() - t0
        if health is not None:
            health.record(c, out is not None, lat if out is not None
                          else None)
        return out

    pending = {pool.submit(run_one, order[0]): order[0]}
    nxt = 1
    # a ripe open breaker rides along as an immediate probe
    if health is not None and nxt < len(order) \
            and health.breaker(order[nxt]).probe_ripe():
        pending[pool.submit(run_one, order[nxt])] = order[nxt]
        if health is not None:
            health.count_hedge("probe")
        nxt += 1
    first_fire = True
    while pending:
        if dl is not None and dl.remaining() <= 0:
            for f in pending:
                f.cancel()
            return None
        wait_s = delay if (first_fire and nxt < len(order)) else 0.5
        if dl is not None:
            wait_s = min(wait_s, max(0.001, dl.remaining()))
        done, _ = wait(pending, timeout=wait_s,
                       return_when=FIRST_COMPLETED)
        for f in done:
            result = f.result()
            won = pending.pop(f)
            if result is not None:
                if ctx_sp is not None:
                    ctx_sp.annotate("hedge.winner", won)
                    ctx_sp.annotate("hedge.legs_fired", nxt)
                for g in pending:
                    g.cancel()
                return result
        if nxt < len(order) and (done or first_fire):
            # primary too slow (hedge) or failed (fail-over): fire next
            if not done and health is not None:
                health.count_hedge("fired")
            pending[pool.submit(run_one, order[nxt])] = order[nxt]
            nxt += 1
            first_fire = False
        elif not done and not first_fire and nxt >= len(order) \
                and not pending:
            break
        elif not done and nxt >= len(order):
            # nothing left to fire; keep waiting on what's in flight
            first_fire = False
    return None
