"""AES-256-GCM via ctypes on the system libcrypto (OpenSSL EVP API).

Drop-in for `cryptography.hazmat.primitives.ciphers.aead.AESGCM` in the
one shape utils/cipher.py uses (no AAD). The container ships OpenSSL but
not the `cryptography` wheel; linking libcrypto directly keeps chunk
encryption working without a pip install, same approach as
native/rs_native.py takes for the GF kernels.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import threading

_EVP_CTRL_GCM_SET_IVLEN = 0x9
_EVP_CTRL_GCM_GET_TAG = 0x10
_EVP_CTRL_GCM_SET_TAG = 0x11
_TAG_LEN = 16

_lock = threading.Lock()
_lib = None
_tried = False


def _load():
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        name = ctypes.util.find_library("crypto")
        candidates = [name] if name else []
        candidates += ["libcrypto.so.3", "libcrypto.so.1.1", "libcrypto.so"]
        for cand in candidates:
            try:
                lib = ctypes.CDLL(cand)
                lib.EVP_CIPHER_CTX_new.restype = ctypes.c_void_p
                lib.EVP_aes_256_gcm.restype = ctypes.c_void_p
                lib.EVP_CIPHER_CTX_free.argtypes = [ctypes.c_void_p]
                for fn in ("EVP_EncryptInit_ex", "EVP_DecryptInit_ex"):
                    getattr(lib, fn).argtypes = [
                        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                        ctypes.c_char_p, ctypes.c_char_p]
                for fn in ("EVP_EncryptUpdate", "EVP_DecryptUpdate"):
                    getattr(lib, fn).argtypes = [
                        ctypes.c_void_p, ctypes.c_char_p,
                        ctypes.POINTER(ctypes.c_int), ctypes.c_char_p,
                        ctypes.c_int]
                for fn in ("EVP_EncryptFinal_ex", "EVP_DecryptFinal_ex"):
                    getattr(lib, fn).argtypes = [
                        ctypes.c_void_p, ctypes.c_char_p,
                        ctypes.POINTER(ctypes.c_int)]
                lib.EVP_CIPHER_CTX_ctrl.argtypes = [
                    ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
                    ctypes.c_void_p]
                _lib = lib
                return _lib
            except (OSError, AttributeError):
                continue
        return None


def available() -> bool:
    return _load() is not None


class InvalidTag(Exception):
    pass


class AESGCM:
    """API-compatible subset of cryptography's AESGCM (no AAD support —
    utils/cipher.py always passes None)."""

    def __init__(self, key: bytes):
        if len(key) not in (16, 24, 32):
            raise ValueError("AESGCM key must be 128/192/256 bits")
        if len(key) != 32:
            raise ValueError("libcrypto fallback supports 256-bit keys only")
        lib = _load()
        if lib is None:
            raise RuntimeError("libcrypto unavailable for AES-GCM")
        self._key = key
        self._lib = lib

    def encrypt(self, nonce: bytes, data: bytes, aad) -> bytes:
        assert aad is None, "AAD unsupported in libcrypto fallback"
        lib = self._lib
        ctx = lib.EVP_CIPHER_CTX_new()
        try:
            if not lib.EVP_EncryptInit_ex(ctx, lib.EVP_aes_256_gcm(),
                                          None, None, None):
                raise RuntimeError("EVP_EncryptInit_ex(cipher) failed")
            lib.EVP_CIPHER_CTX_ctrl(ctx, _EVP_CTRL_GCM_SET_IVLEN,
                                    len(nonce), None)
            if not lib.EVP_EncryptInit_ex(ctx, None, None, self._key, nonce):
                raise RuntimeError("EVP_EncryptInit_ex(key/iv) failed")
            out = ctypes.create_string_buffer(len(data) or 1)
            outl = ctypes.c_int(0)
            if data and not lib.EVP_EncryptUpdate(ctx, out, ctypes.byref(outl),
                                                  data, len(data)):
                raise RuntimeError("EVP_EncryptUpdate failed")
            fin = ctypes.create_string_buffer(16)
            finl = ctypes.c_int(0)
            if not lib.EVP_EncryptFinal_ex(ctx, fin, ctypes.byref(finl)):
                raise RuntimeError("EVP_EncryptFinal_ex failed")
            tag = ctypes.create_string_buffer(_TAG_LEN)
            lib.EVP_CIPHER_CTX_ctrl(ctx, _EVP_CTRL_GCM_GET_TAG, _TAG_LEN, tag)
            return (out.raw[:outl.value] + fin.raw[:finl.value]
                    + tag.raw[:_TAG_LEN])
        finally:
            lib.EVP_CIPHER_CTX_free(ctx)

    def decrypt(self, nonce: bytes, data: bytes, aad) -> bytes:
        assert aad is None, "AAD unsupported in libcrypto fallback"
        if len(data) < _TAG_LEN:
            raise InvalidTag("ciphertext shorter than GCM tag")
        ct, tag = data[:-_TAG_LEN], data[-_TAG_LEN:]
        lib = self._lib
        ctx = lib.EVP_CIPHER_CTX_new()
        try:
            if not lib.EVP_DecryptInit_ex(ctx, lib.EVP_aes_256_gcm(),
                                          None, None, None):
                raise RuntimeError("EVP_DecryptInit_ex(cipher) failed")
            lib.EVP_CIPHER_CTX_ctrl(ctx, _EVP_CTRL_GCM_SET_IVLEN,
                                    len(nonce), None)
            if not lib.EVP_DecryptInit_ex(ctx, None, None, self._key, nonce):
                raise RuntimeError("EVP_DecryptInit_ex(key/iv) failed")
            out = ctypes.create_string_buffer(len(ct) or 1)
            outl = ctypes.c_int(0)
            if ct and not lib.EVP_DecryptUpdate(ctx, out, ctypes.byref(outl),
                                                ct, len(ct)):
                raise RuntimeError("EVP_DecryptUpdate failed")
            tagbuf = ctypes.create_string_buffer(tag, _TAG_LEN)
            lib.EVP_CIPHER_CTX_ctrl(ctx, _EVP_CTRL_GCM_SET_TAG, _TAG_LEN,
                                    tagbuf)
            fin = ctypes.create_string_buffer(16)
            finl = ctypes.c_int(0)
            if not lib.EVP_DecryptFinal_ex(ctx, fin, ctypes.byref(finl)):
                raise InvalidTag("GCM tag verification failed")
            return out.raw[:outl.value] + fin.raw[:finl.value]
        finally:
            lib.EVP_CIPHER_CTX_free(ctx)
