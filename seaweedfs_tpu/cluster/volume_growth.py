"""Volume growth: choose servers honoring replica placement, then allocate.

Functional equivalent of reference weed/topology/volume_growth.go:46-259
(findEmptySlotsForOneVolume): pick a main datacenter/rack/server weighted by
free slots, satisfying the xyz placement (x other DCs, y other racks in the
main DC, z other servers in the main rack), then allocate the volume on all
chosen nodes via a callback RPC.
"""

from __future__ import annotations

import random
from typing import Callable, Optional

from seaweedfs_tpu.cluster.topology import DataCenter, DataNode, Rack, Topology
from seaweedfs_tpu.storage.super_block import ReplicaPlacement


class NoFreeSpaceError(Exception):
    pass


def _weighted_pick(candidates, weight_fn):
    weights = [max(0.0, weight_fn(c)) for c in candidates]
    total = sum(weights)
    if total <= 0:
        raise NoFreeSpaceError("no candidates with free space")
    r = random.random() * total
    acc = 0.0
    for c, w in zip(candidates, weights):
        acc += w
        if r <= acc:
            return c
    return candidates[-1]


def find_empty_slots(topo: Topology, rp: ReplicaPlacement,
                     preferred_dc: str = "",
                     disk: str = "") -> list[DataNode]:
    """Choose rp.copy_count nodes for one volume's replicas. Every
    free-space check is tier-scoped: the empty disk type IS the hdd
    tier (reference types.DiskType), so untyped growth never lands on
    a node that only has ssd slots."""
    def fs(obj) -> float:
        # a draining node takes no new volumes (graceful-drain
        # contract); dc/rack aggregates still count it, but the
        # node-level weighted pick zeroes it out
        if getattr(obj, "draining", False):
            return 0.0
        return obj.free_space(disk or "")

    dcs = [dc for dc in topo.data_centers.values() if fs(dc) >= 1]
    if preferred_dc:
        dcs = [dc for dc in dcs if dc.id == preferred_dc]
    # main DC must fit 1 + same_rack + diff_rack copies; need diff_dc_count
    # other DCs with >= 1 slot
    main_needed = 1 + rp.same_rack_count + rp.diff_rack_count
    viable = [dc for dc in dcs if fs(dc) >= main_needed]
    if not viable or len(topo.data_centers) < rp.diff_dc_count + 1:
        raise NoFreeSpaceError(
            f"not enough data centers for placement {rp}"
            + (f" on disk type {disk!r}" if disk else ""))
    main_dc = _weighted_pick(viable, fs)

    # main rack must fit 1 + same_rack copies; need diff_rack_count other
    # racks in main DC
    racks = [r for r in main_dc.racks.values()
             if fs(r) >= 1 + rp.same_rack_count
             and len([n for n in r.nodes.values() if fs(n) >= 1])
             >= 1 + rp.same_rack_count]
    racks = [r for r in racks
             if len([x for x in main_dc.racks.values()
                     if x is not r and fs(x) >= 1])
             >= rp.diff_rack_count]
    if not racks:
        raise NoFreeSpaceError(f"not enough racks in {main_dc.id} for {rp}")
    main_rack = _weighted_pick(racks, fs)

    nodes = [n for n in main_rack.nodes.values() if fs(n) >= 1]
    if len(nodes) < 1 + rp.same_rack_count:
        raise NoFreeSpaceError(f"not enough servers in rack {main_rack.id}")
    main_node = _weighted_pick(nodes, fs)

    chosen = [main_node]
    # z: other servers in the same rack
    others = [n for n in nodes if n is not main_node]
    random.shuffle(others)
    chosen += others[:rp.same_rack_count]
    if len(chosen) < 1 + rp.same_rack_count:
        raise NoFreeSpaceError("not enough same-rack servers")
    # y: other racks in main DC
    other_racks = [r for r in main_dc.racks.values()
                   if r is not main_rack and fs(r) >= 1]
    random.shuffle(other_racks)
    for r in other_racks[:rp.diff_rack_count]:
        rnodes = [n for n in r.nodes.values() if fs(n) >= 1]
        chosen.append(_weighted_pick(rnodes, fs))
    if len(chosen) < 1 + rp.same_rack_count + rp.diff_rack_count:
        raise NoFreeSpaceError("not enough diff-rack servers")
    # x: other data centers
    other_dcs = [dc for dc in topo.data_centers.values()
                 if dc is not main_dc and fs(dc) >= 1]
    random.shuffle(other_dcs)
    for dc in other_dcs[:rp.diff_dc_count]:
        all_nodes = [n for r in dc.racks.values()
                     for n in r.nodes.values() if fs(n) >= 1]
        chosen.append(_weighted_pick(all_nodes, fs))
    if len(chosen) != rp.copy_count:
        raise NoFreeSpaceError(
            f"found {len(chosen)} slots, need {rp.copy_count}")
    return chosen


def find_ec_group_slots(topo: Topology, scheme,
                        disk: str = "") -> list[DataNode]:
    """Choose a target node per EC shard 0..total-1 with LRC group
    alignment: every member of a local group (its data shards + the
    group's local parity) lands in one rack, each group on a different
    rack when the topology has enough, and the global parities on racks
    outside every group's. A group-local repair then never crosses rack
    boundaries. Raises NoFreeSpaceError when fewer than two racks have
    free space or a group does not fit its rack — callers fall back to
    the balanced spread (shell/ec_plan.balanced_ec_distribution)."""
    def fs(n) -> float:
        if getattr(n, "draining", False):
            return 0.0
        return n.free_space(disk or "")

    by_rack = {rk: [n for n in ns if fs(n) >= 1]
               for rk, ns in topo.nodes_by_rack().items()}
    by_rack = {rk: ns for rk, ns in by_rack.items() if ns}
    racks = sorted(by_rack,
                   key=lambda rk: -sum(fs(n) for n in by_rack[rk]))
    if len(racks) < 2:
        raise NoFreeSpaceError(
            "group-aligned EC placement needs >= 2 racks with free space")
    targets: list[Optional[DataNode]] = [None] * scheme.total_shards
    budget = {n.id: int(fs(n)) for ns in by_rack.values() for n in ns}

    def place(sids: list[int], rack_names: list[str]) -> None:
        pool = sorted((n for rk in rack_names for n in by_rack[rk]),
                      key=lambda n: -budget[n.id])
        i = 0
        for sid in sids:
            for _ in range(len(pool)):
                n = pool[i % len(pool)]
                i += 1
                if budget[n.id] > 0:
                    budget[n.id] -= 1
                    targets[sid] = n
                    break
            else:
                raise NoFreeSpaceError(
                    f"no free slot for shard {sid} in racks {rack_names}")

    group_racks: list[str] = []
    for g in range(scheme.local_groups):
        rk = racks[g % len(racks)]
        group_racks.append(rk)
        place(scheme.group_members(g), [rk])
    others = [rk for rk in racks if rk not in group_racks] or racks
    place(scheme.global_parity_ids(), others)
    return targets


# (node, vid, collection, rp, ttl, disk) -> success
AllocateFn = Callable[[DataNode, int, str, str, str, str], bool]


def grow_by_type(topo: Topology, collection: str, rp_str: str, ttl: str,
                 allocate: AllocateFn, count: int = 1,
                 preferred_dc: str = "", disk: str = "") -> list[int]:
    """Grow `count` volumes; `allocate(node, vid, collection, rp, ttl,
    disk)` is the AllocateVolume RPC (reference volume_growth.go
    AutomaticGrowByType). Returns the new volume ids."""
    rp = ReplicaPlacement.parse(rp_str)
    grown = []
    for _ in range(count):
        nodes = find_empty_slots(topo, rp, preferred_dc, disk)
        vid = topo.next_volume_id()
        ok = all(allocate(n, vid, collection, rp_str, ttl, disk)
                 for n in nodes)
        if ok:
            grown.append(vid)
    return grown
