"""File-id sequencers (reference weed/sequence: memory_sequencer.go,
snowflake_sequencer.go)."""

from __future__ import annotations

import threading
import time


class MemorySequencer:
    def __init__(self, start: int = 1):
        self._counter = start
        self._lock = threading.Lock()

    def next_file_id(self, count: int = 1) -> int:
        with self._lock:
            start = self._counter
            self._counter += count
            return start

    def set_max(self, seen: int) -> None:
        with self._lock:
            if seen > self._counter:
                self._counter = seen + 1

    def peek(self) -> int:
        return self._counter


class SnowflakeSequencer:
    """41-bit ms timestamp | 10-bit node | 12-bit sequence."""

    EPOCH_MS = 1577836800000  # 2020-01-01

    def __init__(self, node_id: int = 1):
        assert 0 <= node_id < 1024
        self.node_id = node_id
        self._lock = threading.Lock()
        self._last_ms = 0
        self._seq = 0

    def next_file_id(self, count: int = 1) -> int:
        with self._lock:
            now = int(time.time() * 1000) - self.EPOCH_MS  # weedlint: disable=raw-clock — IDs embed the absolute epoch
            if now == self._last_ms:
                self._seq += count
                if self._seq >= 4096:
                    while now <= self._last_ms:
                        now = int(time.time() * 1000) - self.EPOCH_MS  # weedlint: disable=raw-clock — IDs embed the absolute epoch
                    self._seq = 0
            else:
                self._seq = 0
            self._last_ms = now
            return (now << 22) | (self.node_id << 12) | self._seq

    def set_max(self, seen: int) -> None:
        pass
