"""Raft consensus for the master group.

The reference runs raft for master HA (weed/server/raft_server.go — a
goraft fork — and raft_hashicorp.go), replicating MaxVolumeId commands
(weed/topology/cluster_commands.go) and snapshotting topology state.
This is a from-scratch implementation of the same protocol over the
masters' HTTP/JSON plane:

- leader election with randomized timeouts, persisted term + vote
- replicated log with the standard AppendEntries consistency check
- commit on majority match, entries applied in order via ``apply_fn``
- log compaction: snapshot of the applied state (``snapshot_fn`` /
  ``restore_fn``) + InstallSnapshot for lagging followers

Node ids are the masters' "host:port" HTTP urls; RPCs travel as JSON
POSTs to /raft/vote, /raft/append, /raft/snapshot on the peer masters.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Callable, Optional

from seaweedfs_tpu.utils import clockctl
from seaweedfs_tpu.utils.httpd import http_json

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"

COMPACT_THRESHOLD = 4096  # applied entries kept before snapshotting


def _default_send(peer: str, path: str, body: dict, timeout: float) -> dict:
    return http_json("POST", f"http://{peer}{path}", body, timeout=timeout)


class RaftNode:
    def __init__(self, node_id: str, peers: list[str],
                 apply_fn: Callable[[dict], None],
                 snapshot_fn: Optional[Callable[[], dict]] = None,
                 restore_fn: Optional[Callable[[dict], None]] = None,
                 state_path: str = "",
                 send_fn: Callable = _default_send,
                 election_timeout: tuple[float, float] = (0.8, 1.6),
                 heartbeat_interval: float = 0.25,
                 compact_threshold: int = COMPACT_THRESHOLD):
        self.id = node_id
        self.peers = [p for p in peers if p != node_id]
        self.apply_fn = apply_fn
        self.snapshot_fn = snapshot_fn or (lambda: {})
        self.restore_fn = restore_fn or (lambda s: None)
        self.state_path = state_path
        self.send = send_fn
        self.election_timeout = election_timeout
        self.heartbeat_interval = heartbeat_interval
        self.compact_threshold = compact_threshold

        # persistent state
        self.current_term = 0
        self.voted_for: Optional[str] = None
        self.log: list[dict] = []  # {"term": int, "command": dict}
        # snapshot covers log indices 1..snap_index (1-based, inclusive)
        self.snap_index = 0
        self.snap_term = 0
        self.snap_state: dict = {}

        # volatile state
        self.state = FOLLOWER
        self.leader_id: Optional[str] = None
        self.commit_index = 0
        self.last_applied = 0
        self.next_index: dict[str, int] = {}
        self.match_index: dict[str, int] = {}

        self.lock = threading.RLock()
        self._commit_cond = threading.Condition(self.lock)
        self._last_heartbeat = clockctl.monotonic()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # check-quorum state: last successful round-trip per peer, and
        # one in-flight append per peer so slow peers don't pile threads
        self._peer_acked: dict[str, float] = {}
        self._inflight: set[str] = set()
        # index of the no-op barrier appended on election; the leader is
        # not "ready" (safe to serve) until it commits, which also
        # commits every inherited prior-term entry
        self._noop_index = 0
        self._load()

    # ---- index helpers (log is 1-based through the snapshot) ----
    def _last_index(self) -> int:
        return self.snap_index + len(self.log)

    def _term_at(self, index: int) -> int:
        if index == self.snap_index:
            return self.snap_term
        if index == 0:
            return 0
        return self.log[index - self.snap_index - 1]["term"]

    def _entry_at(self, index: int) -> dict:
        return self.log[index - self.snap_index - 1]

    # ---- persistence ----
    def _load(self) -> None:
        if not self.state_path or not os.path.exists(self.state_path):
            return
        try:
            with open(self.state_path) as f:
                st = json.load(f)
        except (OSError, ValueError):
            return
        self.current_term = st.get("term", 0)
        self.voted_for = st.get("voted_for")
        self.log = st.get("log", [])
        self.snap_index = st.get("snap_index", 0)
        self.snap_term = st.get("snap_term", 0)
        self.snap_state = st.get("snap_state", {})
        if "peers" in st:
            # committed membership changes override the boot -peers
            # list (the operator's flag predates them)
            self.peers = [p for p in st["peers"] if p != self.id]
        if self.snap_state:
            self.restore_fn(self.snap_state)
            if "peers" not in st:
                # fallback for pre-membership state files only: the
                # persisted peer list is written on every _persist and is
                # therefore always >= the snapshot's age — letting the
                # snapshot's member set win here would revert a
                # membership change committed after the last compaction
                self._apply_snapshot_membership(self.snap_state)
        self.commit_index = self.last_applied = self.snap_index
        # re-apply entries that were committed before shutdown is not
        # possible to know — raft re-commits them once a leader emerges

    def _persist(self) -> None:
        if not self.state_path:
            return
        tmp = self.state_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump({"term": self.current_term,
                           "voted_for": self.voted_for,
                           "log": self.log,
                           "peers": self.peers,
                           "snap_index": self.snap_index,
                           "snap_term": self.snap_term,
                           "snap_state": self.snap_state}, f)
            os.replace(tmp, self.state_path)
        except OSError:
            pass

    # ---- lifecycle ----
    def start(self) -> None:
        t = threading.Thread(target=self._ticker, daemon=True,
                             name="raft-ticker")
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        with self.lock:
            self._persist()

    def _ticker(self) -> None:
        while not self._stop.wait(0.05):
            with self.lock:
                state = self.state
                elapsed = clockctl.monotonic() - self._last_heartbeat
                timeout = self._current_timeout
            if state == LEADER:
                self._check_quorum()
                self._broadcast_append()
                self._stop.wait(self.heartbeat_interval)
            elif elapsed >= timeout:
                self._start_election()

    def _check_quorum(self) -> None:
        """Step down if a majority of peers has been unreachable for a
        full election timeout — a partitioned leader must stop serving
        (prevents split-brain writes on the minority side)."""
        with self.lock:
            if self.state != LEADER or not self.peers:
                return
            lease = self.election_timeout[1]
            now = clockctl.monotonic()
            fresh = sum(1 for p in self.peers
                        if now - self._peer_acked.get(p, 0) < lease)
            # self counts toward the majority
            if (fresh + 1) * 2 <= len(self.peers) + 1:
                self.state = FOLLOWER
                self.leader_id = None
                self._reset_election_timer()
                self._commit_cond.notify_all()

    @property
    def _current_timeout(self) -> float:
        # randomized per-node, re-rolled on each reset
        if not hasattr(self, "_timeout_roll"):
            self._timeout_roll = random.uniform(*self.election_timeout)
        return self._timeout_roll

    def _reset_election_timer(self) -> None:
        self._last_heartbeat = clockctl.monotonic()
        self._timeout_roll = random.uniform(*self.election_timeout)

    # ---- election ----
    def _start_election(self) -> None:
        with self.lock:
            if not self.peers:
                # single-node group: self-elect immediately
                self.current_term += 1
                self._become_leader_locked()
                return
            self.state = CANDIDATE
            self.current_term += 1
            self.voted_for = self.id
            self._persist()
            term = self.current_term
            self._reset_election_timer()
            last_idx = self._last_index()
            last_term = self._term_at(last_idx)
        votes = [self.id]
        votes_lock = threading.Lock()
        done = threading.Event()

        def ask(peer: str):
            try:
                resp = self.send(peer, "/raft/vote", {
                    "term": term, "candidate_id": self.id,
                    "last_log_index": last_idx,
                    "last_log_term": last_term}, 1.0)
            except Exception:
                return
            with self.lock:
                if resp.get("term", 0) > self.current_term:
                    self._step_down(resp["term"])
                    done.set()
                    return
            if resp.get("vote_granted"):
                with votes_lock:
                    votes.append(peer)
                    if len(votes) * 2 > len(self.peers) + 1:
                        done.set()

        threads = [threading.Thread(target=ask, args=(p,), daemon=True,
                                    name="raft-vote")
                   for p in self.peers]
        for t in threads:
            t.start()
        done.wait(timeout=1.0)
        with self.lock:
            if (self.state == CANDIDATE and self.current_term == term
                    and len(votes) * 2 > len(self.peers) + 1):
                self._become_leader_locked()

    def _become_leader_locked(self) -> None:
        self.state = LEADER
        self.leader_id = self.id
        nxt = self._last_index() + 1
        self.next_index = {p: nxt for p in self.peers}
        self.match_index = {p: 0 for p in self.peers}
        now = clockctl.monotonic()
        self._peer_acked = {p: now for p in self.peers}  # quorum grace
        # no-op barrier: committing it commits every inherited
        # prior-term entry (raft §8); is_ready() gates on it
        self.log.append({"term": self.current_term,
                         "command": {"type": "noop"}})
        self._noop_index = self._last_index()
        self._persist()

    def is_ready(self) -> bool:
        """Leader with its election no-op committed — all prior-term
        entries are applied, so the state machine is current."""
        with self.lock:
            return (self.state == LEADER
                    and self.commit_index >= self._noop_index)

    def wait_ready(self, timeout: float = 5.0) -> bool:
        deadline = clockctl.monotonic() + timeout
        with self._commit_cond:
            while not (self.state == LEADER
                       and self.commit_index >= self._noop_index):
                if self.state != LEADER:
                    return False
                remaining = deadline - clockctl.monotonic()
                if remaining <= 0 or self._stop.is_set():
                    return False
                self._commit_cond.wait(min(remaining, 0.1))
        return True

    def _step_down(self, term: int) -> None:
        self.current_term = term
        self.state = FOLLOWER
        self.voted_for = None
        self._persist()
        self._reset_election_timer()

    # ---- leader replication ----
    def _broadcast_append(self) -> None:
        with self.lock:
            if self.state != LEADER:
                return
            # one in-flight append per peer; a slow peer must not
            # accumulate a backlog of threads and stale responses
            peers = [p for p in self.peers if p not in self._inflight]
            self._inflight.update(peers)
        for peer in peers:
            threading.Thread(target=self._replicate_to, args=(peer,),
                             daemon=True, name="raft-replicate").start()
        if not self.peers:
            # single-node: everything is instantly committed
            with self._commit_cond:
                self._advance_commit_locked()

    def _replicate_to(self, peer: str) -> None:
        try:
            self._replicate_to_inner(peer)
        finally:
            with self.lock:
                self._inflight.discard(peer)

    def _replicate_to_inner(self, peer: str) -> None:
        with self.lock:
            if self.state != LEADER:
                return
            term = self.current_term
            nxt = self.next_index.get(peer, self._last_index() + 1)
            need_snapshot = nxt <= self.snap_index
            if not need_snapshot:
                prev_idx = nxt - 1
                prev_term = self._term_at(prev_idx)
                entries = [self._entry_at(i)
                           for i in range(nxt, self._last_index() + 1)]
                commit = self.commit_index
        if need_snapshot:
            self._send_snapshot(peer, term)
            return
        try:
            resp = self.send(peer, "/raft/append", {
                "term": term, "leader_id": self.id,
                "prev_log_index": prev_idx, "prev_log_term": prev_term,
                "entries": entries, "leader_commit": commit}, 2.0)
        except Exception:
            return
        with self._commit_cond:
            if resp.get("term", 0) > self.current_term:
                self._step_down(resp["term"])
                return
            if self.state != LEADER or self.current_term != term:
                return
            self._peer_acked[peer] = clockctl.monotonic()
            if resp.get("success"):
                # max(): a stale response must never regress the indices
                m = max(self.match_index.get(peer, 0),
                        prev_idx + len(entries))
                self.match_index[peer] = m
                self.next_index[peer] = max(self.next_index.get(peer, 1),
                                            m + 1)
                self._advance_commit_locked()
            else:
                # consistency check failed: back off
                hint = resp.get("conflict_index")
                self.next_index[peer] = max(
                    1, hint if hint else self.next_index.get(peer, 2) - 1)

    def _send_snapshot(self, peer: str, term: int) -> None:
        with self.lock:
            body = {"term": term, "leader_id": self.id,
                    "last_included_index": self.snap_index,
                    "last_included_term": self.snap_term,
                    "state": self.snap_state}
            snap_index = self.snap_index
        try:
            resp = self.send(peer, "/raft/snapshot", body, 5.0)
        except Exception:
            return
        with self.lock:
            if resp.get("term", 0) > self.current_term:
                self._step_down(resp["term"])
                return
            self._peer_acked[peer] = clockctl.monotonic()
            self.match_index[peer] = max(self.match_index.get(peer, 0),
                                         snap_index)
            self.next_index[peer] = max(self.next_index.get(peer, 1),
                                        snap_index + 1)

    def _advance_commit_locked(self) -> None:
        """Commit the highest index replicated on a majority whose entry
        is from the current term, then apply."""
        for n in range(self._last_index(), self.commit_index, -1):
            if self._term_at(n) != self.current_term:
                break
            count = 1 + sum(1 for p in self.peers
                            if self.match_index.get(p, 0) >= n)
            if count * 2 > len(self.peers) + 1:
                self.commit_index = n
                break
        self._apply_committed_locked()
        self._commit_cond.notify_all()

    def _apply_committed_locked(self) -> None:
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            entry = self._entry_at(self.last_applied)
            cmd = entry["command"]
            if cmd.get("type") == "noop":  # internal election barrier
                continue
            try:
                self.apply_fn(cmd)
            except Exception:
                pass
        self._maybe_compact_locked()

    def _maybe_compact_locked(self) -> None:
        applied_in_log = self.last_applied - self.snap_index
        if applied_in_log < self.compact_threshold:
            return
        # carry the member set inside the snapshot: compaction may drop
        # raft_config entries from the log, and a follower caught up
        # via snapshot must still learn the committed membership
        self.snap_state = {**self.snapshot_fn(),
                           "_raft_members": sorted(self.peers + [self.id])}
        self.snap_term = self._term_at(self.last_applied)
        self.log = self.log[applied_in_log:]
        self.snap_index = self.last_applied
        self._persist()

    # ---- client API ----
    # ---- membership (reference raft AddServer/RemoveServer, shell
    # cluster.raft.add/remove). Single-step changes: safe when applied
    # one at a time through the log, which is how the shell drives it.
    def add_peer(self, peer: str) -> None:
        with self.lock:
            if peer == self.id or peer in self.peers:
                return
            self.peers.append(peer)
            if self.state == LEADER:
                self.next_index[peer] = self._last_index() + 1
                self.match_index[peer] = 0
                self._peer_acked[peer] = clockctl.monotonic()
            self._persist()

    def remove_peer(self, peer: str) -> None:
        with self.lock:
            if peer not in self.peers:
                return
            self.peers.remove(peer)
            self.next_index.pop(peer, None)
            self.match_index.pop(peer, None)
            self._peer_acked.pop(peer, None)
            self._persist()

    def membership(self) -> dict:
        with self.lock:
            return {"id": self.id, "peers": list(self.peers),
                    "leader": self.leader_id, "term": self.current_term,
                    "state": self.state,
                    "commit_index": self.commit_index}

    def propose(self, command: dict, timeout: float = 5.0) -> bool:
        """Leader-only: append, replicate, wait for commit."""
        with self._commit_cond:
            if self.state != LEADER:
                raise NotLeaderError(self.leader_id)
            self.log.append({"term": self.current_term, "command": command})
            index = self._last_index()
            self._persist()
        self._broadcast_append()
        deadline = clockctl.monotonic() + timeout
        with self._commit_cond:
            while self.commit_index < index:
                remaining = deadline - clockctl.monotonic()
                if remaining <= 0 or self._stop.is_set():
                    return False
                if self.state != LEADER:
                    raise NotLeaderError(self.leader_id)
                self._commit_cond.wait(min(remaining, 0.1))
        return True

    # ---- RPC handlers (wired to HTTP routes by the master) ----
    def _apply_snapshot_membership(self, state: dict) -> None:
        members = state.get("_raft_members")
        if members:
            self.peers = [p for p in members if p != self.id]

    def on_request_vote(self, body: dict) -> dict:
        with self.lock:
            term = body["term"]
            candidate = body["candidate_id"]
            if candidate not in self.peers and candidate != self.id:
                # a removed (or not-yet-added) node must not depose the
                # leader or win votes — reject WITHOUT adopting its
                # term, or its election loop walks our term forever
                return {"term": self.current_term, "vote_granted": False}
            if term > self.current_term:
                self._step_down(term)
            granted = False
            if term == self.current_term and self.voted_for in (
                    None, body["candidate_id"]):
                last_idx = self._last_index()
                last_term = self._term_at(last_idx)
                up_to_date = (body["last_log_term"], body["last_log_index"]) \
                    >= (last_term, last_idx)
                if up_to_date:
                    granted = True
                    self.voted_for = body["candidate_id"]
                    self._persist()
                    self._reset_election_timer()
            return {"term": self.current_term, "vote_granted": granted}

    def on_append_entries(self, body: dict) -> dict:
        with self._commit_cond:
            term = body["term"]
            if term < self.current_term:
                return {"term": self.current_term, "success": False}
            if term > self.current_term:
                self._step_down(term)
            self.state = FOLLOWER
            self.leader_id = body["leader_id"]
            self._reset_election_timer()

            prev_idx = body["prev_log_index"]
            if prev_idx > self._last_index():
                return {"term": self.current_term, "success": False,
                        "conflict_index": self._last_index() + 1}
            if prev_idx >= self.snap_index and \
                    self._term_at(prev_idx) != body["prev_log_term"]:
                # find first index of the conflicting term
                conflict_term = self._term_at(prev_idx)
                ci = prev_idx
                while ci > self.snap_index + 1 and \
                        self._term_at(ci - 1) == conflict_term:
                    ci -= 1
                return {"term": self.current_term, "success": False,
                        "conflict_index": ci}
            # append, truncating any conflicting suffix
            idx = prev_idx
            for entry in body["entries"]:
                idx += 1
                if idx <= self.snap_index:
                    continue
                pos = idx - self.snap_index - 1
                if pos < len(self.log):
                    if self.log[pos]["term"] != entry["term"]:
                        del self.log[pos:]
                        self.log.append(entry)
                else:
                    self.log.append(entry)
            if body["entries"]:
                self._persist()
            if body["leader_commit"] > self.commit_index:
                self.commit_index = min(body["leader_commit"],
                                        self._last_index())
                self._apply_committed_locked()
                self._commit_cond.notify_all()
            return {"term": self.current_term, "success": True}

    def on_install_snapshot(self, body: dict) -> dict:
        with self._commit_cond:
            term = body["term"]
            if term < self.current_term:
                return {"term": self.current_term}
            if term > self.current_term:
                self._step_down(term)
            self.state = FOLLOWER
            self.leader_id = body["leader_id"]
            self._reset_election_timer()
            idx = body["last_included_index"]
            if idx <= self.snap_index:
                return {"term": self.current_term}
            # discard covered log; keep any suffix past the snapshot
            if idx <= self._last_index() and \
                    self._term_at(idx) == body["last_included_term"]:
                self.log = self.log[idx - self.snap_index:]
            else:
                self.log = []
            self.snap_index = idx
            self.snap_term = body["last_included_term"]
            self.snap_state = body["state"]
            self.restore_fn(self.snap_state)
            self._apply_snapshot_membership(self.snap_state)
            self.commit_index = max(self.commit_index, idx)
            self.last_applied = max(self.last_applied, idx)
            self._persist()
            return {"term": self.current_term}


class NotLeaderError(RuntimeError):
    def __init__(self, leader: Optional[str]):
        super().__init__(f"not the raft leader (leader: {leader})")
        self.leader = leader
