"""Cluster topology tree: Topology -> DataCenter -> Rack -> DataNode.

Functional equivalent of reference weed/topology (topology.go, node.go,
data_center.go, rack.go, data_node.go, topology_ec.go): slot counting,
volume location registry, per-(collection, rp, ttl) volume layouts, and the
EC shard map. All pure in-memory logic — the master server wires heartbeats
into it; planners (shell) run against its read API.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional

from seaweedfs_tpu.utils import clockctl
from seaweedfs_tpu.storage.erasure_coding import layout as ec_layout
from seaweedfs_tpu.storage.super_block import ReplicaPlacement, TTL


def norm_disk(disk: str) -> str:
    """'' and 'hdd' are the same default tier (reference types.DiskType:
    the empty disk type IS hdd)."""
    return "" if disk in ("", "hdd") else disk


class DataNode:
    def __init__(self, ip: str, port: int, public_url: str = "",
                 max_volume_count: int = 8):
        self.ip = ip
        self.port = port
        self.public_url = public_url or f"{ip}:{port}"
        self.max_volume_count = max_volume_count
        # slots per disk type (reference DiskInfo map); default: all
        # slots on the hdd tier
        self.disk_slots: dict[str, int] = {"": max_volume_count}
        self.volumes: dict[int, dict] = {}
        self.ec_shards: dict[int, int] = {}  # vid -> shard bits
        self.rack: Optional["Rack"] = None
        self.last_seen = clockctl.now()
        # mid-scrub-pass right now (rides heartbeats): repair dispatch
        # avoids piling rebuild I/O onto a disk being swept
        self.scrubbing = False
        # local QoS overload pressure [0,1] (rides heartbeats): the
        # repair scheduler backs its bandwidth budget off when serving
        # nodes are shedding interactive load
        self.qos_pressure = 0.0
        # graceful-drain announcement (rides heartbeats): a draining
        # node takes no new assignments or volume growth, and its
        # departure must not trigger rebuilds (repair drain grace)
        self.draining = False
        # last telemetry snapshot (RED histogram + hot-key sketches,
        # rides heartbeats next to qos_pressure); merged cluster-wide
        # by the master's ClusterTelemetry
        self.telemetry: Optional[dict] = None

    @property
    def id(self) -> str:
        return f"{self.ip}:{self.port}"

    @property
    def url(self) -> str:
        return f"{self.ip}:{self.port}"

    def ec_shard_count(self) -> int:
        return sum(bin(bits).count("1") for bits in self.ec_shards.values())

    def free_space(self, disk: Optional[str] = None) -> float:
        """Free volume slots; EC shards consume fractional slots
        (reference counts 1 slot per TotalShardsCount shards).
        disk=None: all tiers; otherwise that tier only (EC shards
        count against the default tier)."""
        if disk is None:
            used = len(self.volumes) + \
                self.ec_shard_count() / ec_layout.TOTAL_SHARDS_COUNT
            return self.max_volume_count - used
        d = norm_disk(disk)
        used = sum(1 for v in self.volumes.values()
                   if norm_disk(v.get("disk_type", "")) == d)
        if d == "":
            used += self.ec_shard_count() / ec_layout.TOTAL_SHARDS_COUNT
        return self.disk_slots.get(d, 0) - used

    def to_info(self) -> dict:
        return {
            "id": self.id, "ip": self.ip, "port": self.port,
            "public_url": self.public_url,
            "grpc_port": getattr(self, "grpc_port", 0),
            "max_volume_count": self.max_volume_count,
            "disk_slots": dict(self.disk_slots),
            "volumes": list(self.volumes.values()),
            "ec_shards": [
                {"id": vid, "ec_index_bits": bits}
                for vid, bits in self.ec_shards.items()],
            "rack": self.rack.id if self.rack else "",
            "data_center": self.rack.data_center.id
            if self.rack and self.rack.data_center else "",
        }


class Rack:
    def __init__(self, rack_id: str):
        self.id = rack_id
        self.nodes: dict[str, DataNode] = {}
        self.data_center: Optional["DataCenter"] = None

    def get_or_create_node(self, ip: str, port: int, public_url: str = "",
                           max_volume_count: int = 8) -> DataNode:
        key = f"{ip}:{port}"
        n = self.nodes.get(key)
        if n is None:
            n = DataNode(ip, port, public_url, max_volume_count)
            n.rack = self
            self.nodes[key] = n
        return n

    def free_space(self, disk: Optional[str] = None) -> float:
        return sum(n.free_space(disk) for n in self.nodes.values())


class DataCenter:
    def __init__(self, dc_id: str):
        self.id = dc_id
        self.racks: dict[str, Rack] = {}

    def get_or_create_rack(self, rack_id: str) -> Rack:
        r = self.racks.get(rack_id)
        if r is None:
            r = Rack(rack_id)
            r.data_center = self
            self.racks[rack_id] = r
        return r

    def free_space(self, disk: Optional[str] = None) -> float:
        return sum(r.free_space(disk) for r in self.racks.values())


class VolumeLayout:
    """Writable-volume bookkeeping per (collection, rp, ttl)
    (reference weed/topology/volume_layout.go)."""

    def __init__(self, rp: ReplicaPlacement, ttl: TTL,
                 volume_size_limit: int):
        self.rp = rp
        self.ttl = ttl
        self.volume_size_limit = volume_size_limit
        self.locations: dict[int, list[DataNode]] = {}
        self.writable: set[int] = set()
        self.readonly: set[int] = set()

    def register_volume(self, vinfo: dict, node: DataNode) -> None:
        vid = vinfo["id"]
        locs = self.locations.setdefault(vid, [])
        if node not in locs:
            locs.append(node)
        enough_copies = len(locs) >= self.rp.copy_count
        if vinfo.get("read_only"):
            self.readonly.add(vid)
            self.writable.discard(vid)
        elif vinfo.get("size", 0) >= self.volume_size_limit:
            self.writable.discard(vid)
        elif enough_copies and vid not in self.readonly:
            self.writable.add(vid)

    def unregister_volume(self, vid: int, node: DataNode) -> None:
        locs = self.locations.get(vid)
        if not locs:
            return
        if node in locs:
            locs.remove(node)
        if len(locs) < self.rp.copy_count:
            self.writable.discard(vid)
        if not locs:
            self.locations.pop(vid, None)
            self.readonly.discard(vid)

    def pick_for_write(self) -> tuple[int, list[DataNode]]:
        if not self.writable:
            raise LookupError("no writable volumes")
        # a write lands on EVERY replica, so a volume with any draining
        # holder is not assignable (the drained node 503s new work);
        # when every writable volume touches a draining node, fall back
        # to the full set — a maybe-slow write beats no write at all
        fresh = [vid for vid in sorted(self.writable)
                 if not any(n.draining for n in self.locations.get(vid, []))]
        vid = random.choice(fresh or sorted(self.writable))
        return vid, self.locations[vid]

    def set_volume_unavailable(self, vid: int) -> None:
        self.writable.discard(vid)

    def active_volume_count(self) -> int:
        return len(self.writable)

    def clean_volume_count(self) -> int:
        """Writable volumes with no draining holder — the set
        pick_for_write prefers. Zero while volumes exist means every
        assignment would land on a node that is shutting down, which
        the master treats as a grow trigger."""
        return sum(1 for vid in self.writable
                   if not any(n.draining
                              for n in self.locations.get(vid, [])))


class Topology:
    def __init__(self, volume_size_limit: int = 30 * 1024 ** 3,
                 pulse_seconds: float = 5.0):
        self.data_centers: dict[str, DataCenter] = {}
        self.layouts: dict[tuple[str, str, str], VolumeLayout] = {}
        self.ec_shard_map: dict[int, list[list[DataNode]]] = {}
        self.volume_size_limit = volume_size_limit
        self.pulse_seconds = pulse_seconds
        self.max_volume_id = 0
        self.lock = threading.RLock()
        # VolumeLocation delta subscribers (reference
        # master_grpc_server.go broadcastToClients for KeepConnected)
        self.listeners: list = []

    def _notify(self, node: "DataNode", new_vids=(), deleted_vids=(),
                new_ec_vids=(), deleted_ec_vids=()) -> None:
        if not (new_vids or deleted_vids or new_ec_vids or deleted_ec_vids):
            return
        ev = {"url": node.url, "public_url": node.public_url,
              "new_vids": sorted(new_vids),
              "deleted_vids": sorted(deleted_vids),
              "new_ec_vids": sorted(new_ec_vids),
              "deleted_ec_vids": sorted(deleted_ec_vids)}
        for fn in list(self.listeners):
            try:
                fn(ev)
            except Exception:
                pass

    # ---- tree ----
    def get_or_create_data_center(self, dc_id: str) -> DataCenter:
        dc = self.data_centers.get(dc_id)
        if dc is None:
            dc = DataCenter(dc_id)
            self.data_centers[dc_id] = dc
        return dc

    def all_nodes(self) -> list[DataNode]:
        out = []
        for dc in self.data_centers.values():
            for rack in dc.racks.values():
                out.extend(rack.nodes.values())
        return out

    def find_node(self, node_id: str) -> Optional[DataNode]:
        for n in self.all_nodes():
            if n.id == node_id:
                return n
        return None

    # ---- layouts ----
    def get_layout(self, collection: str, rp: str, ttl: str,
                   disk: str = "") -> VolumeLayout:
        key = (collection, rp, ttl, norm_disk(disk))
        lo = self.layouts.get(key)
        if lo is None:
            lo = VolumeLayout(ReplicaPlacement.parse(rp), TTL.parse(ttl),
                              self.volume_size_limit)
            self.layouts[key] = lo
        return lo

    # ---- heartbeat intake ----
    def sync_data_node_registration(self, hb: dict, dc: str = "",
                                    rack: str = "") -> DataNode:
        """Full heartbeat: (re)register the node and its volumes/EC shards
        (reference master_grpc_server.go:61-234 + topology_ec.go:16)."""
        with self.lock:
            dcn = self.get_or_create_data_center(
                dc or hb.get("data_center") or "DefaultDataCenter")
            rk = dcn.get_or_create_rack(
                rack or hb.get("rack") or "DefaultRack")
            node = rk.get_or_create_node(
                hb["ip"], hb["port"], hb.get("public_url", ""),
                hb.get("max_volume_count", 8))
            node.last_seen = clockctl.now()
            node.scrubbing = bool(hb.get("scrubbing", False))
            node.qos_pressure = float(hb.get("qos_pressure", 0.0))
            node.draining = bool(hb.get("draining", False))
            if hb.get("telemetry"):
                node.telemetry = hb["telemetry"]
            node.grpc_port = hb.get("grpc_port", 0)
            node.max_volume_count = hb.get("max_volume_count",
                                           node.max_volume_count)
            node.disk_slots = {
                norm_disk(d): c
                for d, c in (hb.get("disk_slots")
                             or {"": node.max_volume_count}).items()}
            prev_vids = set(node.volumes)
            prev_ec_vids = set(node.ec_shards)

            # volumes: full sync (replace set)
            new_vols = {v["id"]: v for v in hb.get("volumes", [])}
            for vid in list(node.volumes):
                if vid not in new_vols:
                    self._unregister_volume(node.volumes[vid], node)
                    del node.volumes[vid]
            for vid, v in new_vols.items():
                node.volumes[vid] = v
                self._register_volume(v, node)
                self.max_volume_id = max(self.max_volume_id, vid)

            # EC shards: full sync
            new_ec = {e["id"]: e["ec_index_bits"]
                      for e in hb.get("ec_shards", [])}
            for vid in list(node.ec_shards):
                if vid not in new_ec:
                    self._unregister_ec_shards(vid, node, node.ec_shards[vid])
                    del node.ec_shards[vid]
            for vid, bits in new_ec.items():
                old = node.ec_shards.get(vid, 0)
                node.ec_shards[vid] = bits
                self._register_ec_shards(vid, node, bits, old)
                self.max_volume_id = max(self.max_volume_id, vid)
            self._notify(
                node,
                new_vids=set(new_vols) - prev_vids,
                deleted_vids=prev_vids - set(new_vols),
                new_ec_vids=set(new_ec) - prev_ec_vids,
                deleted_ec_vids=prev_ec_vids - set(new_ec))
            return node

    def incremental_sync(self, node: DataNode, deltas: dict) -> None:
        with self.lock:
            node.last_seen = clockctl.now()
            if "scrubbing" in deltas:
                node.scrubbing = bool(deltas["scrubbing"])
            if "qos_pressure" in deltas:
                node.qos_pressure = float(deltas["qos_pressure"])
            if "draining" in deltas:
                node.draining = bool(deltas["draining"])
            if deltas.get("telemetry"):
                node.telemetry = deltas["telemetry"]
            new_vids, deleted_vids = set(), set()
            new_ec_vids, deleted_ec_vids = set(), set()
            # deletes BEFORE adds: a disk-tier move reports the same
            # vid in both lists (old tier deleted, new tier added) and
            # must net out to "present on the new tier", not "gone"
            for v in deltas.get("deleted_volumes", []):
                node.volumes.pop(v["id"], None)
                self._unregister_volume(v, node)
                deleted_vids.add(v["id"])
            for v in deltas.get("new_volumes", []):
                node.volumes[v["id"]] = v
                self._register_volume(v, node)
                self.max_volume_id = max(self.max_volume_id, v["id"])
                new_vids.add(v["id"])
                deleted_vids.discard(v["id"])
            for e in deltas.get("new_ec_shards", []):
                vid, bits = e["id"], e["ec_index_bits"]
                old = node.ec_shards.get(vid, 0)
                node.ec_shards[vid] = old | bits
                self._register_ec_shards(vid, node, bits, 0)
                new_ec_vids.add(vid)
            for e in deltas.get("deleted_ec_shards", []):
                vid, bits = e["id"], e["ec_index_bits"]
                old = node.ec_shards.get(vid, 0)
                remaining = old & ~bits
                if remaining:
                    node.ec_shards[vid] = remaining
                else:
                    node.ec_shards.pop(vid, None)
                    deleted_ec_vids.add(vid)
                self._unregister_ec_shards(vid, node, bits)
            self._notify(node, new_vids=new_vids, deleted_vids=deleted_vids,
                         new_ec_vids=new_ec_vids,
                         deleted_ec_vids=deleted_ec_vids)

    def unregister_data_node(self, node: DataNode) -> None:
        """Stream dropped: remove everything the node served
        (reference master_grpc_server.go:63-91)."""
        with self.lock:
            for v in node.volumes.values():
                self._unregister_volume(v, node)
            for vid, bits in node.ec_shards.items():
                self._unregister_ec_shards(vid, node, bits)
            self._notify(node, deleted_vids=set(node.volumes),
                         deleted_ec_vids=set(node.ec_shards))
            node.volumes.clear()
            node.ec_shards.clear()
            if node.rack:
                node.rack.nodes.pop(node.id, None)

    # ---- volume registry ----
    def _register_volume(self, v: dict, node: DataNode) -> None:
        rp = ReplicaPlacement.from_byte(v.get("replica_placement", 0))
        ttl = TTL.from_bytes(
            v.get("ttl", 0).to_bytes(2, "big")) if v.get("ttl") else TTL()
        lo = self.get_layout(v.get("collection", ""), str(rp), str(ttl),
                             v.get("disk_type", ""))
        lo.register_volume(v, node)

    def _unregister_volume(self, v: dict, node: DataNode) -> None:
        rp = ReplicaPlacement.from_byte(v.get("replica_placement", 0))
        ttl = TTL.from_bytes(
            v.get("ttl", 0).to_bytes(2, "big")) if v.get("ttl") else TTL()
        lo = self.get_layout(v.get("collection", ""), str(rp), str(ttl),
                             v.get("disk_type", ""))
        lo.unregister_volume(v["id"], node)

    # ---- EC registry ----
    def _register_ec_shards(self, vid: int, node: DataNode, bits: int,
                            old_bits: int = 0) -> None:
        shards = self.ec_shard_map.setdefault(
            vid, [[] for _ in range(ec_layout.TOTAL_SHARDS_COUNT)])
        for sid in range(ec_layout.TOTAL_SHARDS_COUNT):
            if bits & (1 << sid) and node not in shards[sid]:
                shards[sid].append(node)

    def _unregister_ec_shards(self, vid: int, node: DataNode,
                              bits: int) -> None:
        shards = self.ec_shard_map.get(vid)
        if not shards:
            return
        for sid in range(ec_layout.TOTAL_SHARDS_COUNT):
            if bits & (1 << sid) and node in shards[sid]:
                shards[sid].remove(node)
        if all(not s for s in shards):
            self.ec_shard_map.pop(vid, None)

    # ---- lookup ----
    def lookup(self, collection: str, vid: int) -> list[DataNode]:
        for (col, _, _, _), lo in self.layouts.items():
            if collection and col != collection:
                continue
            locs = lo.locations.get(vid)
            if locs:
                return list(locs)
        return []

    def lookup_ec_shards(self, vid: int) -> Optional[list[list[DataNode]]]:
        return self.ec_shard_map.get(vid)

    def nodes_by_rack(self) -> dict[str, list[DataNode]]:
        """{'dc/rack': [nodes]} — the failure-domain view that
        group-aligned EC placement plans against."""
        out: dict[str, list[DataNode]] = {}
        for dc in self.data_centers.values():
            for rack in dc.racks.values():
                out[f"{dc.id}/{rack.id}"] = list(rack.nodes.values())
        return out

    def ec_group_alignment(self, vid: int, scheme) -> dict:
        """Per-local-group rack footprint of an EC volume:
        {group: sorted racks holding any member shard}. A group whose
        footprint is ONE rack repairs single-shard losses without
        crossing rack boundaries."""
        owners = self.lookup_ec_shards(vid)
        if owners is None:
            return {}
        rack_of: dict[str, str] = {}
        for rk, nodes in self.nodes_by_rack().items():
            for n in nodes:
                rack_of[n.id] = rk
        out: dict[int, list[str]] = {}
        for g in range(getattr(scheme, "local_groups", 0)):
            racks = {rack_of.get(n.id, "") for sid in
                     scheme.group_members(g) if sid < len(owners)
                     for n in owners[sid]}
            out[g] = sorted(r for r in racks if r)
        return out

    def next_volume_id(self) -> int:
        with self.lock:
            self.max_volume_id += 1
            return self.max_volume_id

    def prune_dead_nodes(self, timeout: Optional[float] = None) -> list[DataNode]:
        timeout = timeout or self.pulse_seconds * 5
        dead = [n for n in self.all_nodes()
                if clockctl.now() - n.last_seen > timeout]
        for n in dead:
            self.unregister_data_node(n)
        return dead

    def to_info(self) -> dict:
        """Serializable topology dump (the shell planners' input, like
        master_pb.TopologyInfo)."""
        with self.lock:
            return {
                "max_volume_id": self.max_volume_id,
                "data_centers": [{
                    "id": dc.id,
                    "racks": [{
                        "id": r.id,
                        "nodes": [n.to_info() for n in r.nodes.values()],
                    } for r in dc.racks.values()],
                } for dc in self.data_centers.values()],
            }


def aggregate_topology_info(topo: dict) -> dict:
    """Sum capacity/usage over a serialized topology dump (the
    /dir/status shape): {'slots', 'used_bytes', 'file_count'}. Shared
    by filer Statistics and mount statfs so the walk can't drift."""
    used = files = slots = 0
    for dc in topo.get("data_centers", []):
        for rack in dc.get("racks", []):
            for dn in rack.get("nodes", []):
                for v in dn.get("volumes", []):
                    used += v.get("size", 0)
                    files += v.get("file_count", 0)
                slots += dn.get("max_volume_count", 0)
    return {"slots": slots, "used_bytes": used, "file_count": files}


def find_node_info(topo: dict, node_url: str) -> Optional[dict]:
    """Locate one node's info dict in a serialized topology dump by its
    'ip:port' id (shared by shell gRPC-client resolution and backup)."""
    for dc in topo.get("data_centers", []):
        for rack in dc.get("racks", []):
            for n in rack.get("nodes", []):
                if n["id"] == node_url:
                    return n
    return None
