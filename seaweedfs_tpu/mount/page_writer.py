"""Dirty-page write-back pipeline for the mount layer.

Redesign of reference weed/mount/page_writer (upload_pipeline.go:44-57,
dirty_pages_chunked.go, page_chunk_mem.go, page_chunk_swapfile.go):
the file is cut into fixed-size write chunks; each chunk tracks the
byte-ranges actually written in a sorted interval list; when the writer
moves on (or the handle is flushed) a chunk is *sealed* and each of its
contiguous dirty ranges is uploaded by a bounded worker pool. Only a
small number of chunks are RAM-backed — beyond that budget new chunks
are backed by slots in a per-handle swap file on local disk — so a file
of any size streams through a fixed memory footprint instead of being
buffered whole (the pre-round-4 behavior this replaces).

Coherency rules (mirroring upload_pipeline.go MaybeWaitForSealed):
- un-sealed dirty ranges overlay whatever the caller read from the
  filer (read-your-writes);
- a read that touches a range currently being uploaded waits for that
  upload, then sees it through the uploaded FileChunk list;
- re-writing a chunk index whose previous generation is still uploading
  waits for it, so chunk mtimes always increase in write order and the
  filer's newest-shadows-oldest rule (filechunks.go) stays correct.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional

from seaweedfs_tpu.filer.entry import FileChunk

# logical chunk written per upload; matches the filer's auto-chunk size
DEFAULT_CHUNK_SIZE = 4 * 1024 * 1024
# chunks allowed to live in RAM per open handle before spilling
DEFAULT_MEM_CHUNKS = 4
# concurrent sealed-chunk uploads per handle
DEFAULT_CONCURRENCY = 4


class IntervalSet:
    """Sorted, coalesced set of written [start, stop) byte ranges inside
    one chunk (reference page_writer/chunk_interval_list.go)."""

    __slots__ = ("spans",)

    def __init__(self):
        self.spans: list[tuple[int, int]] = []

    def add(self, start: int, stop: int) -> None:
        if stop <= start:
            return
        out: list[tuple[int, int]] = []
        placed = False
        for s, e in self.spans:
            if e < start or s > stop:  # disjoint (touching ranges merge)
                if not placed and s > stop:
                    out.append((start, stop))
                    placed = True
                out.append((s, e))
            else:
                start, stop = min(s, start), max(e, stop)
        if not placed:
            out.append((start, stop))
            out.sort()
        self.spans = out

    def truncate(self, stop: int) -> None:
        self.spans = [(s, min(e, stop)) for s, e in self.spans if s < stop]

    def covered(self) -> int:
        return sum(e - s for s, e in self.spans)

    def overlaps(self, start: int, stop: int) -> list[tuple[int, int]]:
        return [(max(s, start), min(e, stop))
                for s, e in self.spans if e > start and s < stop]


class MemPageChunk:
    """RAM-backed page chunk."""

    def __init__(self, index: int, chunk_size: int):
        self.index = index
        self.chunk_size = chunk_size
        self.buf = bytearray(chunk_size)
        self.intervals = IntervalSet()
        self.last_write = 0.0
        self.in_ram = True

    def write(self, inner_off: int, data: bytes) -> None:
        self.buf[inner_off:inner_off + len(data)] = data
        self.intervals.add(inner_off, inner_off + len(data))
        self.last_write = time.monotonic()

    def read(self, inner_off: int, size: int) -> bytes:
        return bytes(self.buf[inner_off:inner_off + size])

    def release(self) -> None:
        self.buf = bytearray()


class SwapFile:
    """Slot allocator over one spill file shared by a pipeline
    (reference page_writer/page_chunk_swapfile.go). Slots are
    chunk_size-aligned and recycled when their chunk finishes
    uploading."""

    def __init__(self, path: str, chunk_size: int):
        self.path = path
        self.chunk_size = chunk_size
        self._free: list[int] = []
        self._next_slot = 0
        self._lock = threading.Lock()
        self._f = open(path, "w+b", buffering=0)
        # the file exists only as backing store for this handle
        try:
            os.unlink(path)
        except OSError:
            pass

    def alloc(self) -> int:
        with self._lock:
            if self._free:
                return self._free.pop()
            slot = self._next_slot
            self._next_slot += 1
            return slot

    def free(self, slot: int) -> None:
        with self._lock:
            self._free.append(slot)

    def pwrite(self, slot: int, inner_off: int, data: bytes) -> None:
        os.pwrite(self._f.fileno(), data,
                  slot * self.chunk_size + inner_off)

    def pread(self, slot: int, inner_off: int, size: int) -> bytes:
        got = os.pread(self._f.fileno(), size,
                       slot * self.chunk_size + inner_off)
        return got + b"\x00" * (size - len(got))

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass


class SwapPageChunk:
    """Disk-backed page chunk: same interface as MemPageChunk but the
    bytes live in a SwapFile slot, not RAM."""

    def __init__(self, index: int, swap: SwapFile):
        self.index = index
        self.chunk_size = swap.chunk_size
        self.swap = swap
        self.slot = swap.alloc()
        self.intervals = IntervalSet()
        self.last_write = 0.0
        self.in_ram = False

    def write(self, inner_off: int, data: bytes) -> None:
        self.swap.pwrite(self.slot, inner_off, data)
        self.intervals.add(inner_off, inner_off + len(data))
        self.last_write = time.monotonic()

    def read(self, inner_off: int, size: int) -> bytes:
        return self.swap.pread(self.slot, inner_off, size)

    def release(self) -> None:
        self.swap.free(self.slot)


class UploadPipeline:
    """Write-back pipeline for one open file handle.

    upload_fn(data, logical_offset, mtime_ns) -> FileChunk is supplied
    by the mount layer (it assigns a fid from the master and posts the
    payload to a volume server). Uploads run on a bounded executor; at
    most `concurrency` sealed chunks are in flight at once, so peak RAM
    is about (mem_chunks + concurrency) * chunk_size per handle.
    """

    def __init__(self, upload_fn: Callable[[bytes, int, int], FileChunk],
                 chunk_size: int = DEFAULT_CHUNK_SIZE,
                 mem_chunks: int = DEFAULT_MEM_CHUNKS,
                 concurrency: int = DEFAULT_CONCURRENCY,
                 swap_dir: Optional[str] = None):
        self.upload_fn = upload_fn
        self.chunk_size = chunk_size
        self.mem_chunks = mem_chunks
        self.swap_dir = swap_dir or "/tmp"
        self._swap: Optional[SwapFile] = None
        self._chunks: dict[int, object] = {}  # active: index -> chunk
        self._sealed: dict[int, object] = {}  # uploading: index -> chunk
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pool = ThreadPoolExecutor(max_workers=concurrency)
        self._inflight = threading.Semaphore(concurrency)
        self._futures: list = []
        self.uploaded: list[FileChunk] = []
        self._mtime_ns = 0
        self.mem_peak = 0  # high-water mark of RAM-backed active chunks

    # ---- write path ----
    def write(self, offset: int, data: bytes) -> int:
        n = len(data)
        pos = 0
        while pos < n:
            off = offset + pos
            idx, inner = divmod(off, self.chunk_size)
            take = min(n - pos, self.chunk_size - inner)
            self._chunk_for(idx).write(inner, data[pos:pos + take])
            pos += take
        self._maybe_seal_back()
        return n

    def _chunk_for(self, idx: int):
        with self._cond:
            # a previous generation of this index still uploading would
            # break mtime ordering — wait it out (rare: random re-write
            # of a range that just got sealed)
            while idx in self._sealed:
                self._cond.wait()
            ch = self._chunks.get(idx)
            if ch is None:
                in_ram = sum(1 for c in self._chunks.values() if c.in_ram)
                if in_ram < self.mem_chunks:
                    ch = MemPageChunk(idx, self.chunk_size)
                    self.mem_peak = max(self.mem_peak, in_ram + 1)
                else:
                    if self._swap is None:
                        self._swap = SwapFile(
                            os.path.join(self.swap_dir,
                                         f".weed-swap-{id(self)}-"
                                         f"{os.getpid()}"),
                            self.chunk_size)
                    ch = SwapPageChunk(idx, self._swap)
                self._chunks[idx] = ch
            return ch

    def _maybe_seal_back(self) -> None:
        """Seal every fully-written chunk and, past the active-chunk
        budget, the least-recently-written partial ones too (reference
        upload_pipeline.go SaveDataAt -> MoveToSealed)."""
        to_seal = []
        with self._lock:
            live = sorted(self._chunks.values(),
                          key=lambda c: c.last_write)
            hottest = live[-1] if live else None
            keep = []
            for ch in live:
                full = ch.intervals.covered() == ch.chunk_size
                if full and ch is not hottest:
                    to_seal.append(ch)
                else:
                    keep.append(ch)
            # too many actives: seal coldest partial chunks as well
            budget = self.mem_chunks + 2
            while len(keep) > budget and keep[0] is not hottest:
                to_seal.append(keep.pop(0))
            for ch in to_seal:
                del self._chunks[ch.index]
                self._sealed[ch.index] = ch
        for ch in to_seal:
            self._seal(ch)

    def _seal(self, ch) -> None:
        """Queue each contiguous dirty range of a chunk for upload.
        Caller must already have moved `ch` from _chunks to _sealed."""
        base = ch.index * self.chunk_size
        with self._lock:
            self._mtime_ns = max(self._mtime_ns + 1, time.time_ns())
            mtime = self._mtime_ns
        spans = list(ch.intervals.spans)

        def job():
            try:
                done = []
                for s, e in spans:
                    payload = ch.read(s, e - s)
                    fc = self.upload_fn(payload, base + s, mtime)
                    if fc.mtime_ns == 0:
                        fc.mtime_ns = mtime
                    done.append(fc)
                with self._cond:
                    self.uploaded.extend(done)
                    self._sealed.pop(ch.index, None)
                    self._cond.notify_all()
            except BaseException:
                with self._cond:
                    self._sealed.pop(ch.index, None)
                    self._cond.notify_all()
                raise
            finally:
                ch.release()
                self._inflight.release()

        self._inflight.acquire()
        self._futures.append(self._pool.submit(job))

    # ---- read-your-writes ----
    def wait_for_inflight(self, offset: int, stop: int) -> None:
        """Block until no in-flight upload overlaps [offset, stop) —
        afterwards that data is visible via `uploaded`."""
        with self._cond:
            def clear():
                for ch in self._sealed.values():
                    base = ch.index * self.chunk_size
                    if base < stop and base + ch.chunk_size > offset:
                        return False
                return True
            while not clear():
                self._cond.wait()

    def uploaded_snapshot(self) -> list[FileChunk]:
        with self._lock:
            return list(self.uploaded)

    def has_uploads(self) -> bool:
        """True once anything was sealed or uploaded — i.e. the file's
        bytes no longer live wholly in the active dirty pages."""
        with self._lock:
            return bool(self.uploaded or self._sealed or self._futures)

    def overlay(self, buf: bytearray, offset: int) -> None:
        """Patch active (un-sealed) dirty ranges over `buf`, which the
        caller filled from the filer view of [offset, offset+len(buf))."""
        stop = offset + len(buf)
        with self._lock:
            chunks = list(self._chunks.values())
        for ch in chunks:
            base = ch.index * self.chunk_size
            if base >= stop or base + ch.chunk_size <= offset:
                continue
            lo = max(offset, base) - base
            hi = min(stop, base + ch.chunk_size) - base
            for s, e in ch.intervals.overlaps(lo, hi):
                buf[base + s - offset:base + e - offset] = ch.read(s, e - s)

    def truncate(self, size: int) -> None:
        """Drop dirty data beyond `size`; already-uploaded chunks are
        clamped (the entry's file_size clamps reads as well)."""
        self.wait_for_inflight(0, 1 << 62)
        with self._lock:
            for idx in list(self._chunks):
                ch = self._chunks[idx]
                base = idx * self.chunk_size
                if base >= size:
                    ch.release()
                    del self._chunks[idx]
                else:
                    ch.intervals.truncate(size - base)
            for fc in self.uploaded:
                if fc.offset + fc.size > size:
                    fc.size = max(0, size - fc.offset)
            self.uploaded = [fc for fc in self.uploaded if fc.size > 0]

    # ---- flush / close ----
    def flush(self) -> list[FileChunk]:
        """Seal everything, wait for all uploads, return (and clear) the
        uploaded chunk list."""
        with self._lock:
            pending = []
            for i in sorted(self._chunks):
                ch = self._chunks.pop(i)
                self._sealed[i] = ch
                pending.append(ch)
        for ch in pending:
            self._seal(ch)
        futures, self._futures = self._futures, []
        err = None
        for f in futures:
            try:
                f.result()  # surface upload errors on the flushing thread
            except BaseException as e:  # keep draining, then re-raise
                err = err or e
        if err is not None:
            raise err
        with self._lock:
            out, self.uploaded = self.uploaded, []
        return out

    def close(self) -> None:
        self._pool.shutdown(wait=True)
        with self._lock:
            for ch in self._chunks.values():
                ch.release()
            self._chunks.clear()
        if self._swap is not None:
            self._swap.close()
            self._swap = None
