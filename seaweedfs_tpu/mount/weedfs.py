"""WeedFS: the mount filesystem over the filer namespace.

Functional equivalent of reference weed/mount/weedfs.go + inode_to_path.go:
an inode<->path registry, attribute translation, and per-handle
write-back state. Unlike the pre-round-4 design (whole-file RAM buffer
per handle), writes stream through a dirty-page UploadPipeline
(page_writer.py — reference weed/mount/page_writer/upload_pipeline.go)
with bounded memory and swap-file spill, and reads fetch only the chunk
views they need; metadata lookups are served from a MetaCache
subscribed to the filer change log (meta_cache.py — reference
weed/mount/meta_cache/meta_cache_subscribe.go:14-45).
"""

from __future__ import annotations

import dataclasses
import errno
import stat as statmod
import threading
import time
from typing import Optional

from seaweedfs_tpu.filer.entry import Attr, Entry, FileChunk
from seaweedfs_tpu.filer.filechunk_manifest import (has_chunk_manifest,
                                                    maybe_manifestize,
                                                    resolve_chunk_manifest)
from seaweedfs_tpu.filer.filechunks import (non_overlapping_visible_intervals,
                                            view_from_visibles)
from seaweedfs_tpu.mount.fuse_kernel import ROOT_ID, FileAttr
from seaweedfs_tpu.mount.meta_cache import MetaCache, is_negative
from seaweedfs_tpu.mount.page_writer import UploadPipeline

# files at or below this size are stored inline in the entry, matching
# the filer server's small-content threshold
INLINE_LIMIT = 2048


class InodeToPath:
    """Bidirectional inode<->path map (reference mount/inode_to_path.go)."""

    def __init__(self):
        self._path_to_inode: dict[str, int] = {"/": ROOT_ID}
        self._inode_to_path: dict[int, str] = {ROOT_ID: "/"}
        self._next = ROOT_ID + 1
        self._lock = threading.Lock()

    def lookup(self, path: str) -> int:
        with self._lock:
            ino = self._path_to_inode.get(path)
            if ino is None:
                ino = self._next
                self._next += 1
                self._path_to_inode[path] = ino
                self._inode_to_path[ino] = path
            return ino

    def path(self, ino: int) -> Optional[str]:
        return self._inode_to_path.get(ino)

    def move(self, old: str, new: str) -> None:
        """Repoint old -> new, including every path UNDER old when a
        directory moves (children keep their inodes, like the kernel)."""
        prefix = old.rstrip("/") + "/"
        with self._lock:
            for path in [p for p in self._path_to_inode
                         if p == old or p.startswith(prefix)]:
                ino = self._path_to_inode.pop(path)
                moved = new + path[len(old):]
                self._path_to_inode[moved] = ino
                self._inode_to_path[ino] = moved

    def forget(self, path: str) -> None:
        with self._lock:
            ino = self._path_to_inode.pop(path, None)
            if ino is not None:
                self._inode_to_path.pop(ino, None)


class FileHandle:
    """State of one open file (reference mount/filehandle.go): a
    snapshot of the entry's chunks at open time plus a lazily-created
    write-back pipeline. Nothing is buffered whole."""

    def __init__(self, path: str, entry: Optional[Entry], weedfs):
        self.path = path
        self.w = weedfs
        self.lock = threading.RLock()
        self.dirty = False
        self.pipeline: Optional[UploadPipeline] = None
        # resolved base state (manifests expanded once); chunk objects
        # are COPIED — truncate mutates sizes in place and the originals
        # are shared with the meta cache and other handles
        self.content = entry.content if entry else b""
        chunks = list(entry.chunks) if entry else []
        if has_chunk_manifest(chunks):
            chunks = resolve_chunk_manifest(weedfs.fs._read_chunk, chunks)
        self.base_chunks = [dataclasses.replace(c) for c in chunks]
        # chunks flushed during this handle's lifetime
        self.flushed_chunks: list[FileChunk] = []
        self.size = entry.file_size() if entry else 0
        self.attr = entry.attr if entry else Attr()
        self._vis_cache = None  # (key, visibles) for read-path reuse
        self._gen = 0  # bumped whenever chunk lists mutate
        self._last_read_end = -1  # sequential-read detection (prefetch)

    # ---- write ----
    def _ensure_pipeline(self) -> UploadPipeline:
        if self.pipeline is None:
            self.pipeline = UploadPipeline(
                self.w._upload_one, swap_dir=self.w.swap_dir,
                chunk_size=self.w.chunk_size,
                mem_chunks=self.w.mem_chunks,
                concurrency=self.w.upload_concurrency)
            if self.content:
                # inline content becomes dirty page 0 so the flushed
                # entry is a consistent chunked (or re-inlined) file
                self.pipeline.write(0, self.content)
                self.content = b""
        return self.pipeline

    def write(self, offset: int, data: bytes) -> int:
        with self.lock:
            p = self._ensure_pipeline()
            n = p.write(offset, data)
            self.size = max(self.size, offset + n)
            self.dirty = True
            return n

    # ---- read ----
    def read(self, offset: int, size: int) -> bytes:
        with self.lock:
            limit = self.size
            if offset >= limit:
                return b""
            size = min(size, limit - offset)
            if self.pipeline is not None:
                self.pipeline.wait_for_inflight(offset, offset + size)
                uploaded = self.pipeline.uploaded_snapshot()
            else:
                uploaded = []
            if self.content and not uploaded:
                buf = bytearray(self.content[offset:offset + size])
                buf.extend(b"\x00" * (size - len(buf)))
            else:
                chunks = self.base_chunks + self.flushed_chunks + uploaded
                visibles = self._visibles(chunks)
                buf = self.w._read_chunks_range(
                    chunks, offset, size, visibles=visibles)
                if offset == self._last_read_end:
                    # sequential stream: warm the chunks the next reads
                    # will want (reference reader_cache.go MaybeCache
                    # via reader_at.go on consecutive offsets)
                    self.w._prefetch_ahead(chunks, visibles, offset + size)
            self._last_read_end = offset + size
            if self.pipeline is not None:
                self.pipeline.overlay(buf, offset)
            return bytes(buf)

    def _visibles(self, chunks: list[FileChunk]):
        """Visible-interval view of the handle's chunk list, cached
        between reads (the interval build is O(n log n) and a 128KB
        FUSE read stream would otherwise redo it thousands of times;
        the reference caches per file too, filehandle_read.go)."""
        key = (self._gen, len(chunks))
        if self._vis_cache is not None and self._vis_cache[0] == key:
            return self._vis_cache[1]
        vis = non_overlapping_visible_intervals(chunks)
        self._vis_cache = (key, vis)
        return vis

    # ---- truncate ----
    def truncate(self, new_size: int) -> None:
        with self.lock:
            if self.content and new_size > len(self.content):
                # extending an inline file: content becomes page 0 so
                # the hole past it reads as zeros
                self._ensure_pipeline()
            if self.pipeline is not None:
                self.pipeline.truncate(new_size)
            if new_size < self.size:
                self.content = self.content[:new_size]
                for group in (self.base_chunks, self.flushed_chunks):
                    for fc in group:
                        if fc.offset + fc.size > new_size:
                            fc.size = max(0, new_size - fc.offset)
                self.base_chunks = [c for c in self.base_chunks if c.size]
                self.flushed_chunks = [c for c in self.flushed_chunks
                                       if c.size]
            self._gen += 1
            self.size = new_size
            self.dirty = True

    # ---- flush ----
    def flush(self) -> None:
        with self.lock:
            if not self.dirty:
                return
            now = time.time()
            entry = Entry(
                full_path=self.path,
                attr=Attr(mtime=now, crtime=self.attr.crtime or now,
                          mode=self.attr.mode or 0o644,
                          mime=self.attr.mime, uid=self.attr.uid,
                          gid=self.attr.gid, file_size=self.size))
            if self.size <= INLINE_LIMIT and not self.base_chunks \
                    and not self.flushed_chunks \
                    and (self.pipeline is None
                         or not self.pipeline.has_uploads()):
                # tiny file: persist inline, upload NOTHING. The dirty
                # pages stay live in the pipeline so later writes keep
                # layering on them (no orphaned needles, no lost base).
                buf = bytearray(self.content.ljust(self.size, b"\x00")
                                [:self.size])
                if self.pipeline is not None:
                    self.pipeline.overlay(buf, 0)
                entry.content = bytes(buf)
            else:
                uploaded = self.pipeline.flush() if self.pipeline else []
                chunks = self.base_chunks + self.flushed_chunks + uploaded
                entry.chunks = maybe_manifestize(
                    lambda blob: self.w.fs._save_chunk(blob, 0, "", ""),
                    chunks)
                self.flushed_chunks = self.flushed_chunks + uploaded
                self._gen += 1
            self.w.filer.create_entry(entry)
            self.attr = entry.attr
            self.dirty = False

    def close(self) -> None:
        if self.pipeline is not None:
            self.pipeline.close()
            self.pipeline = None


class WeedFS:
    """Operations implementation over a filer."""

    def __init__(self, filer_server, swap_dir: str = "/tmp",
                 chunk_size: int = None, mem_chunks: int = None,
                 upload_concurrency: int = None):
        from seaweedfs_tpu.mount import page_writer as _pw
        self.fs = filer_server
        self.filer = filer_server.filer
        self.inodes = InodeToPath()
        self._handles: dict[int, FileHandle] = {}
        self._next_fh = 1
        self._lock = threading.Lock()
        self.swap_dir = swap_dir
        self.chunk_size = chunk_size or _pw.DEFAULT_CHUNK_SIZE
        self.mem_chunks = mem_chunks or _pw.DEFAULT_MEM_CHUNKS
        self.upload_concurrency = (upload_concurrency
                                   or _pw.DEFAULT_CONCURRENCY)
        # statfs quota override, set live via the mount admin plane
        # (mount_grpc Configure / shell mount.configure); 0 = report
        # the cluster's aggregate capacity
        self.collection_capacity = 0
        self.meta_cache = MetaCache()
        self.meta_cache.attach(self.filer.meta_log)

    # ---- helpers ----
    def _upload_one(self, data: bytes, logical_offset: int,
                    mtime_ns: int) -> FileChunk:
        fc = self.fs._save_chunk(data, logical_offset, "", "")
        fc.mtime_ns = mtime_ns
        return fc

    PREFETCH_BYTES = 2 * 4 * 1024 * 1024  # two default chunks ahead

    def _prefetch_ahead(self, chunks: list[FileChunk], visibles,
                        from_offset: int) -> None:
        """Background-warm the chunks covering the next PREFETCH_BYTES
        of a sequential stream (skips fids already cached/in flight)."""
        rc = getattr(self.fs, "reader_cache", None)
        if rc is None:
            return
        fids = []
        for view in view_from_visibles(visibles, from_offset,
                                       self.PREFETCH_BYTES):
            if view.fid not in fids:
                fids.append(view.fid)
        if fids:
            rc.maybe_prefetch(fids)

    def _read_chunks_range(self, chunks: list[FileChunk], offset: int,
                           size: int, visibles=None) -> bytearray:
        """Materialize [offset, offset+size) from a chunk list, reading
        only the chunks that intersect the range (holes read as zeros)."""
        buf = bytearray(size)
        if visibles is None:
            visibles = non_overlapping_visible_intervals(chunks)
        chunk_by_fid = {c.fid: c for c in chunks}
        for view in view_from_visibles(visibles, offset, size):
            blob = self.fs._read_chunk(chunk_by_fid[view.fid])
            piece = blob[view.offset_in_chunk:view.offset_in_chunk
                         + view.size]
            buf[view.logic_offset - offset:
                view.logic_offset - offset + view.size] = piece
        return buf

    def _find_entry(self, path: str) -> Optional[Entry]:
        cached = self.meta_cache.get(path)
        if cached is not None:
            return None if is_negative(cached) else cached
        seq = self.meta_cache.event_seq
        entry = self.filer.find_entry(path)
        if entry is not None:
            self.meta_cache.seed(entry, as_of=seq)
        return entry

    def _entry_attr(self, entry: Entry) -> FileAttr:
        ino = self.inodes.lookup(entry.full_path)
        size = entry.file_size()
        # an open dirty handle knows the freshest size (it may also be
        # SMALLER than the entry's after an un-flushed truncate)
        dirty_sizes = [h.size for h in self._handles_for(entry.full_path)
                       if h.dirty]
        if dirty_sizes:
            size = max(dirty_sizes)
        if entry.is_directory:
            mode = statmod.S_IFDIR | 0o755
        elif entry.attr.symlink_target:
            mode = statmod.S_IFLNK | 0o777
            size = len(entry.attr.symlink_target)
        else:
            mode = statmod.S_IFREG | (entry.attr.mode & 0o777 or 0o644)
        return FileAttr(ino=ino, size=size,
                        mtime=entry.attr.mtime or time.time(),
                        mode=mode, is_dir=entry.is_directory,
                        uid=entry.attr.uid, gid=entry.attr.gid)

    def _handles_for(self, path: str) -> list[FileHandle]:
        with self._lock:
            return [h for h in self._handles.values() if h.path == path]

    def _child_path(self, parent_ino: int, name: str) -> Optional[str]:
        parent = self.inodes.path(parent_ino)
        if parent is None:
            return None
        return (parent.rstrip("/") + "/" + name) if parent != "/" \
            else "/" + name

    # ---- operations ----
    def lookup(self, parent_ino: int, name: str) -> Optional[FileAttr]:
        path = self._child_path(parent_ino, name)
        if path is None:
            return None
        entry = self._find_entry(path)
        if entry is None:
            return None
        return self._entry_attr(entry)

    def getattr(self, ino: int) -> Optional[FileAttr]:
        path = self.inodes.path(ino)
        if path is None:
            return None
        entry = self._find_entry(path)
        if entry is None:
            return None
        return self._entry_attr(entry)

    def setattr(self, ino: int, valid: int, size: int, mode: int,
                mtime: int, fh: int) -> Optional[FileAttr]:
        path = self.inodes.path(ino)
        if path is None:
            return None
        entry = self._find_entry(path)
        if entry is None:
            return None
        FATTR_SIZE = 1 << 3
        if valid & FATTR_SIZE:
            h = self._handles.get(fh)
            if h is None:
                handles = self._handles_for(path)
                h = handles[0] if handles else None
            if h is not None:
                h.truncate(size)
            else:
                # no open handle: rewrite the entry truncated
                h = FileHandle(path, entry, self)
                h.truncate(size)
                h.flush()
                h.close()
                entry = self.filer.find_entry(path)
        return self._entry_attr(entry)

    # ---- xattrs (reference weedfs_xattr.go: Entry.Extended map) ----
    XATTR_CREATE, XATTR_REPLACE = 1, 2

    def _xattr_entry(self, ino: int) -> Optional[Entry]:
        path = self.inodes.path(ino)
        return None if path is None else self._find_entry(path)

    def setxattr(self, ino: int, name: str, value: bytes,
                 flags: int) -> int:
        entry = self._xattr_entry(ino)
        if entry is None:
            return errno.ENOENT
        if flags & self.XATTR_CREATE and name in entry.extended:
            return errno.EEXIST
        if flags & self.XATTR_REPLACE and name not in entry.extended:
            return errno.ENODATA
        entry.extended[name] = value
        self.filer.update_entry(entry)
        return 0

    def getxattr(self, ino: int, name: str) -> Optional[bytes]:
        entry = self._xattr_entry(ino)
        if entry is None:
            return None
        return entry.extended.get(name)

    def listxattr(self, ino: int) -> list[str]:
        entry = self._xattr_entry(ino)
        return sorted(entry.extended) if entry is not None else []

    def removexattr(self, ino: int, name: str) -> int:
        entry = self._xattr_entry(ino)
        if entry is None:
            return errno.ENOENT
        if name not in entry.extended:
            return errno.ENODATA
        del entry.extended[name]
        self.filer.update_entry(entry)
        return 0

    def mkdir(self, parent_ino: int, name: str, mode: int) -> FileAttr:
        path = self._child_path(parent_ino, name)
        self.filer.mkdirs(path)
        return self._entry_attr(self.filer.find_entry(path))

    def unlink(self, parent_ino: int, name: str) -> int:
        path = self._child_path(parent_ino, name)
        try:
            self.filer.delete_entry(path)
        except FileNotFoundError:
            return errno.ENOENT
        except OSError:
            return errno.ENOTEMPTY
        self.inodes.forget(path)
        return 0

    def rmdir(self, parent_ino: int, name: str) -> int:
        path = self._child_path(parent_ino, name)
        entry = self._find_entry(path)
        if entry is None:
            return errno.ENOENT
        if not entry.is_directory:
            return errno.ENOTDIR
        try:
            self.filer.delete_entry(path)
        except OSError:
            return errno.ENOTEMPTY
        self.inodes.forget(path)
        return 0

    def rename(self, parent_ino: int, oldname: str, newdir_ino: int,
               newname: str) -> int:
        old = self._child_path(parent_ino, oldname)
        new = self._child_path(newdir_ino, newname)
        if old is None or new is None:
            return errno.ENOENT
        try:
            self.filer.rename_entry(old, new)
        except FileNotFoundError:
            return errno.ENOENT
        self.inodes.move(old, new)
        # repoint open handles on the file AND under a renamed directory
        # so un-flushed writes land at the new path
        prefix = old.rstrip("/") + "/"
        with self._lock:
            for h in self._handles.values():
                if h.path == old:
                    h.path = new
                elif h.path.startswith(prefix):
                    h.path = new + h.path[len(old):]
        return 0

    def open(self, ino: int) -> Optional[int]:
        path = self.inodes.path(ino)
        if path is None:
            return None
        entry = self._find_entry(path)
        if entry is None or entry.is_directory:
            return None
        h = FileHandle(path, entry, self)
        with self._lock:
            fh = self._next_fh
            self._next_fh += 1
            self._handles[fh] = h
        return fh

    def create(self, parent_ino: int, name: str,
               mode: int) -> tuple[FileAttr, int]:
        path = self._child_path(parent_ino, name)
        now = time.time()
        entry = Entry(full_path=path,
                      attr=Attr(mtime=now, crtime=now,
                                mode=mode & 0o777, file_size=0))
        self.filer.create_entry(entry)
        h = FileHandle(path, entry, self)
        h.dirty = True
        with self._lock:
            fh = self._next_fh
            self._next_fh += 1
            self._handles[fh] = h
        return self._entry_attr(entry), fh

    def read(self, ino: int, fh: int, offset: int,
             size: int) -> Optional[bytes]:
        h = self._handles.get(fh)
        if h is None:
            return None
        return h.read(offset, size)

    def write(self, ino: int, fh: int, offset: int,
              data: bytes) -> Optional[int]:
        h = self._handles.get(fh)
        if h is None:
            return None
        return h.write(offset, data)

    def flush(self, ino: int, fh: int) -> None:
        h = self._handles.get(fh)
        if h is not None:
            h.flush()

    def release(self, ino: int, fh: int) -> None:
        h = self._handles.get(fh)
        try:
            if h is not None:
                try:
                    h.flush()
                finally:
                    h.close()
        finally:
            # always drop the handle — a failed flush must not leave a
            # dead dirty handle pinning stale sizes in _entry_attr
            with self._lock:
                self._handles.pop(fh, None)

    def symlink(self, parent_ino: int, name: str,
                target: str) -> Optional[FileAttr]:
        """reference weedfs_symlink.go: the target rides the entry's
        attributes, no data chunks."""
        path = self._child_path(parent_ino, name)
        if path is None or self._find_entry(path) is not None:
            return None
        now = time.time()
        entry = Entry(full_path=path,
                      attr=Attr(mtime=now, crtime=now, mode=0o777,
                                symlink_target=target))
        self.filer.create_entry(entry)
        return self._entry_attr(entry)

    def readlink(self, ino: int) -> Optional[str]:
        path = self.inodes.path(ino)
        entry = self._find_entry(path) if path else None
        if entry is None or not entry.attr.symlink_target:
            return None
        return entry.attr.symlink_target

    def link(self, old_ino: int, newparent_ino: int,
             newname: str) -> Optional[FileAttr]:
        """Hard link (reference weedfs_link.go): both names share the
        data through the filer's hard-link id. POSIX link(2): an
        existing destination is EEXIST, never a silent replace."""
        src = self.inodes.path(old_ino)
        dst = self._child_path(newparent_ino, newname)
        if src is None or dst is None:
            return None
        if self._find_entry(dst) is not None:
            raise FileExistsError(dst)  # fuse maps to EEXIST
        try:
            entry = self.filer.add_hard_link(src, dst)
        except (FileNotFoundError, IsADirectoryError):
            return None
        return self._entry_attr(entry)

    STATFS_TTL = 10.0

    def statfs(self):
        """(blocks, bfree, bavail, files, ffree) in 4096-byte units from
        the master topology (reference weedfs_statfs.go -> filer
        Statistics). Cached on a TTL with a SHORT timeout: this runs in
        the single-threaded FUSE loop, so a slow master must degrade to
        stale/static numbers, never stall the whole mount."""
        now = time.time()
        cached = getattr(self, "_statfs_cache", None)
        if cached is not None and cached[0] > now:
            return cached[1]
        from seaweedfs_tpu.cluster.topology import aggregate_topology_info
        from seaweedfs_tpu.utils.httpd import http_json
        master = self.fs.mc.leader or self.fs.mc.master_urls[0]
        try:
            topo = http_json("GET", f"http://{master}/dir/status",
                             timeout=2.0)
        except Exception:
            # re-arm the TTL with the stale value: a down master must
            # not cost 2s PER statfs once the cache expires
            stale = cached[1] if cached else None
            self._statfs_cache = (now + self.STATFS_TTL, stale)
            return stale
        agg = aggregate_topology_info(topo.get("Topology", topo))
        if agg["slots"] == 0 and not self.collection_capacity:
            # no volume servers registered (yet): report the static
            # defaults rather than a 0-bytes-free filesystem
            result = None
        else:
            limit_mb = topo.get("VolumeSizeLimitMB", 1024)
            total = agg["slots"] * limit_mb * 1024 * 1024
            if self.collection_capacity:
                # admin-set quota wins over cluster capacity (used
                # bytes remain the cluster aggregate — a per-mount
                # byte meter would need per-collection accounting)
                total = self.collection_capacity
            bsize = 4096
            blocks = max(total // bsize, 1)
            bfree = max((total - agg["used_bytes"]) // bsize, 0)
            files = agg["file_count"]
            f_files = max(files * 2, 1 << 20)
            result = (blocks, bfree, bfree, f_files,
                      max(f_files - files, 1 << 19))
        self._statfs_cache = (now + self.STATFS_TTL, result)
        return result

    def readdir(self, ino: int) -> list[tuple[str, FileAttr]]:
        path = self.inodes.path(ino)
        if path is None:
            return []
        out = [(".", FileAttr(ino=ino, is_dir=True,
                              mode=statmod.S_IFDIR | 0o755)),
               ("..", FileAttr(ino=ROOT_ID, is_dir=True,
                               mode=statmod.S_IFDIR | 0o755))]
        entries = self.meta_cache.listing(path)
        if entries is None:
            seq = self.meta_cache.event_seq
            entries = self.filer.list_entries(path, limit=1 << 20)
            self.meta_cache.seed_listing(path, entries, as_of=seq)
        for e in entries:
            out.append((e.name, self._entry_attr(e)))
        return out
