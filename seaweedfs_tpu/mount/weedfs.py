"""WeedFS: the mount filesystem over the filer namespace.

Functional equivalent of reference weed/mount/weedfs.go + inode_to_path.go:
an inode<->path registry, attribute translation, and open-file write-back
buffers that flush into the filer as chunked uploads on flush/release.
Serves the Operations interface that fuse_kernel.FuseConnection dispatches
into. Works against the Filer/FilerServer in process (the `weed-tpu mount`
command connects one to a remote filer over HTTP using the same interface).
"""

from __future__ import annotations

import errno
import stat as statmod
import threading
import time
from typing import Optional

from seaweedfs_tpu.filer.entry import Attr, Entry
from seaweedfs_tpu.mount.fuse_kernel import ROOT_ID, FileAttr


class InodeToPath:
    """Bidirectional inode<->path map (reference mount/inode_to_path.go)."""

    def __init__(self):
        self._path_to_inode: dict[str, int] = {"/": ROOT_ID}
        self._inode_to_path: dict[int, str] = {ROOT_ID: "/"}
        self._next = ROOT_ID + 1
        self._lock = threading.Lock()

    def lookup(self, path: str) -> int:
        with self._lock:
            ino = self._path_to_inode.get(path)
            if ino is None:
                ino = self._next
                self._next += 1
                self._path_to_inode[path] = ino
                self._inode_to_path[ino] = path
            return ino

    def path(self, ino: int) -> Optional[str]:
        return self._inode_to_path.get(ino)

    def move(self, old: str, new: str) -> None:
        with self._lock:
            ino = self._path_to_inode.pop(old, None)
            if ino is not None:
                self._path_to_inode[new] = ino
                self._inode_to_path[ino] = new

    def forget(self, path: str) -> None:
        with self._lock:
            ino = self._path_to_inode.pop(path, None)
            if ino is not None:
                self._inode_to_path.pop(ino, None)


class OpenFile:
    """Write-back buffer for one open handle (the reference uses dirty
    pages + an upload pipeline, mount/page_writer.go; we buffer the whole
    file and flush on flush/release)."""

    def __init__(self, path: str, data: bytearray, dirty: bool = False):
        self.path = path
        self.data = data
        self.dirty = dirty
        self.lock = threading.Lock()


class WeedFS:
    """Operations implementation over a filer."""

    def __init__(self, filer_server):
        self.fs = filer_server
        self.filer = filer_server.filer
        self.inodes = InodeToPath()
        self._handles: dict[int, OpenFile] = {}
        self._next_fh = 1
        self._lock = threading.Lock()

    # ---- helpers ----
    def _entry_attr(self, entry: Entry) -> FileAttr:
        ino = self.inodes.lookup(entry.full_path)
        return FileAttr(ino=ino, size=entry.file_size(),
                        mtime=entry.attr.mtime or time.time(),
                        mode=(statmod.S_IFDIR | 0o755) if entry.is_directory
                        else (statmod.S_IFREG | (entry.attr.mode & 0o777
                                                 or 0o644)),
                        is_dir=entry.is_directory,
                        uid=entry.attr.uid, gid=entry.attr.gid)

    def _child_path(self, parent_ino: int, name: str) -> Optional[str]:
        parent = self.inodes.path(parent_ino)
        if parent is None:
            return None
        return (parent.rstrip("/") + "/" + name) if parent != "/" \
            else "/" + name

    # ---- operations ----
    def lookup(self, parent_ino: int, name: str) -> Optional[FileAttr]:
        path = self._child_path(parent_ino, name)
        if path is None:
            return None
        entry = self.filer.find_entry(path)
        if entry is None:
            return None
        return self._entry_attr(entry)

    def getattr(self, ino: int) -> Optional[FileAttr]:
        path = self.inodes.path(ino)
        if path is None:
            return None
        entry = self.filer.find_entry(path)
        if entry is None:
            return None
        return self._entry_attr(entry)

    def setattr(self, ino: int, valid: int, size: int, mode: int,
                mtime: int, fh: int) -> Optional[FileAttr]:
        path = self.inodes.path(ino)
        if path is None:
            return None
        entry = self.filer.find_entry(path)
        if entry is None:
            return None
        FATTR_SIZE = 1 << 3
        if valid & FATTR_SIZE:
            of = self._handles.get(fh)
            if of is not None:
                with of.lock:
                    if size < len(of.data):
                        del of.data[size:]
                    else:
                        of.data.extend(b"\x00" * (size - len(of.data)))
                    of.dirty = True
            else:
                data = bytearray(self.fs._read_entry_bytes(entry))
                if size < len(data):
                    del data[size:]
                else:
                    data.extend(b"\x00" * (size - len(data)))
                self._write_back(path, bytes(data), entry)
                entry = self.filer.find_entry(path)
        return self._entry_attr(entry)

    def mkdir(self, parent_ino: int, name: str, mode: int) -> FileAttr:
        path = self._child_path(parent_ino, name)
        self.filer.mkdirs(path)
        return self._entry_attr(self.filer.find_entry(path))

    def unlink(self, parent_ino: int, name: str) -> int:
        path = self._child_path(parent_ino, name)
        try:
            self.filer.delete_entry(path)
        except FileNotFoundError:
            return errno.ENOENT
        except OSError:
            return errno.ENOTEMPTY
        self.inodes.forget(path)
        return 0

    def rmdir(self, parent_ino: int, name: str) -> int:
        path = self._child_path(parent_ino, name)
        entry = self.filer.find_entry(path)
        if entry is None:
            return errno.ENOENT
        if not entry.is_directory:
            return errno.ENOTDIR
        try:
            self.filer.delete_entry(path)
        except OSError:
            return errno.ENOTEMPTY
        self.inodes.forget(path)
        return 0

    def rename(self, parent_ino: int, oldname: str, newdir_ino: int,
               newname: str) -> int:
        old = self._child_path(parent_ino, oldname)
        new = self._child_path(newdir_ino, newname)
        if old is None or new is None:
            return errno.ENOENT
        try:
            self.filer.rename_entry(old, new)
        except FileNotFoundError:
            return errno.ENOENT
        self.inodes.move(old, new)
        return 0

    def open(self, ino: int) -> Optional[int]:
        path = self.inodes.path(ino)
        if path is None:
            return None
        entry = self.filer.find_entry(path)
        if entry is None or entry.is_directory:
            return None
        data = bytearray(self.fs._read_entry_bytes(entry))
        with self._lock:
            fh = self._next_fh
            self._next_fh += 1
            self._handles[fh] = OpenFile(path, data)
        return fh

    def create(self, parent_ino: int, name: str,
               mode: int) -> tuple[FileAttr, int]:
        path = self._child_path(parent_ino, name)
        now = time.time()
        entry = Entry(full_path=path,
                      attr=Attr(mtime=now, crtime=now,
                                mode=mode & 0o777, file_size=0))
        self.filer.create_entry(entry)
        with self._lock:
            fh = self._next_fh
            self._next_fh += 1
            self._handles[fh] = OpenFile(path, bytearray(), dirty=True)
        return self._entry_attr(entry), fh

    def read(self, ino: int, fh: int, offset: int,
             size: int) -> Optional[bytes]:
        of = self._handles.get(fh)
        if of is None:
            return None
        with of.lock:
            return bytes(of.data[offset:offset + size])

    def write(self, ino: int, fh: int, offset: int,
              data: bytes) -> Optional[int]:
        of = self._handles.get(fh)
        if of is None:
            return None
        with of.lock:
            if offset > len(of.data):
                of.data.extend(b"\x00" * (offset - len(of.data)))
            of.data[offset:offset + len(data)] = data
            of.dirty = True
        return len(data)

    def flush(self, ino: int, fh: int) -> None:
        of = self._handles.get(fh)
        if of is None or not of.dirty:
            return
        with of.lock:
            entry = self.filer.find_entry(of.path)
            self._write_back(of.path, bytes(of.data), entry)
            of.dirty = False

    def release(self, ino: int, fh: int) -> None:
        self.flush(ino, fh)
        with self._lock:
            self._handles.pop(fh, None)

    def readdir(self, ino: int) -> list[tuple[str, FileAttr]]:
        path = self.inodes.path(ino)
        if path is None:
            return []
        out = [(".", FileAttr(ino=ino, is_dir=True, mode=statmod.S_IFDIR | 0o755)),
               ("..", FileAttr(ino=ROOT_ID, is_dir=True,
                               mode=statmod.S_IFDIR | 0o755))]
        for e in self.filer.list_entries(path, limit=1 << 20):
            out.append((e.name, self._entry_attr(e)))
        return out

    # ---- write-back ----
    def _write_back(self, path: str, data: bytes,
                    old_entry: Optional[Entry]) -> None:
        now = time.time()
        entry = Entry(full_path=path,
                      attr=Attr(mtime=now,
                                crtime=old_entry.attr.crtime
                                if old_entry else now,
                                mode=old_entry.attr.mode
                                if old_entry else 0o644,
                                mime=old_entry.attr.mime
                                if old_entry else "",
                                file_size=len(data)))
        if len(data) <= 2048:
            entry.content = data
        else:
            entry.chunks = self.fs._upload_chunks(data, "", "")
        self.filer.create_entry(entry)
