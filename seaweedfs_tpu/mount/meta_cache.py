"""Local metadata cache for the mount, kept coherent by subscribing to
the filer's metadata change log.

Redesign of reference weed/mount/meta_cache (meta_cache.go,
meta_cache_init.go, meta_cache_subscribe.go:14-45): lookups and
directory listings are served from a local entry cache; a subscription
to the filer meta log applies create/update/rename/delete events from
ANY writer (other mounts, HTTP clients, S3 gateway) so the cache never
goes stale. Directories are cached whole on first listing ("visited"
in the reference); lookups inside an un-visited directory fall through
to the filer and seed the cache.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from seaweedfs_tpu.filer.entry import Entry


class MetaCache:
    def __init__(self, max_entries: int = 1 << 17):
        self._entries: dict[str, Entry] = {}
        self._listed: set[str] = set()  # dirs whose full listing is cached
        self._children: dict[str, set[str]] = {}  # dir -> child names
        self._lock = threading.RLock()
        self.max_entries = max_entries
        self._detach: Optional[Callable[[], None]] = None
        self.events_applied = 0
        # bumped on every applied event; seeds taken from a filer read
        # that STARTED before an event landed are dropped instead of
        # cached (fill/invalidate race — the reference serializes fills
        # against the subscription the same way)
        self.event_seq = 0

    # ---- subscription ----
    def attach(self, meta_log) -> None:
        """Subscribe to a filer MetaLog; events keep this cache fresh
        (reference meta_cache_subscribe.go SubscribeMetaEvents)."""
        listener = self._apply_event
        meta_log.listeners.append(listener)
        self._detach = lambda: (meta_log.listeners.remove(listener)
                                if listener in meta_log.listeners else None)

    def detach(self) -> None:
        if self._detach:
            self._detach()
            self._detach = None

    def attach_http(self, filer_addr: str) -> None:
        """Subscribe to a REMOTE filer's metadata change log by
        long-polling its /__api/meta_events endpoint — the HTTP twin of
        the gRPC SubscribeMetadata stream the reference mount uses.
        Events from other writers (HTTP clients, S3 gateway, other
        mounts) reach this cache with at most one poll of latency."""
        import threading as _th

        from seaweedfs_tpu.utils.httpd import HttpError, http_json
        stop = _th.Event()

        class _Ev:
            __slots__ = ("tsns", "directory", "old_entry", "new_entry")

        def loop():
            cursor = 0
            while not stop.is_set():
                try:
                    out = http_json(
                        "GET", f"http://{filer_addr}/__api/meta_events"
                               f"?since_ns={cursor}&wait=25",
                        timeout=40)
                except (ConnectionError, HttpError):
                    if stop.wait(1.0):
                        return
                    continue
                for d in out.get("events", []):
                    ev = _Ev()
                    ev.tsns = d.get("tsns", 0)
                    ev.directory = d.get("directory", "/")
                    ev.old_entry = d.get("old_entry")
                    ev.new_entry = d.get("new_entry")
                    self._apply_event(ev)
                    cursor = max(cursor, ev.tsns)

        t = _th.Thread(target=loop, daemon=True,
                       name="meta-cache-subscribe")
        t.start()
        prev = self._detach
        self._detach = lambda: (stop.set(),
                                prev() if prev else None) and None

    def _apply_event(self, ev) -> None:
        """MetaLogEvent -> cache mutation. old+new = update/rename,
        old only = delete, new only = create."""
        try:
            old_path = ev.old_entry["full_path"] if ev.old_entry else None
            new = Entry.from_dict(ev.new_entry) if ev.new_entry else None
        except (KeyError, ValueError, TypeError):
            return
        with self._lock:
            self.events_applied += 1
            self.event_seq += 1
            if old_path and (new is None or new.full_path != old_path):
                self._drop(old_path)
            if new is not None:
                self._insert_if_relevant(new)

    # ---- cache ops ----
    def _drop(self, path: str) -> None:
        self._entries.pop(path, None)
        parent, name = _split(path)
        kids = self._children.get(parent)
        if kids is not None:
            kids.discard(name)
        # a dropped directory invalidates its cached listing subtree
        if path in self._listed:
            self._listed.discard(path)
            self._children.pop(path, None)

    def _insert_if_relevant(self, entry: Entry) -> None:
        """Cache an event's entry only when we track its directory —
        otherwise ignore it (the reference only applies events under
        visited paths, meta_cache_subscribe.go:30-40)."""
        parent, name = _split(entry.full_path)
        if parent in self._listed or entry.full_path in self._entries:
            self._entries[entry.full_path] = entry
            if parent in self._listed:
                self._children.setdefault(parent, set()).add(name)

    def seed(self, entry: Entry, as_of: Optional[int] = None) -> None:
        """Cache a single entry fetched from the filer. `as_of` is the
        event_seq read BEFORE the filer round-trip: if events landed in
        between, the fetched snapshot may be stale — drop it."""
        with self._lock:
            if as_of is not None and as_of != self.event_seq:
                return
            if len(self._entries) >= self.max_entries:
                self._evict()
            self._entries[entry.full_path] = entry

    def seed_listing(self, dir_path: str, entries: list[Entry],
                     as_of: Optional[int] = None) -> None:
        with self._lock:
            if as_of is not None and as_of != self.event_seq:
                return
            if len(self._entries) + len(entries) >= self.max_entries:
                self._evict()
            self._listed.add(dir_path)
            self._children[dir_path] = {e.name for e in entries}
            for e in entries:
                self._entries[e.full_path] = e

    def _evict(self) -> None:
        """Simple full reset on overflow — correctness first; the next
        lookups re-seed hot paths."""
        self._entries.clear()
        self._listed.clear()
        self._children.clear()

    def get(self, path: str) -> Optional[Entry]:
        with self._lock:
            e = self._entries.get(path)
            if e is not None:
                return e
            # inside a fully-listed dir, absence is authoritative
            parent, name = _split(path)
            if parent in self._listed:
                return _NEGATIVE
            return None

    def listing(self, dir_path: str) -> Optional[list[Entry]]:
        with self._lock:
            if dir_path not in self._listed:
                return None
            names = sorted(self._children.get(dir_path, ()))
            out = []
            for n in names:
                e = self._entries.get(_join(dir_path, n))
                if e is not None:
                    out.append(e)
            return out

    def invalidate(self, path: str) -> None:
        with self._lock:
            self._drop(path)


# sentinel: "known not to exist" (negative cache hit)
_NEGATIVE = Entry(full_path="\x00negative\x00")


def is_negative(e: Optional[Entry]) -> bool:
    return e is _NEGATIVE


def _split(path: str) -> tuple[str, str]:
    d, _, n = path.rpartition("/")
    return d or "/", n


def _join(dir_path: str, name: str) -> str:
    return ("/" + name) if dir_path == "/" else f"{dir_path}/{name}"
