"""Mount admin gRPC plane (reference weed/pb/mount.proto + its
mount_server Configure handler, driven by shell command_mount_configure.go).

The running mount serves weedtpu_mount_pb.SeaweedTpuMount.Configure and
announces itself to the master as a cluster node of type "mount" whose
URL is this gRPC address — that is how the shell finds live mounts
(reference mounts announce through the filer's cluster membership).
"""

from __future__ import annotations

import threading
from concurrent import futures

import grpc

from seaweedfs_tpu.pb import mount_pb2 as pb

SERVICE = "weedtpu_mount_pb.SeaweedTpuMount"


class MountGrpc:
    def __init__(self, weedfs):
        self.weedfs = weedfs

    def configure(self, request, context):
        if request.collection_capacity >= 0:
            self.weedfs.collection_capacity = request.collection_capacity
            # next statfs must reflect the new quota immediately
            self.weedfs._statfs_cache = None
        return pb.ConfigureResponse(
            collection_capacity=self.weedfs.collection_capacity)

    def handlers(self):
        rpcs = {
            "Configure": grpc.unary_unary_rpc_method_handler(
                self.configure,
                request_deserializer=pb.ConfigureRequest.FromString,
                response_serializer=pb.ConfigureResponse.SerializeToString),
        }
        return grpc.method_handlers_generic_handler(SERVICE, rpcs)


def start_mount_grpc(weedfs, master_url: str = "", host: str = "127.0.0.1",
                     port: int = 0, tls="auto"):
    """Serve the mount admin plane; announce to the master while alive.
    Returns (server, port, stop_announce)."""
    from seaweedfs_tpu.utils import tls as tlsmod
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    server.add_generic_rpc_handlers((MountGrpc(weedfs).handlers(),))
    cfg = tlsmod.load_tls_config("mount") if tls == "auto" else tls
    if cfg is not None:
        bound = server.add_secure_port(
            f"{host}:{port}", tlsmod.server_credentials(cfg))
    else:
        bound = server.add_insecure_port(f"{host}:{port}")
    server.start()
    stop = threading.Event()
    if master_url:
        from seaweedfs_tpu.utils.httpd import http_json

        def announce():
            while True:
                try:
                    http_json(
                        "POST", f"http://{master_url}/cluster/register",
                        {"type": "mount", "url": f"{host}:{bound}"},
                        timeout=5)
                except Exception:
                    pass  # master down: retry on the next beat
                if stop.wait(15.0):
                    return

        threading.Thread(target=announce, daemon=True,
                         name="mount-announce").start()
    return server, bound, stop


class MountAdminClient:
    def __init__(self, address: str, tls="auto"):
        from seaweedfs_tpu.utils.tls import make_channel
        self.channel = make_channel(address, role="client", tls=tls)

    def configure(self, collection_capacity: int = -1) -> int:
        fn = self.channel.unary_unary(
            f"/{SERVICE}/Configure",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.ConfigureResponse.FromString)
        resp = fn(pb.ConfigureRequest(
            collection_capacity=collection_capacity), timeout=10)
        return resp.collection_capacity

    def close(self):
        self.channel.close()
