"""Raw FUSE kernel protocol implementation (no libfuse).

The reference's `weed mount` uses hanwen/go-fuse, which speaks the kernel
FUSE wire protocol directly rather than linking libfuse
(reference weed/mount/weedfs.go); we do the same: open /dev/fuse, mount(2)
with fd=N options, then serve fuse_in_header-framed requests. Struct
layouts follow /usr/include/linux/fuse.h (protocol 7.x); we negotiate
minor 31 semantics.

`FuseConnection` owns the device fd and the serve loop; filesystem
behavior is delegated to an Operations object (see weedfs.py).
"""

from __future__ import annotations

import ctypes
import ctypes.util
import errno
import os
import stat as statmod
import struct
import threading
from typing import Optional

# opcodes (linux/fuse.h:517-560)
FUSE_LOOKUP = 1
FUSE_FORGET = 2
FUSE_GETATTR = 3
FUSE_SETATTR = 4
FUSE_READLINK = 5
FUSE_SYMLINK = 6
FUSE_MKNOD = 8
FUSE_MKDIR = 9
FUSE_LINK = 13
FUSE_UNLINK = 10
FUSE_RMDIR = 11
FUSE_RENAME = 12
FUSE_OPEN = 14
FUSE_READ = 15
FUSE_WRITE = 16
FUSE_STATFS = 17
FUSE_RELEASE = 18
FUSE_FSYNC = 20
FUSE_SETXATTR = 21
FUSE_GETXATTR = 22
FUSE_LISTXATTR = 23
FUSE_REMOVEXATTR = 24
FUSE_FLUSH = 25
FUSE_INIT = 26
FUSE_OPENDIR = 27
FUSE_READDIR = 28
FUSE_RELEASEDIR = 29
FUSE_ACCESS = 34
FUSE_CREATE = 35
FUSE_INTERRUPT = 36
FUSE_DESTROY = 38
FUSE_BATCH_FORGET = 42
FUSE_READDIRPLUS = 44
FUSE_RENAME2 = 45

IN_HEADER = struct.Struct("<IIQQIIIHH")  # len opcode unique nodeid uid gid pid extlen pad
OUT_HEADER = struct.Struct("<IiQ")  # len error unique
ATTR = struct.Struct("<QQQQQQIIIIIIIIII")  # fuse_attr
ENTRY_OUT_HEAD = struct.Struct("<QQQQII")  # nodeid gen entry_valid attr_valid nsecs
ATTR_OUT_HEAD = struct.Struct("<QII")  # attr_valid, attr_valid_nsec, dummy
INIT_IN = struct.Struct("<IIII")  # major minor max_readahead flags (+flags2+unused)
INIT_OUT = struct.Struct("<IIIIHHIIHHI28x")  # through flags2 + unused[7]
OPEN_OUT = struct.Struct("<QII")
WRITE_OUT = struct.Struct("<II")
GETATTR_IN = struct.Struct("<IIQ")
SETATTR_IN = struct.Struct("<IIQQQQQQIIIIIIII")
READ_IN = struct.Struct("<QQIIQII")
WRITE_IN = struct.Struct("<QQIIQII")
RELEASE_IN = struct.Struct("<QIIQ")
CREATE_IN = struct.Struct("<IIII")
MKDIR_IN = struct.Struct("<II")
RENAME_IN = struct.Struct("<Q")
RENAME2_IN = struct.Struct("<QII")
KSTATFS = struct.Struct("<QQQQQIIII24x")

ROOT_ID = 1


class FileAttr:
    __slots__ = ("ino", "size", "mtime", "mode", "nlink", "uid", "gid")

    def __init__(self, ino=0, size=0, mtime=0.0, mode=0o644, is_dir=False,
                 nlink=1, uid=0, gid=0):
        self.ino = ino
        self.size = size
        self.mtime = mtime
        self.mode = mode | (statmod.S_IFDIR if is_dir else statmod.S_IFREG) \
            if not (mode & 0o170000) else mode
        self.nlink = nlink
        self.uid = uid
        self.gid = gid

    def pack(self) -> bytes:
        sec = int(self.mtime)
        nsec = int((self.mtime - sec) * 1e9)
        return ATTR.pack(
            self.ino, self.size, (self.size + 511) // 512,
            sec, sec, sec, nsec, nsec, nsec,
            self.mode, self.nlink, self.uid, self.gid, 0, 4096, 0)


class FuseError(OSError):
    pass


def _libc():
    return ctypes.CDLL(None, use_errno=True)


def mount_fuse(mountpoint: str, fsname: str = "seaweedfs-tpu") -> int:
    """open /dev/fuse + mount(2). Returns the device fd."""
    fd = os.open("/dev/fuse", os.O_RDWR)
    st = os.stat(mountpoint)
    opts = (f"fd={fd},rootmode={st.st_mode & 0o170000:o},"
            f"user_id=0,group_id=0,allow_other")
    libc = _libc()
    ret = libc.mount(fsname.encode(), mountpoint.encode(), b"fuse",
                     0, opts.encode())
    if ret != 0:
        e = ctypes.get_errno()
        os.close(fd)
        raise FuseError(e, f"mount failed: {os.strerror(e)}")
    return fd


def umount(mountpoint: str) -> None:
    libc = _libc()
    if libc.umount2(mountpoint.encode(), 2) != 0:  # MNT_DETACH
        libc.umount(mountpoint.encode())


class FuseConnection:
    """Serve loop: parse requests, dispatch to ops, write replies."""

    MAX_WRITE = 1 << 20

    def __init__(self, ops, mountpoint: str):
        self.ops = ops
        self.mountpoint = mountpoint
        self.fd = mount_fuse(mountpoint)
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self.proto_minor = 31

    # ---- replies ----
    def _reply(self, unique: int, payload: bytes = b"", error: int = 0):
        buf = OUT_HEADER.pack(OUT_HEADER.size + len(payload), -error,
                              unique) + payload
        try:
            os.write(self.fd, buf)
        except OSError:
            pass

    def _reply_err(self, unique: int, err: int):
        self._reply(unique, b"", err)

    def _reply_entry(self, unique: int, attr: FileAttr):
        payload = ENTRY_OUT_HEAD.pack(attr.ino, 0, 1, 1, 0, 0) + attr.pack()
        self._reply(unique, payload)

    def _reply_attr(self, unique: int, attr: FileAttr):
        self._reply(unique, ATTR_OUT_HEAD.pack(1, 0, 0) + attr.pack())

    # ---- loop ----
    def serve_forever(self, background: bool = True):
        if background:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="fuse-loop")
            self._thread.start()
        else:
            self._loop()

    def _loop(self):
        bufsize = self.MAX_WRITE + 4096
        while not self._stop:
            try:
                req = os.read(self.fd, bufsize)
            except OSError as e:
                if e.errno in (errno.ENODEV, errno.EBADF):
                    return  # unmounted
                if e.errno == errno.EINTR:
                    continue
                return
            if not req:
                return
            try:
                self._dispatch(req)
            except Exception:
                try:
                    (_, _, unique, *_rest) = IN_HEADER.unpack_from(req)
                    self._reply_err(unique, errno.EIO)
                except Exception:
                    pass

    def close(self):
        self._stop = True
        umount(self.mountpoint)
        try:
            os.close(self.fd)
        except OSError:
            pass

    # ---- dispatch ----
    def _dispatch(self, req: bytes):
        (length, opcode, unique, nodeid, uid, gid, pid, _extlen,
         _pad) = IN_HEADER.unpack_from(req)
        body = req[IN_HEADER.size:length]
        if opcode == FUSE_INIT:
            major, minor, max_ra, flags = INIT_IN.unpack_from(body)
            self.proto_minor = min(minor, 31)
            out = INIT_OUT.pack(7, self.proto_minor, max_ra, 0, 12, 10,
                                self.MAX_WRITE, 1, 256, 0, 0)
            self._reply(unique, out)
            return
        if opcode in (FUSE_FORGET, FUSE_BATCH_FORGET):
            return  # no reply
        if opcode == FUSE_DESTROY:
            self._reply(unique)
            return
        if opcode == FUSE_INTERRUPT:
            self._reply_err(unique, errno.EAGAIN)
            return
        handler = {
            FUSE_LOOKUP: self._op_lookup,
            FUSE_GETATTR: self._op_getattr,
            FUSE_SETATTR: self._op_setattr,
            FUSE_MKDIR: self._op_mkdir,
            FUSE_UNLINK: self._op_unlink,
            FUSE_RMDIR: self._op_rmdir,
            FUSE_RENAME: self._op_rename,
            FUSE_RENAME2: self._op_rename2,
            FUSE_OPEN: self._op_open,
            FUSE_READ: self._op_read,
            FUSE_WRITE: self._op_write,
            FUSE_STATFS: self._op_statfs,
            FUSE_RELEASE: self._op_release,
            FUSE_FLUSH: self._op_flush,
            FUSE_FSYNC: self._op_flush,
            FUSE_OPENDIR: self._op_opendir,
            FUSE_READDIR: self._op_readdir,
            FUSE_RELEASEDIR: lambda u, n, b: self._reply(u),
            FUSE_ACCESS: lambda u, n, b: self._reply(u),
            FUSE_CREATE: self._op_create,
            FUSE_SYMLINK: self._op_symlink,
            FUSE_READLINK: self._op_readlink,
            FUSE_LINK: self._op_link,
            FUSE_GETXATTR: self._op_getxattr,
            FUSE_LISTXATTR: self._op_listxattr,
            FUSE_SETXATTR: self._op_setxattr,
            FUSE_REMOVEXATTR: self._op_removexattr,
        }.get(opcode)
        if handler is None:
            self._reply_err(unique, errno.ENOSYS)
            return
        handler(unique, nodeid, body)

    # ---- xattr ops (reference weedfs_xattr.go: attributes live in
    # Entry.Extended; get/list answer the size-probe convention) ----
    def _op_setxattr(self, unique, nodeid, body):
        # fuse_setxattr_in: size u32, flags u32; then name\0value
        size, flags = struct.unpack_from("<II", body)
        rest = body[8:]
        name, _, tail = rest.partition(b"\x00")
        value = tail[:size]
        err = self.ops.setxattr(nodeid, name.decode(), value, flags)
        if err:
            self._reply_err(unique, err)
        else:
            self._reply(unique)

    def _op_getxattr(self, unique, nodeid, body):
        out_size, _pad = struct.unpack_from("<II", body)
        name = body[8:].rstrip(b"\x00").decode()
        value = self.ops.getxattr(nodeid, name)
        if value is None:
            self._reply_err(unique, errno.ENODATA)
            return
        if out_size == 0:  # size probe: fuse_getxattr_out
            self._reply(unique, struct.pack("<II", len(value), 0))
        elif len(value) > out_size:
            self._reply_err(unique, errno.ERANGE)
        else:
            self._reply(unique, value)

    def _op_listxattr(self, unique, nodeid, body):
        out_size, _pad = struct.unpack_from("<II", body)
        names = self.ops.listxattr(nodeid)
        payload = b"".join(n.encode() + b"\x00" for n in names)
        if out_size == 0:
            self._reply(unique, struct.pack("<II", len(payload), 0))
        elif len(payload) > out_size:
            self._reply_err(unique, errno.ERANGE)
        else:
            self._reply(unique, payload)

    def _op_removexattr(self, unique, nodeid, body):
        name = body.rstrip(b"\x00").decode()
        err = self.ops.removexattr(nodeid, name)
        if err:
            self._reply_err(unique, err)
        else:
            self._reply(unique)

    # ---- ops ----
    def _op_lookup(self, unique, nodeid, body):
        name = body.rstrip(b"\x00").decode()
        attr = self.ops.lookup(nodeid, name)
        if attr is None:
            self._reply_err(unique, errno.ENOENT)
        else:
            self._reply_entry(unique, attr)

    def _op_getattr(self, unique, nodeid, body):
        attr = self.ops.getattr(nodeid)
        if attr is None:
            self._reply_err(unique, errno.ENOENT)
        else:
            self._reply_attr(unique, attr)

    def _op_setattr(self, unique, nodeid, body):
        (valid, _pad, fh, size, _lo, atime, mtime, _ct, _ans, _mns, _cns,
         mode, _u4, uid, gid, _u5) = SETATTR_IN.unpack_from(body)
        attr = self.ops.setattr(nodeid, valid, size=size, mode=mode,
                                mtime=mtime, fh=fh)
        if attr is None:
            self._reply_err(unique, errno.ENOENT)
        else:
            self._reply_attr(unique, attr)

    def _op_mkdir(self, unique, nodeid, body):
        mode, _umask = MKDIR_IN.unpack_from(body)
        name = body[MKDIR_IN.size:].rstrip(b"\x00").decode()
        attr = self.ops.mkdir(nodeid, name, mode)
        self._reply_entry(unique, attr)

    def _op_unlink(self, unique, nodeid, body):
        name = body.rstrip(b"\x00").decode()
        err = self.ops.unlink(nodeid, name)
        self._reply_err(unique, err) if err else self._reply(unique)

    def _op_rmdir(self, unique, nodeid, body):
        name = body.rstrip(b"\x00").decode()
        err = self.ops.rmdir(nodeid, name)
        self._reply_err(unique, err) if err else self._reply(unique)

    def _op_rename(self, unique, nodeid, body):
        newdir, = RENAME_IN.unpack_from(body)
        self._do_rename(unique, nodeid, newdir, body[RENAME_IN.size:])

    def _op_rename2(self, unique, nodeid, body):
        newdir, _flags, _pad = RENAME2_IN.unpack_from(body)
        self._do_rename(unique, nodeid, newdir, body[RENAME2_IN.size:])

    def _do_rename(self, unique, nodeid, newdir, rest):
        names = rest.split(b"\x00")
        oldname, newname = names[0].decode(), names[1].decode()
        err = self.ops.rename(nodeid, oldname, newdir, newname)
        self._reply_err(unique, err) if err else self._reply(unique)

    def _op_open(self, unique, nodeid, body):
        fh = self.ops.open(nodeid)
        if fh is None:
            self._reply_err(unique, errno.ENOENT)
        else:
            self._reply(unique, OPEN_OUT.pack(fh, 0, 0))

    def _op_opendir(self, unique, nodeid, body):
        self._reply(unique, OPEN_OUT.pack(0, 0, 0))

    def _op_read(self, unique, nodeid, body):
        fh, offset, size, _rf, _lo, _fl, _pad = READ_IN.unpack_from(body)
        data = self.ops.read(nodeid, fh, offset, size)
        if data is None:
            self._reply_err(unique, errno.EBADF)
        else:
            self._reply(unique, data)

    def _op_write(self, unique, nodeid, body):
        fh, offset, size, _wf, _lo, _fl, _pad = WRITE_IN.unpack_from(body)
        data = body[WRITE_IN.size:WRITE_IN.size + size]
        written = self.ops.write(nodeid, fh, offset, data)
        if written is None:
            self._reply_err(unique, errno.EBADF)
        else:
            self._reply(unique, WRITE_OUT.pack(written, 0))

    def _op_symlink(self, unique, nodeid, body):
        # body: linkname\0 target\0 (fuse SYMLINK sends name first)
        name, _, rest = body.partition(b"\x00")
        target = rest.split(b"\x00", 1)[0]
        attr = self.ops.symlink(nodeid, name.decode(), target.decode())
        if attr is None:
            self._reply_err(unique, errno.EEXIST)
        else:
            self._reply_entry(unique, attr)

    def _op_readlink(self, unique, nodeid, body):
        target = self.ops.readlink(nodeid)
        if target is None:
            self._reply_err(unique, errno.EINVAL)
        else:
            self._reply(unique, target.encode())

    def _op_link(self, unique, nodeid, body):
        old_nodeid, = struct.unpack_from("<Q", body)
        name = body[8:].rstrip(b"\x00").decode()
        try:
            attr = self.ops.link(old_nodeid, nodeid, name)
        except FileExistsError:
            self._reply_err(unique, errno.EEXIST)
            return
        if attr is None:
            self._reply_err(unique, errno.ENOENT)
        else:
            self._reply_entry(unique, attr)

    def _op_statfs(self, unique, nodeid, body):
        stats = None
        statfs = getattr(self.ops, "statfs", None)
        if statfs is not None:
            stats = statfs()
        if stats is None:  # static fallback
            stats = (1 << 30, 1 << 29, 1 << 29, 1 << 20, 1 << 19)
        blocks, bfree, bavail, files, ffree = stats
        self._reply(unique, KSTATFS.pack(
            blocks, bfree, bavail, files, ffree, 4096, 255, 4096, 0))

    def _op_release(self, unique, nodeid, body):
        fh, _fl, _rf, _lo = RELEASE_IN.unpack_from(body)
        self.ops.release(nodeid, fh)
        self._reply(unique)

    def _op_flush(self, unique, nodeid, body):
        fh = struct.unpack_from("<Q", body)[0]
        self.ops.flush(nodeid, fh)
        self._reply(unique)

    def _op_readdir(self, unique, nodeid, body):
        fh, offset, size, _rf, _lo, _fl, _pad = READ_IN.unpack_from(body)
        entries = self.ops.readdir(nodeid)  # list[(name, FileAttr)]
        buf = bytearray()
        idx = 0
        for name, attr in entries:
            idx += 1
            if idx <= offset:
                continue
            nb = name.encode()
            ent_len = 24 + len(nb)
            aligned = (ent_len + 7) & ~7
            if len(buf) + aligned > size:
                break
            dtype = 4 if statmod.S_ISDIR(attr.mode) else 8
            buf += struct.pack("<QQII", attr.ino, idx, len(nb), dtype)
            buf += nb + b"\x00" * (aligned - ent_len)
        self._reply(unique, bytes(buf))

    def _op_create(self, unique, nodeid, body):
        flags, mode, _umask, _of = CREATE_IN.unpack_from(body)
        name = body[CREATE_IN.size:].rstrip(b"\x00").decode()
        attr, fh = self.ops.create(nodeid, name, mode)
        payload = (ENTRY_OUT_HEAD.pack(attr.ino, 0, 1, 1, 0, 0)
                   + attr.pack() + OPEN_OUT.pack(fh, 0, 0))
        self._reply(unique, payload)
