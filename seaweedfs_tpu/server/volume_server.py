"""Volume server: public read/write/delete + admin/EC RPCs + heartbeat.

Functional equivalent of reference weed/server/volume_server*.go over
HTTP/JSON. Public data path:

  POST/PUT /<vid>,<key_cookie>     upload (raw body; ?type=replicate for
                                   the replica fan-out leg)
  GET/HEAD /<vid>,<key_cookie>     read (normal volume, else EC, with
                                   remote/degraded fallback)
  DELETE   /<vid>,<key_cookie>     delete (replicated like writes)

Admin plane under /admin/... (JSON), including the nine EC RPCs of
reference weed/server/volume_grpc_erasure_coding.go:24-35.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Optional

import numpy as np

from seaweedfs_tpu.models.coder import (ErasureCoder, scheme_from_dict,
                                        scheme_to_dict)
from seaweedfs_tpu.ops.rs_cpu import gf_partial_product
from seaweedfs_tpu.qos import (BACKGROUND, WRITE, QosGovernor, class_scope,
                               classify, current_class, from_headers)
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.erasure_coding import decoder as ecdec
from seaweedfs_tpu.storage.erasure_coding import encoder as ecenc
from seaweedfs_tpu.storage.erasure_coding import layout
from seaweedfs_tpu.storage.erasure_coding import partial as ecpart
from seaweedfs_tpu.storage.file_id import parse_needle_id_cookie
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.store import Store
from seaweedfs_tpu.storage.volume import (CookieMismatchError, DeletedError,
                                          NotFoundError)
from seaweedfs_tpu.utils import headers as weed_headers
from seaweedfs_tpu.utils import clockctl, glog, profiler, tracing
from seaweedfs_tpu.utils.httpd import (HttpError, HttpServer, Request,
                                       Response, http_call, http_json)
from seaweedfs_tpu.utils.resilience import (Deadline, PeerHealth,
                                            RetryPolicy, current_deadline,
                                            deadline_scope, hedged)

PULSE_SECONDS = 2.0
# Refuse to mint fids from a lease this close to its expiry: covers
# clock skew between master and holder plus the in-flight upload time,
# so an acked fid never rides a range the master already re-granted.
LEASE_MINT_SAFETY_S = 3.0
# Wake the heartbeat (renewal piggyback) once a mint leaves this
# fraction or less of the granted range: a write flood can burn
# LEASE_RANGE keys in under one pulse, and waiting out PULSE_SECONDS
# would strand the holder range-exhausted — falling back to a master
# that may be dark. Mirrors the master's LEASE_RANGE_REFILL_FRACTION
# (the threshold at which it stops skipping healthy renewals).
LEASE_REFILL_FRACTION = 0.25
# Default edge budget for a public read that arrives without a
# propagated X-Weed-Deadline: bounds the whole local -> remote ->
# degraded-reconstruction chain (was: unbounded handler + timeout=30
# per remote leg, which could stack).
READ_DEADLINE_S = 30.0


def _human_bytes(n: int) -> str:
    f = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if f < 1024 or unit == "TiB":
            return f"{f:.1f} {unit}" if unit != "B" else f"{int(f)} B"
        f /= 1024
    return f"{int(n)} B"


class VolumeServer:
    def __init__(self, directories: list[str], master_url: str | list,
                 host: str = "127.0.0.1", port: int = 0,
                 public_url: str = "", rack: str = "", data_center: str = "",
                 coder: Optional[ErasureCoder] = None,
                 max_volume_counts: Optional[list[int]] = None,
                 jwt_signing_key: str = "", jwt_read_key: str = "",
                 needle_map_kind: str = "memory",
                 tcp_port: int = -1, grpc_port: Optional[int] = None,
                 concurrent_upload_limit_mb: int = 256,
                 concurrent_download_limit_mb: int = 256,
                 file_size_limit_mb: int = 256,
                 inflight_timeout: float = 30.0,
                 disk_types: Optional[list[str]] = None,
                 scrub_rate_mbps: float = 8.0,
                 scrub_interval_s: float = 600.0,
                 advertise: str = "",
                 resilient_reads: bool = True,
                 parallel_replication: bool = True,
                 fsync: bool = False,
                 qos: bool = True,
                 tracing_enabled: bool = True,
                 trace_sample: float = 0.01,
                 ec_batcher: bool = False,
                 ec_batch_window_s: float = 0.005,
                 needle_cache_mb: int = 64,
                 hinted_handoff: bool = True,
                 zero_copy: bool = True,
                 assign_leases: bool = True,
                 profile_hz: float = profiler.DEFAULT_HZ):
        """tcp_port >= 0 enables the raw TCP data path (0 = ephemeral;
        reference volume_server_tcp_handlers_write.go). grpc_port starts
        the volume_server_pb gRPC admin plane (0 = ephemeral).

        concurrent_upload/download_limit_mb cap the total request/
        response payload bytes in flight at once; excess writers wait up
        to inflight_timeout then get 429 (reference
        weed/server/volume_server.go:23-30 + `weed volume
        -concurrentUploadLimitMB`). file_size_limit_mb rejects a single
        oversized upload with 413 (`-fileSizeLimitMB`). 0 = unlimited.

        scrub_rate_mbps throttles the background integrity scrubber's
        reads (<= 0 = unthrottled); scrub_interval_s is the idle gap
        between passes (<= 0 disables the scrubber thread; run_once via
        /admin/scrub still works).

        advertise ("host:port") overrides the address this server
        registers with the master — peers then reach it through that
        address instead of the listening socket (how chaos tests and
        bench interpose a tools/netchaos.py proxy on the peer path).
        resilient_reads toggles health-ranked + hedged remote-shard
        fetching (off = the serial lookup-order walk, kept as the
        bench comparator).
        parallel_replication toggles the concurrent replica fan-out
        (off = the one-at-a-time peer loop, kept as the bench
        comparator). fsync forces a durable fsync per commit batch on
        every volume (reference `weed volume -fsync`; group commit in
        storage/volume.py amortizes it across concurrent writers).
        qos toggles the admission-control governor (adaptive
        concurrency limit + class-weighted shedding, see
        seaweedfs_tpu/qos/); off = today's queue-everything behavior,
        kept as the overload-bench comparator.
        tracing_enabled/trace_sample control the distributed-tracing
        flight recorder (utils/tracing.py): head-sample rate for
        guaranteed retention; slow/error spans are kept regardless.
        Off = the shared NOOP span, zero allocation per request.

        ec_batcher routes this node's EC encode/rebuild work through a
        cross-volume batch scheduler (parallel/batcher.py): concurrent
        volumes' block-groups coalesce for ec_batch_window_s into one
        device-mesh dispatch, with a CPU drain when devices fail
        mid-run. Off (the default) keeps the per-volume coder path.
        Ignored when an explicit `coder` is passed.

        needle_cache_mb byte-budgets the hot-needle record cache
        (storage/needle_cache.py) fronting the healthy and degraded-EC
        read paths; admission follows this server's HotKeys sketch and
        0 disables the cache entirely.

        zero_copy serves eligible whole-needle and Range GETs as
        (fd, offset, length) descriptors that the HTTP core hands to
        os.sendfile — the payload never enters Python. An explicit
        fallback ladder (cached, EC, tiered, compressed-for-plain-
        clients, resize, TTL, v1, sub-threshold payloads) keeps the
        buffered path, which also stays available wholesale as the
        bit-identity comparator (zero_copy=False).

        hinted_handoff turns replicated writes into a sloppy quorum:
        a write whose primary + majority of replica legs land is acked,
        and each missed leg becomes a persisted hint
        (storage/hinted_handoff.py) that a background drain replays
        through the raw needle-blob transfer once the peer heals. Off =
        the legacy any-leg-fails-the-write contract, kept as the
        comparator for the divergence drill.

        assign_leases requests epoch-stamped fid-range leases from the
        master via heartbeat piggyback and serves /admin/lease_assign:
        clients mint fids here, off the master's per-PUT critical path,
        and writes survive a master leader outage while a lease is
        valid. Expiry discipline runs on clockctl so the sim can
        rehearse lease lapses on the virtual clock. Off = this server
        never requests leases and lease_assign answers 503, kept as
        the bench comparator (assign_leases=False).

        profile_hz sets the always-on wall-stack sampler's rate
        (utils/profiler.py; 19Hz default, prime so it can't phase-lock
        with periodic work). 0 disables: no sampler thread, and the
        per-request scope tagging collapses to one global check."""
        urls = (master_url.split(",") if isinstance(master_url, str)
                else list(master_url))
        self.master_urls = [u.strip() for u in urls if u.strip()]
        self.master_url = self.master_urls[0]
        self.http = HttpServer(host, port)
        self._store_dirs = directories
        self._max_volume_counts = max_volume_counts
        self._disk_types = disk_types
        self._rack = rack
        self._dc = data_center
        self._coder = coder
        self._ec_batcher_enabled = ec_batcher and coder is None
        self._ec_batch_window_s = ec_batch_window_s
        self.ec_batcher = None  # EcBatchScheduler when enabled
        self._needle_map_kind = needle_map_kind
        self._tcp_port = tcp_port
        self.tcp_server = None
        self._grpc_port_arg = grpc_port
        self._grpc_server = None
        self.grpc_port: Optional[int] = None
        self._public_url = public_url
        self.store: Optional[Store] = None
        self.needle_cache = None  # NeedleCache, attached in start()
        self._stop = threading.Event()
        # graceful-drain announcement: rides every heartbeat so the
        # master stops assigning here and grants repair drain grace
        self.draining = False
        self._hb_thread: Optional[threading.Thread] = None
        self.volume_size_limit = 0
        self.jwt_signing_key = jwt_signing_key
        # read JWT (reference jwt.signing.read): when a read key is set —
        # explicitly or in security.toml — GETs require a token signed
        # with it (the filer signs its own chunk reads; same shared key)
        if not jwt_read_key:
            from seaweedfs_tpu.utils import config as _cfg
            conf = _cfg.load_configuration("security")
            jwt_read_key = _cfg.get(conf, "jwt.signing.read.key", "") or ""
        self.jwt_read_key = jwt_read_key
        from seaweedfs_tpu.utils.limiter import InFlightLimiter
        self.file_size_limit = file_size_limit_mb * 1024 * 1024
        self.upload_limiter = InFlightLimiter(
            concurrent_upload_limit_mb * 1024 * 1024, inflight_timeout)
        self.download_limiter = InFlightLimiter(
            concurrent_download_limit_mb * 1024 * 1024, inflight_timeout)
        self.http.body_gate = self._upload_gate
        # vid -> (expires_monotonic, [peer urls]) for replica fan-out
        self._replica_cache: dict[int, tuple[float, list]] = {}
        self.advertise = advertise
        # zero-copy read plane: descriptor GETs via sendfile. The
        # minimum payload keeps tiny hot needles on the buffered path,
        # where the needle cache (and its cache-aware routing) earns
        # its keep; bulk payloads skip the cache and ride the kernel.
        self.zero_copy = zero_copy
        self.zero_copy_min = 64 * 1024
        self.resilient_reads = resilient_reads
        self.parallel_replication = parallel_replication
        self._fsync = fsync
        # sloppy-quorum replication: journal of missed replica legs,
        # drained by a background thread once the peer heals
        self.hinted_handoff = hinted_handoff
        self.hint_journal = None  # HintJournal, attached in start()
        self._hint_thread: Optional[threading.Thread] = None
        # assign leases: vid -> lease dict from the master's grant,
        # plus a local "next_key" mint cursor. Renewal wants ride every
        # full heartbeat; expiry is checked against clockctl at mint.
        self.assign_leases = assign_leases
        self._leases: dict[int, dict] = {}
        self._lease_lock = threading.Lock()
        self.lease_stats = {"installed": 0, "minted": 0, "refused": 0}
        # demand-triggered renewal: set when a mint drains a lease past
        # its refill threshold, waking the heartbeat loop early so a
        # fresh range lands before the active one exhausts (a flood can
        # burn LEASE_RANGE keys in under one pulse). Also set by stop()
        # to keep shutdown prompt.
        self._lease_hungry = threading.Event()
        # lazily-built shared pool for the concurrent replica fan-out
        self._replicate_pool: Optional[object] = None
        self._replicate_pool_lock = threading.Lock()
        # per-peer circuit breakers + latency health, fed by every
        # outbound call (masters and peer volume servers alike)
        self.retry = RetryPolicy()
        # vid -> (expires_monotonic, {shard_id: [peer urls]})
        # vid -> (expires_monotonic, {shard_id: [urls]}, {url: pressure})
        self._shard_loc_cache: dict[int, tuple] = {}
        self._scrub_rate = scrub_rate_mbps * 1024 * 1024
        self._scrub_interval = scrub_interval_s
        self.scrubber = None
        from seaweedfs_tpu.utils.metrics import Registry
        self.metrics = Registry()
        self._m_req = self.metrics.counter(
            "volumeServer", "request_total", "requests", ("type",))
        self._m_lat = self.metrics.histogram(
            "volumeServer", "request_seconds", "request latency", ("type",))
        # gauges refreshed at scrape (reference stats/metrics.go
        # VolumeServerVolumeCounter / disk gauges + disk_supported.go)
        self._m_volumes = self.metrics.gauge(
            "volumeServer", "volumes", "mounted volumes")
        self._m_ec_shards = self.metrics.gauge(
            "volumeServer", "ec_shards", "mounted ec shards")
        self._m_bytes = self.metrics.gauge(
            "volumeServer", "total_disk_size", "bytes across volumes")
        self._m_disk_free = self.metrics.gauge(
            "volumeServer", "disk_free_bytes", "statvfs free bytes",
            ("dir",))
        # mesh->CPU drains in the EC batch scheduler, labeled by the
        # classified reason (device_put / relay_timeout / probe_error)
        self._m_ec_fallbacks = self.metrics.counter(
            "volumeServer", "ec_coder_fallbacks",
            "EC batcher mesh dispatch failures drained via CPU",
            ("reason",))
        # hot-needle record cache + selector-core connection counters,
        # refreshed at scrape from their owners' stats() snapshots
        self._m_cache = self.metrics.gauge(
            "volumeServer", "needle_cache",
            "hot-needle cache counters", ("stat",))
        self._m_conns = self.metrics.gauge(
            "volumeServer", "http_connections",
            "selector-core connection counters", ("stat",))
        self.metrics.on_expose(self._refresh_gauges)
        self.peer_health = PeerHealth(metrics=self.metrics)
        # per-volume record of the last repair strategy this server
        # executed ({vid: {"strategy", "sources", "mode"}}), surfaced
        # via /admin/ec/shard_stat for the shell's ec.scheme.status
        self._ec_last_strategy: dict[int, dict] = {}
        # admission control: class-weighted slots under an adaptive
        # concurrency limit; shed requests get 503 + Retry-After at the
        # socket edge, before their body is buffered
        self.qos = QosGovernor(metrics=self.metrics, enabled=qos)
        self.http.admission_gate = self._admission_gate
        # lets the selector core size its worker pool off the adaptive
        # concurrency ceiling and quote governor pressure when shedding
        self.http.governor = self.qos
        self._needle_cache_mb = needle_cache_mb
        # distributed-tracing flight recorder; served at /debug/traces
        self.tracer = tracing.Tracer(
            node=f"volume@{host}:{port}", enabled=tracing_enabled,
            sample_rate=trace_sample)
        self.http.tracer = self.tracer
        # RED edge histogram (single observation site in HttpServer)
        # + hot-needle sketch; both ride heartbeats to the master
        from seaweedfs_tpu.stats.hotkeys import HotKeys
        from seaweedfs_tpu.utils.metrics import RedRecorder
        self.red = RedRecorder(self.metrics, "volume")
        self.http.red = self.red
        self.hotkeys = HotKeys(dims=("needle",))
        # per-volume cumulative read counters — the tiering autopilot's
        # temperature signal, piggybacked on heartbeats via
        # telemetry_snapshot(). Cumulative on purpose: the master diffs
        # successive reports, so a lost heartbeat costs nothing and a
        # restart clamps to zero instead of going negative.
        self.vol_reads: dict[int, int] = collections.defaultdict(int)
        # rung-transition counters for /admin/tier + tier_profile
        self.tier_stats = {"demotes": 0, "promotes": 0,
                           "bytes_demoted": 0, "bytes_promoted": 0,
                           "failed": 0}
        # continuous profiling + per-(class, tenant) resource ledger;
        # both ride the telemetry piggyback to the master
        from seaweedfs_tpu.stats.ledger import ResourceLedger
        self.sampler = profiler.WallSampler(hz=profile_hz)
        self.ledger = ResourceLedger()
        self.http.ledger = self.ledger

    # ---- lifecycle ----
    def start(self) -> None:
        self.http.start()
        self.sampler.start()
        self.tracer.node = f"volume@{self.http.host}:{self.http.port}"
        # register the ADVERTISED address with the master when one is
        # set, so peers route to us through it (chaos-proxy interpose)
        if self.advertise:
            adv_host, adv_port = self.advertise.rsplit(":", 1)
            reg_host, reg_port = adv_host, int(adv_port)
        else:
            reg_host, reg_port = self.http.host, self.http.port
        if self._ec_batcher_enabled and self._coder is None:
            from seaweedfs_tpu.parallel.batcher import (BatchCoder,
                                                        EcBatchScheduler)
            self.ec_batcher = EcBatchScheduler(
                window_s=self._ec_batch_window_s,
                on_fallback=lambda reason: self._m_ec_fallbacks.inc(reason))
            self._coder = BatchCoder(self.ec_batcher)
        self.store = Store(
            self._store_dirs, self._max_volume_counts,
            ip=reg_host, port=reg_port,
            public_url=self._public_url or f"{reg_host}:{reg_port}",
            rack=self._rack, data_center=self._dc, coder=self._coder,
            needle_map_kind=self._needle_map_kind,
            disk_types=self._disk_types, fsync=self._fsync)
        self.store.load_existing_volumes()
        self.store.remote_shard_reader = self._remote_shard_reader
        self.store.peer_health = self.peer_health
        self.store.shard_locations = self._shard_locations
        self.store.shard_pressure = self._shard_pressure
        self.store.resilient_reads = self.resilient_reads
        self.store.remote_partial_reader = self._remote_partial_reader
        if self.hinted_handoff:
            from seaweedfs_tpu.storage.hinted_handoff import HintJournal
            self.hint_journal = HintJournal(
                os.path.join(self._store_dirs[0], "hints.journal"),
                fsync=self._fsync)
            self._hint_thread = threading.Thread(
                target=self._hint_drain_loop, daemon=True,
                name="hint-drain")
            self._hint_thread.start()
        if self._needle_cache_mb > 0:
            from seaweedfs_tpu.storage.needle_cache import NeedleCache
            sketch = self.hotkeys.sketches["needle"]
            self.store.needle_cache = NeedleCache(
                capacity_bytes=self._needle_cache_mb << 20,
                hot_fn=lambda vid, nid: sketch.estimate(
                    "%d,%x" % (vid, nid)))
        self.needle_cache = self.store.needle_cache
        if self._tcp_port >= 0:
            from seaweedfs_tpu.server.volume_tcp import TcpDataServer
            self.tcp_server = TcpDataServer(self.store, self.http.host,
                                            self._tcp_port)
            self.tcp_server.start()
        if self._grpc_port_arg is not None:
            from seaweedfs_tpu.server.volume_grpc import start_volume_grpc
            self._grpc_server, self.grpc_port = start_volume_grpc(
                self, self.http.host, self._grpc_port_arg)
        self._register_routes()
        self.heartbeat_once()
        self._hb_thread = threading.Thread(target=self._heartbeat_loop,
                                           daemon=True,
                                           name="volume-heartbeat")
        self._hb_thread.start()
        from seaweedfs_tpu.scrub import Scrubber
        self.scrubber = Scrubber(self.store,
                                 rate_bytes_per_sec=self._scrub_rate,
                                 interval_s=self._scrub_interval,
                                 report_fn=self._report_scrub,
                                 metrics=self.metrics,
                                 pressure_fn=self.qos.pressure)
        if self._scrub_interval > 0:
            self.scrubber.start()
        glog.info("volume server up at %s (dirs=%s, master=%s)",
                  self.url, ",".join(self._store_dirs), self.master_url)

    def stop(self, graceful: bool = True,
             drain_timeout: float = 5.0) -> None:
        """Stop serving. graceful=True (the default) drains first:
        announce draining to the master (no new assigns, repair drain
        grace for our volumes), let in-flight requests finish, flush
        the group commit, then send a final draining heartbeat so the
        grace clock restarts from the actual departure."""
        self._stop.set()
        self._lease_hungry.set()  # wake the heartbeat loop's wait
        self.sampler.stop()
        if self.scrubber is not None:
            self.scrubber.stop()
        graceful = graceful and self.store is not None
        if graceful:
            self.draining = True
            try:
                self.heartbeat_once()
            except Exception:
                pass  # master gone: hard teardown still proceeds
            self.http.drain(drain_timeout)
        if self._replicate_pool is not None:
            # graceful: wait out queued replica fan-out legs so every
            # acked write reaches its peers before we disappear
            self._replicate_pool.shutdown(wait=graceful)
        if graceful:
            for loc in self.store.locations:
                for v in list(loc.volumes.values()):
                    try:
                        v.sync()
                    except Exception:
                        pass
            try:
                self.heartbeat_once()
            except Exception:
                pass
        if self._hint_thread is not None:
            self._hint_thread.join(timeout=2.0)
        if self.hint_journal is not None:
            self.hint_journal.close()
        self.metrics.stop_push()
        if self.tcp_server is not None:
            self.tcp_server.stop()
        if self._grpc_server is not None:
            self._grpc_server.stop(0)
        self.http.stop()
        if self.ec_batcher is not None:
            self.ec_batcher.stop()
        if self.store:
            self.store.close()

    @property
    def url(self) -> str:
        """Cluster-facing identity: the advertised address when set
        (so peers dial through the interposed proxy), else the socket."""
        return self.advertise or f"{self.http.host}:{self.http.port}"

    def _is_self(self, url: str) -> bool:
        return url in (self.advertise,
                       f"{self.http.host}:{self.http.port}") and bool(url)

    def _master_json(self, method: str, path: str, body=None,
                     timeout: float = 5.0, deadline=None):
        """One master RPC with a deadline cap and breaker bookkeeping.
        An HttpError still counts as transport-healthy (the master
        answered); only ConnectionError marks the peer down."""
        t0 = clockctl.monotonic()
        try:
            out = http_json(method, f"http://{self.master_url}{path}",
                            body, timeout=timeout, deadline=deadline)
        except HttpError:
            self.peer_health.record(self.master_url, True,
                                    clockctl.monotonic() - t0)
            raise
        except ConnectionError:
            self.peer_health.record(self.master_url, False)
            raise
        self.peer_health.record(self.master_url, True,
                                clockctl.monotonic() - t0)
        return out

    def _is_scrubbing(self) -> bool:
        """Mid-scrub-pass right now? Rides every heartbeat so the
        master's repair dispatch can avoid piling rebuild I/O onto a
        disk that the scrubber is already sweeping."""
        s = self.scrubber
        if s is None:
            return False
        try:
            return bool(s.status().get("current"))
        except Exception:
            return False

    # ---- heartbeat (reference volume_grpc_client_to_master.go) ----
    def heartbeat_once(self) -> None:
        hb = self.store.collect_heartbeat()
        hb["scrubbing"] = self._is_scrubbing()
        hb["draining"] = self.draining
        # local overload pressure rides every heartbeat so the master's
        # repair scheduler can back off nodes that are shedding load
        hb["qos_pressure"] = round(self.qos.pressure(), 4)
        # telemetry snapshot (RED histogram + hot-needle sketch)
        # piggybacks the same way — the master merges these into the
        # cluster-wide /cluster/telemetry view
        hb["telemetry"] = self.telemetry_snapshot()
        if self.grpc_port:
            hb["grpc_port"] = self.grpc_port
        lease_req = self._lease_req(hb)
        if lease_req is not None:
            hb["lease_req"] = lease_req
        for _attempt in range(2):  # second try after a leader redirect
            try:
                reply = self._master_json(
                    "POST", "/heartbeat", hb,
                    deadline=Deadline.after(2 * PULSE_SECONDS))
                if reply:
                    self.volume_size_limit = reply.get(
                        "volume_size_limit", 0)
                    if reply.get("jwt_signing_key") \
                            and not self.jwt_signing_key:
                        self.jwt_signing_key = reply["jwt_signing_key"]
                    self._install_leases(reply)
                return
            except HttpError as e:
                old = self.master_url
                self._follow_leader_hint(e)
                if self.master_url == old:
                    return
            except ConnectionError:
                self._fail_over()

    def _follow_leader_hint(self, e: "HttpError") -> None:
        """A follower replied 409 {"leader": url}: re-aim at the leader
        (the reference restarts doHeartbeat at the new leader,
        volume_grpc_client_to_master.go newLeader handling). A 409
        WITHOUT a hint — a deposed leader cut off from the election —
        falls through to _fail_over, else the node would hammer the
        ex-leader forever and never re-register with the winner."""
        import json as _json
        try:
            body = _json.loads(e.body)
        except Exception:
            body = {}
        leader = body.get("leader")
        if leader and leader != self.master_url:
            self.master_url = leader
        else:
            self._fail_over()

    def _fail_over(self) -> None:
        for url in self.master_urls:
            if url == self.master_url:
                continue
            try:
                out = http_json("GET", f"http://{url}/cluster/status",
                                deadline=Deadline.after(2.0))
                self.peer_health.record(url, True)
                # adopt the peer's leader view when it has one; a live
                # follower is still a fine next hop (its 409 will carry
                # the hint once the election settles)
                leader = (out or {}).get("Leader")
                self.master_url = leader or url
                return
            except (ConnectionError, HttpError):
                self.peer_health.record(url, False)
                continue

    # ---- assign leases (local fid minting off the master's path) ----
    def _lease_req(self, hb: dict) -> Optional[dict]:
        """Renewal wants for the heartbeat piggyback: one entry per
        writable local volume, carrying the mint cursor + epoch of any
        lease already held so the master can skip still-healthy ones.
        Also GCs lapsed leases — expiry is the only revocation."""
        if not self.assign_leases:
            return None
        req: dict[str, dict] = {}
        now = clockctl.now()
        with self._lease_lock:
            for vid in [vid for vid, l in self._leases.items()
                        if l["expires_at"] <= now]:
                del self._leases[vid]
            for v in hb.get("volumes", []):
                if v.get("read_only"):
                    continue
                if self.volume_size_limit \
                        and v.get("size", 0) >= self.volume_size_limit:
                    continue
                held = self._leases.get(v["id"])
                req[str(v["id"])] = (
                    {"next_key": held["next_key"], "epoch": held["epoch"]}
                    if held else {})
        return req

    def _install_leases(self, reply: dict) -> None:
        """Adopt granted/renewed leases from a heartbeat reply. A grant
        from an older epoch (a stale leader's last gasp) never replaces
        a newer one; every accepted grant is a fresh range, so the mint
        cursor resets to its key_lo."""
        for l in reply.get("leases") or []:
            vid = int(l["vid"])
            with self._lease_lock:
                cur = self._leases.get(vid)
                if cur is not None and l["epoch"] < cur["epoch"]:
                    continue
                self._leases[vid] = dict(l, next_key=l["key_lo"])
                self.lease_stats["installed"] += 1
            # the grant names this vid's replica peers: prime the
            # fan-out cache so a leased write replicates even while
            # the master (this cache's only other source) is dark
            peers = [r["url"] for r in l.get("replicas", [])
                     if not self._is_self(r["url"])]
            if peers:
                self._replica_cache[vid] = (
                    clockctl.monotonic() + self.REPLICA_CACHE_TTL, peers)

    def _admin_lease_assign(self, req: Request) -> Response:
        """Mint fids locally from an active lease (the direct-to-volume
        assign lane; shape mirrors the master's /dir/assign reply).
        Refuses — 503, so clients fall back to the master — when no
        matching lease is valid: none held, wrong collection, range
        exhausted, or within LEASE_MINT_SAFETY_S of expiry."""
        count = max(1, int(req.query.get("count", "1") or "1"))
        collection = req.query.get("collection", "")
        if self.draining or not self.assign_leases:
            return Response({"error": "no active lease"}, status=503)
        chosen = None
        now = clockctl.now()
        with self._lease_lock:
            for vid, l in self._leases.items():
                if l["expires_at"] - now <= LEASE_MINT_SAFETY_S:
                    continue
                if l.get("collection", "") != collection:
                    continue
                if l["next_key"] + count > l["key_hi"] + 1:
                    continue
                v = self.store.find_volume(vid)
                if v is None or v.read_only:
                    continue
                if self.volume_size_limit \
                        and v.content_size() >= self.volume_size_limit:
                    continue
                key = l["next_key"]
                l["next_key"] += count
                chosen = (vid, dict(l), key)
                break
            if chosen is None:
                self.lease_stats["refused"] += 1
            else:
                self.lease_stats["minted"] += count
                span = chosen[1]["key_hi"] - chosen[1]["key_lo"] + 1
                left = chosen[1]["key_hi"] - chosen[1]["next_key"] + 1
                if left <= span * LEASE_REFILL_FRACTION:
                    # running dry: pulse now, don't wait out the tick
                    self._lease_hungry.set()
        if chosen is None:
            return Response({"error": "no active lease"}, status=503)
        vid, lease, key = chosen
        import random
        from seaweedfs_tpu.storage.file_id import format_needle_id_cookie
        cookie = random.getrandbits(32)
        out = {"fid": f"{vid},{format_needle_id_cookie(key, cookie)}",
               "url": self.url, "publicUrl": self.store.public_url,
               "count": count, "lease_epoch": lease["epoch"],
               "replicas": lease.get("replicas", [])}
        if self.jwt_signing_key:
            from seaweedfs_tpu.utils.security import gen_jwt
            out["auth"] = gen_jwt(self.jwt_signing_key, out["fid"])
        return Response(out)

    def _push_deltas(self) -> None:
        """Send pending volume/EC-shard deltas to the master immediately
        (the reference's delta channels wake the heartbeat stream;
        volume_grpc_client_to_master.go:164-260)."""
        deltas = self.store.drain_deltas()
        if not any(deltas.values()):
            return
        body = {"ip": self.store.ip, "port": self.store.port,
                "is_delta": True, "scrubbing": self._is_scrubbing(),
                "qos_pressure": round(self.qos.pressure(), 4),
                "draining": self.draining,
                "telemetry": self.telemetry_snapshot(),
                **deltas}
        try:
            self._master_json("POST", "/heartbeat", body,
                              deadline=Deadline.after(2 * PULSE_SECONDS))
        except HttpError as e:
            if e.status == 409:
                self._follow_leader_hint(e)
                self.heartbeat_once()
        except ConnectionError:
            self._fail_over()

    def _heartbeat_loop(self) -> None:
        ticks = 0
        while True:
            # pulse cadence, cut short when a mint drains a lease past
            # its refill threshold (or stop() wakes us for shutdown)
            self._lease_hungry.wait(PULSE_SECONDS)
            self._lease_hungry.clear()
            if self._stop.is_set():
                return
            ticks += 1
            if ticks % 12 == 0:
                # TTL volume reaping (reference master vacuum loop
                # cadence); deletions ride the next delta heartbeat
                try:
                    self.store.delete_expired_ttl_volumes()
                except Exception as e:
                    import logging
                    logging.getLogger("seaweedfs_tpu.volume").warning(
                        "TTL reap failed (will retry): %s", e,
                        exc_info=True)
            deltas = self.store.drain_deltas()
            has_delta = any(deltas.values())
            try:
                if has_delta:
                    body = {"ip": self.store.ip, "port": self.store.port,
                            "is_delta": True,
                            "scrubbing": self._is_scrubbing(),
                            "qos_pressure": round(self.qos.pressure(), 4),
                            "draining": self.draining,
                            "telemetry": self.telemetry_snapshot(),
                            **deltas}
                    reply = self._master_json(
                        "POST", "/heartbeat", body,
                        deadline=Deadline.after(2 * PULSE_SECONDS))
                    self._install_leases(reply or {})
                else:
                    self.heartbeat_once()
            except HttpError as e:
                if e.status == 409:  # new leader or master forgot us
                    self._follow_leader_hint(e)
                    self.heartbeat_once()
            except ConnectionError:
                self._fail_over()
                self.heartbeat_once()

    # ---- routes ----
    def _register_routes(self) -> None:
        r = self.http.add
        for method in ("POST", "PUT"):
            r(method, r"/(\d+),([0-9a-fA-F]+)(?:_\d+)?(?:\.\w+)?",
              self._handle_write)
        r("GET", r"/(\d+),([0-9a-fA-F]+)(?:_\d+)?(?:\.\w+)?",
          self._handle_read)
        r("HEAD", r"/(\d+),([0-9a-fA-F]+)(?:_\d+)?(?:\.\w+)?",
          self._handle_read)
        r("DELETE", r"/(\d+),([0-9a-fA-F]+)(?:_\d+)?(?:\.\w+)?",
          self._handle_delete)
        r("GET", "/status", self._handle_status)
        r("GET", "/metrics", self._handle_metrics)
        r("GET", "/ui", self._handle_ui)
        from seaweedfs_tpu.utils.debug import install_debug_routes
        install_debug_routes(self.http)
        # admin
        r("POST", "/admin/allocate_volume", self._admin_allocate_volume)
        r("POST", "/admin/delete_volume", self._admin_delete_volume)
        r("POST", "/admin/mark_readonly", self._admin_mark_readonly)
        r("POST", "/admin/mount_volume", self._admin_mount_volume)
        r("POST", "/admin/unmount_volume", self._admin_unmount_volume)
        r("POST", "/admin/configure_replication",
          self._admin_configure_replication)
        r("POST", "/admin/leave", self._admin_leave)
        r("POST", "/admin/batch_delete", self._admin_batch_delete)
        r("GET", "/admin/volume_file_status",
          self._admin_volume_file_status)
        r("POST", "/admin/vacuum", self._admin_vacuum)
        r("POST", "/admin/sync", self._admin_sync)
        r("POST", "/admin/copy_volume", self._admin_copy_volume)
        r("POST", "/admin/move_volume_disk",
          self._admin_move_volume_disk)
        r("GET", "/admin/volume_file", self._admin_volume_file)
        r("POST", "/admin/tier_upload", self._admin_tier_upload)
        r("POST", "/admin/tier_download", self._admin_tier_download)
        # tiering autopilot: rung state + BACKGROUND-classed moves
        r("GET", "/admin/tier", self._admin_tier_status)
        r("POST", "/admin/tier/demote", self._admin_tier_demote)
        r("POST", "/admin/tier/promote", self._admin_tier_promote)
        r("GET", "/admin/volume_digest", self._admin_volume_digest)
        r("GET", "/admin/needle", self._admin_needle)
        r("GET", "/admin/needle_blob", self._admin_needle_blob)
        r("POST", "/admin/write_needle_blob", self._admin_write_needle_blob)
        # divergence repair: clients report a lagging replica here, the
        # hint journal is inspectable for drills and the ops shell
        r("POST", "/admin/replica_repair", self._admin_replica_repair)
        r("GET", "/admin/hints", self._admin_hints)
        # EC rpcs
        r("POST", "/admin/ec/generate", self._ec_generate)
        r("POST", "/admin/ec/rebuild", self._ec_rebuild)
        r("POST", "/admin/ec/copy", self._ec_copy)
        r("POST", "/admin/ec/mount", self._ec_mount)
        r("POST", "/admin/ec/unmount", self._ec_unmount)
        r("POST", "/admin/ec/delete_shards", self._ec_delete_shards)
        r("POST", "/admin/ec/to_volume", self._ec_to_volume)
        r("POST", "/admin/ec/blob_delete", self._ec_blob_delete)
        r("GET", "/admin/ec/shard_read", self._ec_shard_read)
        r("GET", "/admin/ec/shard_file", self._ec_shard_file)
        r("GET", "/admin/ec/shard_stat", self._ec_shard_stat)
        # partial-column repair (network-frugal rebuild; see
        # storage/erasure_coding/partial.py for the chain protocol)
        r("POST", "/admin/ec/partial_read", self._ec_partial_read)
        r("POST", "/admin/ec/rebuild_partial", self._ec_rebuild_partial)
        # batch-scheduler snapshot (coalescing + fallback counters)
        r("GET", "/admin/ec/batcher", self._admin_ec_batcher)
        # integrity scrub
        r("POST", "/admin/scrub", self._admin_scrub)
        r("GET", "/admin/scrub/status", self._admin_scrub_status)
        # direct-to-volume fid minting from the master's assign lease
        r("POST", "/admin/lease_assign", self._admin_lease_assign)
        # per-peer breaker/health table (cluster.health shell command)
        r("GET", "/admin/health", self._admin_health)
        # admission-control snapshot + runtime tuning (cluster.qos)
        r("GET", "/admin/qos", self._admin_qos)
        r("POST", "/admin/qos", self._admin_qos_configure)
        # hot-needle sketch + full telemetry snapshot (RED histogram)
        r("GET", "/admin/hotkeys", self.hotkeys.handler(self.url))
        r("GET", "/admin/telemetry", self._admin_telemetry)
        # folded-stack window from the wall sampler (prof_collect)
        r("GET", "/admin/profile", profiler.make_profile_handler(
            self.sampler, lambda: self.url, "volume"))
        # hot-needle record cache snapshot + runtime resize
        r("GET", "/admin/cache", self._admin_cache)
        r("POST", "/admin/cache", self._admin_cache_configure)

    def _admin_ec_batcher(self, req: Request) -> Response:
        if self.ec_batcher is None:
            return Response({"enabled": False})
        return Response({"enabled": True, **self.ec_batcher.stats()})

    def _admin_health(self, req: Request) -> Response:
        return Response({"url": self.url,
                         "scrubbing": self._is_scrubbing(),
                         "peers": self.peer_health.snapshot()})

    # paths the admission gate never sheds: observability and the tiny
    # control endpoints an operator needs most exactly when the node is
    # overloaded (shedding /admin/qos would saw off the escape hatch)
    QOS_EXEMPT = ("/status", "/metrics", "/ui", "/debug",
                  "/admin/qos", "/admin/health", "/admin/scrub/status",
                  "/admin/ec/batcher", "/admin/hotkeys",
                  "/admin/telemetry", "/admin/cache", "/admin/hints",
                  "/admin/profile")

    def _admission_gate(self, method: str, path: str, headers, client):
        """HttpServer admission hook: classify (propagated header wins
        over the method/path default), ask the governor for a slot,
        shed with 503 + Retry-After when it says no."""
        if not self.qos.enabled:
            return None
        for p in self.QOS_EXEMPT:
            if path.startswith(p):
                return None
        cls = from_headers(headers) or classify(method, path)
        grant = self.qos.admit(cls)
        if not grant.ok:
            self._m_req.inc("qos_shed")
            return Response(
                {"error": "overloaded", "class": cls}, status=503,
                headers={"Retry-After": f"{grant.retry_after:.2f}"})
        return grant.release

    def _admin_qos(self, req: Request) -> Response:
        return Response({"url": self.url, **self.qos.snapshot()})

    def _admin_qos_configure(self, req: Request) -> Response:
        return Response({"url": self.url,
                         **self.qos.configure(**(req.json() or {}))})

    def _admin_cache(self, req: Request) -> Response:
        cache = self.store.needle_cache if self.store else None
        if cache is None:
            return Response({"url": self.url, "enabled": False,
                             "connections": self.http.conn_stats()})
        return Response({"url": self.url, "enabled": True,
                         **cache.stats(),
                         "connections": self.http.conn_stats()})

    def _admin_cache_configure(self, req: Request) -> Response:
        cache = self.store.needle_cache if self.store else None
        if cache is None:
            return Response({"error": "cache disabled"}, status=409)
        b = req.json() or {}
        out = cache.configure(
            capacity_bytes=b.get("capacity_bytes"),
            admit_min=b.get("admit_min"))
        if b.get("clear"):
            for loc in self.store.locations:
                for vid in list(loc.volumes):
                    cache.invalidate_volume(vid)
                for vid in list(loc.ec_volumes):
                    cache.invalidate_volume(vid)
            out = cache.stats()
        return Response({"url": self.url, "enabled": True, **out})

    def telemetry_snapshot(self) -> dict:
        snap = {"node": self.url, "server": "volume",
                "red": self.red.snapshot(),
                "hotkeys": self.hotkeys.snapshot(),
                "ledger": self.ledger.snapshot(),
                "tiering": self.tiering_report()}
        if self.hint_journal is not None:
            # journal size/age ride the heartbeat so the master can
            # fire hints_stale when a drain wedges
            st = self.hint_journal.stats()
            snap["hints"] = {"pending_rows": st["pending_rows"],
                             "oldest_debt_age_s": st["oldest_debt_age_s"]}
        return snap

    def tiering_report(self) -> dict:
        """Per-volume tier state + cumulative read counters for the
        master's TieringPlanner (rides every heartbeat's telemetry
        piggyback). A tiered volume's size comes from the backend's
        cached stat — one HEAD against the gateway on the first report
        after demotion, free afterwards."""
        vols = {}
        for loc in self.store.locations:
            for vid, v in list(loc.volumes.items()):
                has_ec = vid in loc.ec_volumes \
                    or self.store.has_ec_volume(vid)
                if v.is_tiered:
                    rung = "cloud"
                else:
                    rung = "ec" if has_ec else "hot"
                try:
                    size = v.content_size()
                except (IOError, OSError, ValueError):
                    size = 0  # tier endpoint blip: report, don't crash
                vols[vid] = {"reads": self.vol_reads.get(vid, 0),
                             "rung": rung, "size": size,
                             "read_only": v.read_only,
                             "has_ec_shards": has_ec}
        return {"volumes": vols, "stats": dict(self.tier_stats)}

    def _admin_telemetry(self, req: Request) -> Response:
        return Response(self.telemetry_snapshot())

    def _refresh_gauges(self) -> None:
        # runs before every exposition (scrape AND push-gateway loop)
        import os
        store = getattr(self, "store", None)
        if store is None:
            return
        hb = store.collect_heartbeat()
        self._m_volumes.set(value=len(hb.get("volumes", [])))
        self._m_ec_shards.set(value=sum(
            bin(e.get("ec_index_bits", 0)).count("1")
            for e in hb.get("ec_shards", [])))
        self._m_bytes.set(value=sum(
            v.get("size", 0) for v in hb.get("volumes", [])))
        for d in self._store_dirs:
            try:
                st = os.statvfs(d)
                self._m_disk_free.set(d, value=st.f_bavail * st.f_frsize)
            except OSError:
                pass
        cache = store.needle_cache
        if cache is not None:
            cs = cache.stats()
            for stat in ("hits", "misses", "bytes", "evictions",
                         "items", "rejects", "coalesced"):
                self._m_cache.set(stat, value=cs[stat])
        for stat, val in self.http.conn_stats().items():
            self._m_conns.set(stat, value=val)

    def _handle_metrics(self, req: Request) -> Response:
        return Response(self.metrics.expose_text(),
                        content_type="text/plain; version=0.0.4")

    def _handle_ui(self, req: Request) -> Response:
        """Status page (reference weed/server/volume_server_ui/): disk,
        concurrency, scrub progress, volumes, EC shards — server-side
        rendered, zero assets."""
        hb = self.store.collect_heartbeat()
        rows = "".join(
            f"<tr><td>{v['id']}</td><td>{v['collection']}</td>"
            f"<td>{_human_bytes(v['size'])}</td><td>{v['file_count']}</td>"
            f"<td>{v['delete_count']}</td>"
            f"<td>{v.get('disk_type', 'hdd')}</td>"
            f"<td>{'RO' if v['read_only'] else 'RW'}</td></tr>"
            for v in hb["volumes"])
        ec_rows = "".join(
            f"<tr><td>{e['id']}</td>"
            f"<td>{bin(e['ec_index_bits']).count('1')}</td>"
            f"<td><code>{e['ec_index_bits']:014b}</code></td></tr>"
            for e in hb["ec_shards"])
        disk_rows = []
        for d in self._store_dirs:
            try:
                st = os.statvfs(d)
                free = st.f_bavail * st.f_frsize
                total = st.f_blocks * st.f_frsize
                disk_rows.append(
                    f"<tr><td>{d}</td><td>{_human_bytes(total)}</td>"
                    f"<td>{_human_bytes(free)}</td></tr>")
            except OSError:
                disk_rows.append(f"<tr><td>{d}</td><td>?</td><td>?</td></tr>")
        scrub = self.scrubber.status() if self.scrubber else {}
        cur = scrub.get("current")
        if cur and cur.get("size"):
            pct = 100.0 * cur["offset"] / cur["size"]
            progress = (f"vol {cur['volume_id']} ({cur['kind']}) "
                        f"{pct:.1f}% ({_human_bytes(cur['offset'])} / "
                        f"{_human_bytes(cur['size'])})")
        else:
            progress = "idle"
        scrub_rows = (
            f"<tr><th>state</th><td>"
            f"{'running' if scrub.get('running') else 'stopped'}</td></tr>"
            f"<tr><th>progress</th><td>{progress}</td></tr>"
            f"<tr><th>rate limit</th><td>"
            f"{_human_bytes(int(scrub.get('rate_bytes_per_sec', 0)))}/s"
            f"</td></tr>"
            f"<tr><th>bytes scrubbed</th><td>"
            f"{_human_bytes(scrub.get('bytes_scrubbed', 0))}</td></tr>"
            f"<tr><th>corruptions found</th><td>"
            f"{scrub.get('corruptions_found', 0)}</td></tr>"
            f"<tr><th>passes completed</th><td>"
            f"{scrub.get('passes_completed', 0)}</td></tr>")
        html = (
            "<html><head><title>seaweedfs-tpu volume server</title>"
            "<style>body{font-family:sans-serif;margin:2em}"
            "table{border-collapse:collapse;margin-bottom:1.5em}"
            "td,th{border:1px solid #999;padding:3px 10px;"
            "text-align:left}</style></head>"
            f"<body><h1>Volume Server {self.url}</h1>"
            f"<p>master: {self.master_url} | rack: {self.store.rack}"
            f" | dc: {self.store.data_center}"
            f" | grpc: {self.grpc_port or '-'}"
            f" | tcp: {self.tcp_server.port if self.tcp_server else '-'}"
            "</p>"
            "<h2>Disk</h2><table><tr><th>dir</th><th>total</th>"
            f"<th>free</th></tr>{''.join(disk_rows)}</table>"
            "<h2>Concurrency</h2><table>"
            f"<tr><th>upload in-flight</th>"
            f"<td>{_human_bytes(self.upload_limiter.in_flight)}</td></tr>"
            f"<tr><th>download in-flight</th>"
            f"<td>{_human_bytes(self.download_limiter.in_flight)}</td>"
            "</tr></table>"
            f"<h2>Scrub</h2><table>{scrub_rows}</table>"
            f"<h2>Volumes ({len(hb['volumes'])})</h2>"
            "<table><tr><th>id</th>"
            "<th>collection</th><th>size</th><th>files</th><th>deleted</th>"
            f"<th>disk</th><th>mode</th></tr>{rows}</table>"
            f"<h2>EC shards ({len(hb['ec_shards'])} vols)</h2>"
            "<table><tr><th>vid</th><th>shards</th>"
            f"<th>bits</th></tr>{ec_rows}</table></body></html>")
        return Response(html, content_type="text/html")

    # ---- integrity scrub ----
    def _admin_scrub(self, req: Request) -> Response:
        """Trigger a synchronous scrub pass (optionally one volume).
        The background thread keeps its own schedule; this is the
        operator/shell entry point."""
        b = req.json() if req.body else {}
        vid = b.get("volume_id")
        result = self.scrubber.run_once(
            volume_id=int(vid) if vid is not None else None,
            use_cursor=bool(b.get("use_cursor", True)))
        return Response(result)

    def _admin_scrub_status(self, req: Request) -> Response:
        return Response(self.scrubber.status())

    def _report_scrub(self, report: dict) -> None:
        """Forward a corruption report to the master's repair queue,
        following a leader redirect like the heartbeat path does."""
        body = {"url": self.url, **report}
        for _attempt in range(2):
            try:
                self._master_json("POST", "/scrub/report", body,
                                  deadline=Deadline.after(5.0))
                return
            except HttpError as e:
                old = self.master_url
                self._follow_leader_hint(e)
                if self.master_url == old:
                    return
            except ConnectionError:
                self._fail_over()

    def _check_jwt(self, req: Request) -> Optional[Response]:
        if not self.jwt_signing_key or req.query.get("type") == "replicate":
            return None
        from seaweedfs_tpu.utils.security import verify_jwt
        auth = req.headers.get("Authorization", "")
        token = auth[7:] if auth.startswith("Bearer ") else \
            req.query.get("jwt", "")
        fid = f"{req.match.group(1)},{req.match.group(2)}"
        if not verify_jwt(self.jwt_signing_key, token, fid):
            return Response({"error": "unauthorized"}, status=401)
        return None

    def _check_read_jwt(self, req: Request) -> Optional[Response]:
        if not self.jwt_read_key:
            return None
        from seaweedfs_tpu.utils.security import verify_jwt
        auth = req.headers.get("Authorization", "")
        token = auth[7:] if auth.startswith("Bearer ") else             req.query.get("jwt", "")
        fid = f"{req.match.group(1)},{req.match.group(2)}"
        if not verify_jwt(self.jwt_read_key, token, fid):
            return Response({"error": "unauthorized"}, status=401)
        return None

    # ---- public data path ----
    def _upload_gate(self, path: str, length: int):
        """Pre-body-read throttle for needle uploads (reference
        volume_server_handlers.go:48-80): consulted by HttpServer with
        the declared Content-Length BEFORE buffering the payload, so N
        concurrent large PUTs wait at the socket instead of ballooning
        RSS. Admin/EC transfers are internal and exempt, as in the
        reference (their sizes are volume-bounded)."""
        if path.startswith("/admin"):
            return None
        if self.file_size_limit > 0 and length > self.file_size_limit:
            return Response({"error": f"file over the limit of "
                             f"{self.file_size_limit} bytes"}, status=413)
        if not self.upload_limiter.try_acquire(length):
            self._m_req.inc("write_shed")
            return Response(
                {"error": "too many requests"}, status=429,
                headers={"Retry-After": "2"})
        return lambda: self.upload_limiter.release(length)

    def _parse_fid(self, req: Request) -> tuple[int, int, int]:
        vid = int(req.match.group(1))
        key, cookie = parse_needle_id_cookie(req.match.group(2))
        return vid, key, cookie

    def _handle_write(self, req: Request) -> Response:
        denied = self._check_jwt(req)
        if denied:
            return denied
        self._m_req.inc("write")
        vid, key, cookie = self._parse_fid(req)
        self.hotkeys.record("needle", "%d,%x" % (vid, key))
        n = Needle(id=key, cookie=cookie, data=req.body,
                   name=req.query.get("name", "").encode(),
                   mime=req.query.get("mime", "").encode())
        if req.query.get("gzip") == "1":
            from seaweedfs_tpu.storage.needle import FLAG_IS_COMPRESSED
            n.flags |= FLAG_IS_COMPRESSED
        if req.query.get("ttl"):
            from seaweedfs_tpu.storage.needle import FLAG_HAS_TTL
            from seaweedfs_tpu.storage.super_block import TTL
            n.ttl = TTL.parse(req.query["ttl"]).to_bytes()
            n.flags |= FLAG_HAS_TTL
            if not n.last_modified:
                n.last_modified = int(clockctl.now())
            from seaweedfs_tpu.storage.needle import \
                FLAG_HAS_LAST_MODIFIED_DATE
            n.flags |= FLAG_HAS_LAST_MODIFIED_DATE
        if req.query.get("ts"):
            n.last_modified = int(req.query["ts"])
        n.set_flags_from_fields()
        try:
            size = self.store.write_volume_needle(vid, n)
        except NotFoundError:
            return Response({"error": f"volume {vid} not found"}, status=404)
        except PermissionError as e:
            return Response({"error": str(e)}, status=409)
        if req.query.get("type") != "replicate":
            err = self._replicate(req, "write")
            if err:
                return Response({"error": err}, status=500)
        return Response({"name": req.query.get("name", ""),
                         "size": len(req.body), "eTag": f"{n.checksum:x}"},
                        status=201)

    def _peek_read_size(self, req: Request) -> int:
        """Estimate a GET's payload from the needle map before touching
        disk, for download byte accounting (the reference reads the map
        entry first too: volume_read.go ReadNeedleDataInto)."""
        try:
            vid = int(req.match.group(1))
            key, _ = parse_needle_id_cookie(req.match.group(2))
        except (AttributeError, ValueError, IndexError):
            return 0
        vol = self.store.find_volume(vid)
        if vol is None:
            # EC-served volumes get accounted too (their reads
            # materialize whole needles just the same)
            ev = self.store.find_ec_volume(vid) \
                if hasattr(self.store, "find_ec_volume") else None
            if ev is not None:
                try:
                    _, size = ev.find_needle_from_ecx(key)
                    return max(int(size), 0)
                except Exception:
                    return 0
            return 0
        nv = vol.nm.get(key)
        if nv is None or nv[1] <= 0:
            return 0
        return int(nv[1])

    def _handle_read(self, req: Request) -> Response:
        # byte-accounted backpressure only on the real HTTP socket path
        # (gRPC/LocalRequest dispatch never fires on_sent)
        est = self._peek_read_size(req) \
            if getattr(req, "handler", None) is not None else 0
        if est and not self.download_limiter.try_acquire(est):
            self._m_req.inc("read_shed")
            return Response({"error": "too many requests"}, status=429,
                            headers={"Retry-After": "2"})
        try:
            # request edge: inherit the caller's propagated budget or
            # mint a fresh one; every nested hop (remote shard fetch,
            # degraded recovery, master lookup) reads this scope
            dl = Deadline.from_headers(req.headers,
                                       default=READ_DEADLINE_S)
            with deadline_scope(dl):
                resp = self._handle_read_inner(req)
        except BaseException:
            self.download_limiter.release(est)
            raise
        if est:
            resp.on_sent = lambda: self.download_limiter.release(est)
        return resp

    def _handle_read_inner(self, req: Request) -> Response:
        denied = self._check_read_jwt(req)
        if denied:
            return denied
        self._m_req.inc("read")
        vid, key, cookie = self._parse_fid(req)
        self.hotkeys.record("needle", "%d,%x" % (vid, key))
        # temperature signal for the tiering planner: demand against
        # the volume, wherever the bytes end up coming from (local,
        # EC-degraded, or the cloud tier). GIL-atomic int bump.
        self.vol_reads[vid] += 1
        if req.headers.get("Range") and \
                self.store.find_volume(vid) is None and \
                self.store.has_ec_volume(vid) and \
                not (req.query.get("width") or req.query.get("height")):
            resp = self._ec_ranged_read(req, vid, key, cookie)
            if resp is not None:
                return resp
            # else: metadata says we can't serve the subrange (v1,
            # compressed, malformed range) — fall through to full read
        if self.zero_copy:
            resp = self._zero_copy_read(req, vid, key, cookie)
            if resp is not None:
                return resp
            # else: some rung of the fallback ladder claimed the read —
            # the buffered path below is the single error/repair
            # authority and the bit-identity comparator
        try:
            if self.store.find_volume(vid) is not None:
                try:
                    n = self.store.read_volume_needle(vid, key, cookie)
                except (NotFoundError, ValueError):
                    # divergence suspect: this replica may have missed a
                    # quorum write (404) or hold a torn record (CRC) —
                    # pull from a peer and serve the repaired copy.
                    # DeletedError never repairs: tombstones are
                    # authoritative here
                    n = self._pull_repair(vid, key, cookie)
                    if n is None:
                        raise
            elif self.store.has_ec_volume(vid):
                n = self.store.read_ec_shard_needle(vid, key, cookie)
            else:
                return Response({"error": f"volume {vid} not found"},
                                status=404)
        except (NotFoundError, DeletedError):
            return Response(b"", status=404, content_type="text/plain")
        except CookieMismatchError:
            return Response(b"", status=404, content_type="text/plain")
        h = getattr(req, "handler", None)
        self.ledger.charge_disk(
            len(n.data),
            tenant=h.client_address[0] if h is not None else "-")
        headers = {}
        if n.is_compressed:
            accept = req.headers.get("Accept-Encoding", "")
            if "gzip" in accept:
                headers["Content-Encoding"] = "gzip"
            else:
                import gzip as _gz
                n.data = _gz.decompress(n.data)
        if n.last_modified:
            headers["X-Last-Modified"] = str(n.last_modified)
        mime_str = n.mime.decode(errors="replace") if n.mime else ""
        if (req.query.get("width") or req.query.get("height")) and \
                not n.is_compressed:
            from seaweedfs_tpu.utils.images import is_image, resized
            if is_image(mime_str, n.name.decode(errors="replace")):
                n.data = resized(
                    n.data,
                    int(req.query.get("width") or 0) or None,
                    int(req.query.get("height") or 0) or None,
                    req.query.get("mode", ""))
        if n.name:
            headers["X-File-Name"] = n.name.decode(errors="replace")
        if n.has_ttl and n.ttl and n.last_modified:
            from seaweedfs_tpu.storage.super_block import TTL
            ttl = TTL.from_bytes(n.ttl)
            if ttl.minutes and \
                    clockctl.now() > n.last_modified + ttl.minutes * 60:
                return Response(b"", status=404, content_type="text/plain")
        mime = (n.mime.decode(errors="replace")
                if n.mime else "application/octet-stream")
        # cache-aware routing: advertise when this read was (or is now)
        # backed by the hot-needle cache so clients can prefer this
        # replica for the next read of the same needle
        cache = self.store.needle_cache
        if cache is not None and cache.contains(vid, key):
            headers[weed_headers.CACHE_HOT] = "1"
        from seaweedfs_tpu.utils.httpd import (RangeNotSatisfiable,
                                               parse_byte_range)
        try:
            rng = parse_byte_range(req.headers.get("Range", ""),
                                   len(n.data))
        except RangeNotSatisfiable:
            headers["Content-Range"] = f"bytes */{len(n.data)}"
            return Response(b"", status=416, content_type=mime,
                            headers=headers)
        if rng is not None:
            lo, hi = rng
            piece = n.data[lo:hi + 1]
            headers["Content-Range"] = f"bytes {lo}-{hi}/{len(n.data)}"
            return Response(piece, status=206, content_type=mime,
                            headers=headers)
        headers["ETag"] = f'"{n.checksum:x}"'
        if req.headers.get("If-None-Match") == f'"{n.checksum:x}"':
            return Response(b"", status=304, content_type=mime)
        return Response(n.data, content_type=mime, headers=headers)

    def _zero_copy_read(self, req: Request, vid: int, key: int,
                        cookie) -> Optional[Response]:
        """Descriptor fast path: answer a whole-needle or Range GET
        with ``send_file(fd, offset, count)`` so the payload moves
        page-cache -> socket inside the kernel. Returns None to fall
        back to the buffered path — the explicit ladder:

        - in-process dispatch (no socket to sendfile to)
        - image resize (must materialize and transform)
        - cached needle (memory beats disk; keeps cache-aware routing)
        - EC / tiered / v1 volumes, expired volumes, malformed records
        - any lookup error (buffered path owns read-repair + 404 shape)
        - compressed payload for a client that doesn't accept gzip
        - TTL-expired needle (buffered 404 shape kept)
        - payloads under zero_copy_min (syscall setup beats the copy
          only above a threshold; small hot needles feed the cache)

        The ETag is the record's STORED crc — identical to the
        buffered path's computed value for locally written records."""
        if getattr(req, "handler", None) is None:
            return None
        if req.query.get("width") or req.query.get("height"):
            return None
        desc = self.store.read_volume_needle_descriptor(vid, key, cookie)
        if desc is None:
            return None
        n, fd, payload_off, data_size = desc
        try:
            if data_size < self.zero_copy_min:
                return None
            if n.is_compressed and "gzip" not in \
                    req.headers.get("Accept-Encoding", ""):
                return None
            if n.has_ttl and n.ttl and n.last_modified:
                from seaweedfs_tpu.storage.super_block import TTL
                ttl = TTL.from_bytes(n.ttl)
                if ttl.minutes and clockctl.now() > \
                        n.last_modified + ttl.minutes * 60:
                    return None  # buffered path serves the 404 shape
            h = req.handler
            self.ledger.charge_disk(data_size,
                                    tenant=h.client_address[0])
            headers = {weed_headers.ZERO_COPY: "1"}
            if n.is_compressed:
                headers["Content-Encoding"] = "gzip"
            if n.last_modified:
                headers["X-Last-Modified"] = str(n.last_modified)
            if n.name:
                headers["X-File-Name"] = n.name.decode(errors="replace")
            mime = (n.mime.decode(errors="replace")
                    if n.mime else "application/octet-stream")
            from seaweedfs_tpu.utils.httpd import (RangeNotSatisfiable,
                                                   parse_byte_range,
                                                   send_file)
            try:
                rng = parse_byte_range(req.headers.get("Range", ""),
                                       data_size)
            except RangeNotSatisfiable:
                headers["Content-Range"] = f"bytes */{data_size}"
                return Response(b"", status=416, content_type=mime,
                                headers=headers)
            self._m_req.inc("read_zero_copy")
            if rng is not None:
                lo, hi = rng
                headers["Content-Range"] = f"bytes {lo}-{hi}/{data_size}"
                return send_file(fd, payload_off + lo, hi - lo + 1,
                                 status=206, content_type=mime,
                                 headers=headers)
            headers["ETag"] = f'"{n.checksum:x}"'
            if req.headers.get("If-None-Match") == f'"{n.checksum:x}"':
                return Response(b"", status=304, content_type=mime)
            return send_file(fd, payload_off, data_size,
                             content_type=mime, headers=headers)
        finally:
            # send_file dup'd its own handle; the descriptor's is ours
            os.close(fd)

    def _ec_ranged_read(self, req: Request, vid: int, key: int,
                        cookie) -> Optional[Response]:
        """Subrange degraded read: satisfy an EC Range request by
        reconstructing ONLY the needle's requested byte range, not the
        whole record — when a shard is missing, recovery cost scales
        with the range, not the needle (or large-block) size. Returns
        None to fall back to the whole-needle path (v1 volume,
        compressed data, no parsable range)."""
        from seaweedfs_tpu.utils.httpd import (RangeNotSatisfiable,
                                               parse_byte_range)
        try:
            n, data_size = self.store.ec_needle_meta(vid, key, cookie)
        except (NotFoundError, DeletedError, CookieMismatchError):
            return Response(b"", status=404, content_type="text/plain")
        except ValueError:
            return None  # v1 layout: data offset isn't knowable cheaply
        if n.is_compressed or data_size == 0:
            return None  # must inflate (or 404) via the full path
        headers = {}
        if n.last_modified:
            headers["X-Last-Modified"] = str(n.last_modified)
        if n.name:
            headers["X-File-Name"] = n.name.decode(errors="replace")
        if n.has_ttl and n.ttl and n.last_modified:
            from seaweedfs_tpu.storage.super_block import TTL
            ttl = TTL.from_bytes(n.ttl)
            if ttl.minutes and \
                    clockctl.now() > n.last_modified + ttl.minutes * 60:
                return Response(b"", status=404, content_type="text/plain")
        mime = (n.mime.decode(errors="replace")
                if n.mime else "application/octet-stream")
        try:
            rng = parse_byte_range(req.headers["Range"], data_size)
        except RangeNotSatisfiable:
            headers["Content-Range"] = f"bytes */{data_size}"
            return Response(b"", status=416, content_type=mime,
                            headers=headers)
        if rng is None:
            return None  # malformed spec -> full body per RFC
        lo, hi = rng
        try:
            piece = self.store.read_ec_needle_data_range(
                vid, key, lo, hi - lo + 1)
        except (NotFoundError, DeletedError):
            return Response(b"", status=404, content_type="text/plain")
        except Exception as e:
            glog.warning("ec subrange read v%d,%x failed (%s); "
                         "falling back to full read", vid, key, e)
            return None
        self._m_req.inc("ec_subrange")
        headers["Content-Range"] = f"bytes {lo}-{hi}/{data_size}"
        return Response(piece, status=206, content_type=mime,
                        headers=headers)

    def _handle_delete(self, req: Request) -> Response:
        denied = self._check_jwt(req)
        if denied:
            return denied
        self._m_req.inc("delete")
        vid, key, cookie = self._parse_fid(req)
        try:
            if self.store.find_volume(vid) is not None:
                size = self.store.delete_volume_needle(vid, key, cookie)
            elif self.store.has_ec_volume(vid):
                size = self._ec_delete_fanout(vid, key, cookie)
            else:
                return Response({"error": f"volume {vid} not found"},
                                status=404)
        except (NotFoundError, DeletedError):
            return Response({"size": 0}, status=404)
        if req.query.get("type") != "replicate" \
                and self.store.find_volume(vid) is not None:
            err = self._replicate(req, "delete")
            if err:
                return Response({"error": err}, status=500)
        return Response({"size": size}, status=202)

    REPLICA_CACHE_TTL = 5.0  # matches the freshest vidMap tier

    def _replica_peers(self, vid: int) -> list[str]:
        """Peer replica urls for a volume, with a short-TTL cache — a
        master /dir/lookup per write would cost more than the write
        itself (the reference's writers resolve replicas through the
        wdclient vidMap cache the same way)."""
        now = clockctl.monotonic()
        cached = self._replica_cache.get(vid)
        if cached is not None and cached[0] > now:
            return cached[1]
        try:
            locs = self._master_json(
                "GET", f"/dir/lookup?volumeId={vid}",
                deadline=Deadline.after(5.0))
        except (ConnectionError, HttpError):
            return []  # nobody to replicate to (not registered yet)
        others = [l["url"] for l in locs.get("locations", [])
                  if not self._is_self(l["url"])]
        self._replica_cache[vid] = (now + self.REPLICA_CACHE_TTL, others)
        return others

    # Edge budget for one replica fan-out when the client sent none:
    # bounds the whole concurrent batch, not each leg.
    REPLICATE_DEADLINE_S = 20.0

    def _replicate_pool_get(self):
        if self._replicate_pool is None:
            with self._replicate_pool_lock:
                if self._replicate_pool is None:
                    from concurrent.futures import ThreadPoolExecutor
                    self._replicate_pool = ThreadPoolExecutor(
                        max_workers=16, thread_name_prefix="replicate")
        return self._replicate_pool

    def _replicate(self, req: Request, op: str) -> Optional[str]:
        """Synchronous fan-out to the other replicas
        (reference topology/store_replicate.go:58-110), posted to ALL
        peers concurrently so a replicated write costs ~max(peers)
        instead of sum(peers). Per-peer circuit breakers fail fast on
        known-down replicas; any failure drops the cached peer list so
        the next write re-resolves the (possibly moved) topology
        instead of pinning the error for the cache TTL.

        With hinted handoff on, the fan-out is a SLOPPY QUORUM: the
        local write plus a majority of the peer legs completes the
        request, and each missed leg is journaled as a hint the drain
        thread replays after the peer heals (read-repair covers reads
        that hit the lagging replica meanwhile). Only falling below
        the quorum fails the write."""
        vid = int(req.match.group(1))
        vol = self.store.find_volume(vid)
        if vol is not None and \
                vol.super_block.replica_placement.to_byte() == 0:
            # single-copy volume: no peers can exist, skip the lookup
            return None
        others = self._replica_peers(vid)
        if not others:
            return None
        qs = "&".join(f"{k}={v}" for k, v in req.query.items()
                      if k != "type")
        sep = "&" if qs else ""
        dl = current_deadline() or Deadline.after(self.REPLICATE_DEADLINE_S)
        # pool legs don't inherit contextvars: capture the ambient
        # class (a replica leg of a client PUT stays write class) and
        # the ambient trace span, so each replica leg's http_call nests
        # as a child span of the PUT that fanned out
        cls = current_class() or WRITE
        span = tracing.current_span()
        if span is not None:
            span.annotate("replica.fanout", len(others))

        def send(url: str) -> Optional[str]:
            if not self.peer_health.allow(url):
                return f"replica {url}: circuit open"
            target = (f"http://{url}{req.path}?{qs}{sep}type=replicate")
            t0 = clockctl.monotonic()
            try:
                with class_scope(cls), tracing.span_scope(span):
                    if op == "write":
                        status, _body, _ = http_call("POST", target,
                                                     body=req.body,
                                                     deadline=dl)
                    else:
                        status, _body, _ = http_call("DELETE", target,
                                                     deadline=dl)
            except ConnectionError as e:
                self.peer_health.record(url, False)
                return f"replica {url}: {e}"
            # an HTTP answer means the peer is up (same convention as
            # _master_json); the write itself may still have failed
            self.peer_health.record(url, True, clockctl.monotonic() - t0)
            if status >= 400 and status != 404:
                return f"replica {url}: HTTP {status}"
            return None

        if len(others) == 1 or not self.parallel_replication:
            errs = [send(u) for u in others]
        else:
            errs = list(self._replicate_pool_get().map(send, others))
        failed = [(u, e) for u, e in zip(others, errs) if e]
        if not failed:
            return None
        self._replica_cache.pop(vid, None)
        # quorum of the PEER legs (the local write already landed):
        # floor(len/2) keeps a 2-copy volume writable with its only
        # peer dark — availability-biased, the hint closes the gap
        if self.hinted_handoff and self.hint_journal is not None \
                and len(others) - len(failed) >= len(others) // 2:
            key, cookie = parse_needle_id_cookie(req.match.group(2))
            for url, why in failed:
                self.hint_journal.record(op, vid, key, cookie, url,
                                         fid=req.match.group(2))
                glog.warning("replica %s missed %s of %d,%x (%s); "
                             "hint journaled", url, op, vid, key, why)
            if span is not None:
                span.annotate("replica.hinted", len(failed))
            self._m_req.inc("replica_hinted")
            return None
        return "; ".join(why for _, why in failed)

    # cadence of the hint drain pass (the pass itself is cheap when
    # nothing is pending: one dict snapshot)
    HINT_DRAIN_INTERVAL_S = 2.0

    def _hint_drain_loop(self) -> None:
        while not self._stop.wait(self.HINT_DRAIN_INTERVAL_S):
            try:
                self.drain_hints()
            except Exception as e:
                glog.warning("hint drain pass failed (will retry): %s", e)

    def drain_hints(self, limit: int = 256) -> int:
        """One drain pass: replay up to `limit` pending hints, oldest
        first, skipping peers whose breaker is still open. Returns the
        number repaid. Public so drills can force a synchronous drain
        instead of waiting out the loop cadence.

        The BACKGROUND class scope lives HERE, not in the loop: every
        replayed write must carry the background QoS class to the peer
        (http_call stamps X-Weed-Class from the ambient scope), so a
        drain burst after a partition heals queues behind foreground
        traffic — including when a drill invokes this synchronously."""
        j = self.hint_journal
        if j is None or self.store is None:
            return 0
        drained = 0
        with class_scope(BACKGROUND), \
                profiler.scope(cls=BACKGROUND, route="hints"):
            for h in j.pending()[:limit]:
                if self._stop.is_set():
                    break
                if not self.peer_health.allow(h["peer"]):
                    continue
                try:
                    ok = self._replay_hint(h)
                except Exception as e:
                    glog.warning("hint replay %s failed: %s", h, e)
                    ok = False
                if ok:
                    j.ack(h["seq"])
                    drained += 1
        if drained:
            self._m_req.inc("hint_drained")
        return drained

    def _replay_hint(self, h: dict) -> bool:
        """Repay one hint. True means the debt is settled (replayed,
        or moot: needle/volume gone locally, peer no longer hosts the
        volume); False means keep it pending for the next pass."""
        url = h["peer"]
        vid, key = int(h["vid"]), int(h["key"])
        if self._is_self(url):
            return True  # topology moved the replica onto us
        if h["op"] == "delete":
            t0 = clockctl.monotonic()
            try:
                status, _, _ = http_call(
                    "DELETE", f"http://{url}/{vid},{h['fid']}"
                    "?type=replicate",
                    deadline=Deadline.after(10.0))
            except ConnectionError:
                self.peer_health.record(url, False)
                return False
            self.peer_health.record(url, True, clockctl.monotonic() - t0)
            return status < 400 or status == 404
        v = self.store.find_volume(vid)
        if v is None:
            return True  # volume left this node: nothing to hand off
        try:
            blob, size = v.read_needle_blob(key)
        except Exception:
            # deleted (or never committed) since the hint was taken —
            # the delete got its own hint, this one is moot
            return True
        t0 = clockctl.monotonic()
        try:
            status, _, _ = http_call(
                "POST", f"http://{url}/admin/write_needle_blob",
                json_body={"volume_id": vid, "blob": blob.hex(),
                           "size": size},
                deadline=Deadline.after(20.0))
        except ConnectionError:
            self.peer_health.record(url, False)
            return False
        self.peer_health.record(url, True, clockctl.monotonic() - t0)
        # 404 = the peer no longer hosts the volume (moved/rebuilt):
        # the debt is no longer owed to THIS peer
        return status < 400 or status == 404

    # budget for one peer blob fetch during in-line read repair when
    # the read arrived without an ambient deadline
    PULL_REPAIR_DEADLINE_S = 10.0

    def _pull_repair(self, vid: int, key: int,
                     cookie: Optional[int] = None) -> Optional[Needle]:
        """In-line read repair: this replica is missing (or holds a
        corrupt copy of) a needle that a replicated volume should have.
        Pull the raw record from a healthy peer, land it locally with
        strict cache invalidation, and return the repaired needle —
        the read that detected the divergence is also the one that
        heals it. Returns None when no peer can supply the record
        (including the legitimate case: the needle never existed)."""
        if not self.hinted_handoff:
            return None
        v = self.store.find_volume(vid)
        if v is None or v.read_only or v.is_expired():
            return None
        if v.super_block.replica_placement.to_byte() == 0:
            return None  # single copy: nothing to diverge from
        peers = self._replica_peers(vid)
        if not peers:
            return None
        dl = current_deadline() or \
            Deadline.after(self.PULL_REPAIR_DEADLINE_S)
        blob = None
        size = 0
        for url in self.peer_health.rank(peers):
            if not self.peer_health.allow(url):
                continue
            t0 = clockctl.monotonic()
            try:
                out = http_json(
                    "GET", f"http://{url}/admin/needle_blob"
                    f"?volumeId={vid}&key={key}", deadline=dl)
            except HttpError:
                # the peer answered but doesn't have it either
                self.peer_health.record(url, True,
                                        clockctl.monotonic() - t0)
                continue
            except ConnectionError:
                self.peer_health.record(url, False)
                continue
            self.peer_health.record(url, True, clockctl.monotonic() - t0)
            blob, size = bytes.fromhex(out["blob"]), int(out["size"])
            break
        if blob is None:
            return None
        cache = self.store.needle_cache
        if cache is not None:
            # same double-invalidation discipline as
            # Store.write_volume_needle: no stale epoch can be admitted
            cache.invalidate(vid, key)
        try:
            v.write_needle_blob(blob, size)
        except Exception as e:
            glog.warning("read repair of %d,%x failed to land: %s",
                         vid, key, e)
            return None
        finally:
            if cache is not None:
                cache.invalidate(vid, key)
        self._m_req.inc("read_repair")
        glog.info("read-repaired %d,%x from a peer replica", vid, key)
        try:
            return self.store.read_volume_needle(vid, key, cookie)
        except Exception:
            return None

    def _admin_replica_repair(self, req: Request) -> Response:
        """A reader observed this replica lagging (404 here while a
        sibling served the needle): pull the record from a peer now
        instead of waiting for the owner's hint drain."""
        b = req.json()
        vid, key = int(b["volume_id"]), int(b["key"])
        if self.store.find_volume(vid) is None:
            return Response({"error": f"volume {vid} not found"},
                            status=404)
        try:
            self.store.read_volume_needle(vid, key)
            return Response({"repaired": False, "present": True})
        except DeletedError:
            # our tombstone is authoritative — the reporter raced a
            # delete, which the delete fan-out/hints will settle
            return Response({"repaired": False, "present": True})
        except (NotFoundError, ValueError):
            pass
        n = self._pull_repair(vid, key)
        if n is None:
            return Response(
                {"error": "no peer could supply the needle"}, status=409)
        return Response({"repaired": True, "size": len(n.data)})

    def _admin_hints(self, req: Request) -> Response:
        j = self.hint_journal
        if j is None:
            return Response({"url": self.url, "enabled": False,
                             "pending": 0})
        return Response({"url": self.url, "enabled": True,
                         **j.stats(),
                         "hints": j.pending()[:100]})

    def _handle_status(self, req: Request) -> Response:
        hb = self.store.collect_heartbeat()
        extra = {}
        if self.tcp_server is not None:
            extra["TcpPort"] = self.tcp_server.port
        with self._lease_lock:
            extra["Leases"] = {"held": len(self._leases),
                               **self.lease_stats}
        return Response({"Version": "seaweedfs-tpu 0.1", **extra, **hb})

    # ---- admin ----
    def _admin_allocate_volume(self, req: Request) -> Response:
        b = req.json()
        try:
            self.store.add_volume(b["volume_id"], b.get("collection", ""),
                                  b.get("replication", "000"),
                                  b.get("ttl", ""),
                                  disk_type=b.get("disk_type", ""))
        except ValueError as e:
            return Response({"error": str(e)}, status=400)
        return Response({})

    def _admin_delete_volume(self, req: Request) -> Response:
        b = req.json()
        ok = self.store.delete_volume(b["volume_id"])
        self._push_deltas()
        return Response({"deleted": ok})

    def _admin_mark_readonly(self, req: Request) -> Response:
        b = req.json()
        ok = self.store.mark_volume_readonly(b["volume_id"],
                                             b.get("read_only", True))
        return Response({"ok": ok})

    def _admin_mount_volume(self, req: Request) -> Response:
        """Attach a volume whose files are already on disk (reference
        volume_grpc_admin.go VolumeMount)."""
        ok = self.store.mount_volume(req.json()["volume_id"])
        self._push_deltas()
        return Response({"mounted": ok} if ok else
                        {"error": "volume files not found"},
                        status=200 if ok else 404)

    def _admin_unmount_volume(self, req: Request) -> Response:
        """Detach without deleting files (reference VolumeUnmount)."""
        ok = self.store.unmount_volume(req.json()["volume_id"])
        self._push_deltas()
        return Response({"unmounted": ok} if ok else
                        {"error": "volume not found"},
                        status=200 if ok else 404)

    def _admin_configure_replication(self, req: Request) -> Response:
        """Rewrite a volume's replica placement in its superblock
        (reference command_volume_configure_replication.go)."""
        b = req.json()
        v = self.store.find_volume(b["volume_id"])
        if v is None:
            return Response({"error": "volume not found"}, status=404)
        v.configure_replication(b["replication"])
        self.heartbeat_once()  # re-announce with the new placement
        return Response({"replication": b["replication"]})

    def _admin_volume_file_status(self, req: Request) -> Response:
        """HTTP twin of the ReadVolumeFileStatus gRPC: file sizes,
        mtimes, counts — what shell planners gate destructive ops on."""
        vid = int(req.query["volumeId"])
        v = self.store.find_volume(vid)
        if v is None:
            return Response({"error": "volume not found"}, status=404)
        v.sync()
        base = v.file_name()
        out = {"volume_id": vid, "collection": v.collection,
               "file_count": v.file_count(),
               "last_append_at_ns": v.last_append_at_ns}
        for ext, ts_key, size_key in (
                (".idx", "idx_file_timestamp_seconds", "idx_file_size"),
                (".dat", "dat_file_timestamp_seconds", "dat_file_size")):
            try:
                st = os.stat(base + ext)
                out[ts_key] = int(st.st_mtime)
                out[size_key] = st.st_size
            except OSError:
                pass
        return Response(out)

    def _admin_batch_delete(self, req: Request) -> Response:
        """HTTP twin of the BatchDelete gRPC (local deletes only; the
        caller addresses each replica — reference
        volume_grpc_batch_delete.go)."""
        from seaweedfs_tpu.storage.file_id import FileId
        b = req.json()
        skip = b.get("skip_cookie_check", False)
        results = []
        for fid in b.get("file_ids", []):
            r = {"file_id": fid, "status": 202, "error": "", "size": 0}
            try:
                f = FileId.parse(fid)
                r["size"] = self.store.delete_volume_needle(
                    f.volume_id, f.key, None if skip else f.cookie)
            except (ValueError, KeyError):
                r["status"], r["error"] = 400, "malformed file id"
            except (NotFoundError, DeletedError) as e:
                r["status"], r["error"] = 404, str(e) or "not found"
            except PermissionError as e:
                r["status"], r["error"] = 403, str(e)
            except Exception as e:
                r["status"], r["error"] = 500, f"{type(e).__name__}: {e}"
            results.append(r)
        return Response({"results": results})

    def _admin_leave(self, req: Request) -> Response:
        """Stop heartbeating and unregister from the master — graceful
        drain (reference shell command_volume_server_leave.go)."""
        self._stop.set()
        try:
            http_json("POST", f"http://{self.master_url}/dir/leave",
                      {"url": self.url})
        except (ConnectionError, HttpError) as e:
            return Response({"left": True, "master": str(e)})
        return Response({"left": True})

    def _admin_vacuum(self, req: Request) -> Response:
        b = req.json()
        v = self.store.find_volume(b["volume_id"])
        if v is None:
            return Response({"error": "volume not found"}, status=404)
        garbage = v.garbage_level()
        if b.get("check_only"):
            return Response({"garbage_ratio": garbage})
        cache = self.store.needle_cache
        if cache is not None:
            # vacuum rewrites offsets under the volume: strict drop,
            # before AND after compaction (same race shape as
            # Store.write_volume_needle's double invalidation)
            cache.invalidate_volume(v.id)
        try:
            v.compact()
        finally:
            if cache is not None:
                cache.invalidate_volume(v.id)
        return Response({"garbage_ratio": garbage, "compacted": True})

    def _admin_sync(self, req: Request) -> Response:
        b = req.json() or {}
        v = self.store.find_volume(b.get("volume_id", 0))
        if v:
            v.sync()
        return Response({})

    def _admin_move_volume_disk(self, req: Request) -> Response:
        """Intra-node tier move: relocate a volume's files to a
        location of another disk type (volume.tier.move on one
        server)."""
        b = req.json()
        try:
            ok = self.store.move_volume_disk(b["volume_id"],
                                             b.get("disk_type", ""))
        except ValueError as e:
            return Response({"error": str(e)}, status=400)
        if not ok:
            return Response({"error": "volume not found"}, status=404)
        self._push_deltas()
        return Response({"moved": b["volume_id"]})

    def _admin_copy_volume(self, req: Request) -> Response:
        """Pull a volume's .dat/.idx from a peer and load it
        (reference volume_grpc_copy.go VolumeCopy)."""
        b = req.json()
        vid = b["volume_id"]
        collection = b.get("collection", "")
        src = b["source_data_node"]
        if self.store.find_volume(vid) is not None:
            return Response({"error": f"volume {vid} already exists"},
                            status=409)
        # "" IS the hdd tier, same strictness as add_volume: an
        # untyped copy (balance/evacuate/fix.replication) must not
        # silently flip an hdd volume onto an ssd dir
        want = b.get("disk_type", "") or "hdd"
        candidates = [l for l in self.store.locations
                      if l.disk_type == want]
        if not candidates:
            return Response(
                {"error": f"no {want!r} disk on this server"}, status=400)
        loc = min(candidates, key=lambda l: l.volumes_len())
        name = f"{collection}_{vid}" if collection else str(vid)
        base = os.path.join(loc.directory, name)
        for ext in (".dat", ".idx"):
            url = (f"http://{src}/admin/volume_file?volumeId={vid}"
                   f"&ext={ext}&collection={collection}")
            status, body, hdrs = http_call("GET", url, timeout=300)
            if status >= 400:
                return Response({"error": f"copy {ext}: HTTP {status}"},
                                status=500)
            with open(base + ext, "wb") as f:
                f.write(body)
            # preserve the source's mtime: a replica copy must NOT
            # restart a TTL volume's expiry clock
            src_mtime = hdrs.get(weed_headers.FILE_MTIME)
            if src_mtime:
                os.utime(base + ext, (float(src_mtime),
                                      float(src_mtime)))
        from seaweedfs_tpu.storage.volume import Volume
        vol = Volume(loc.directory, collection, vid)
        loc.add_volume(vol)
        self.store.new_volumes.append(self.store.volume_info(vol))
        self._push_deltas()
        return Response({})

    def _tier_key(self, v) -> str:
        """Node-unique S3 object key for this replica's .dat: replicas
        of a volume compact independently and need not be
        byte-identical, so each node demotes to its own object — a
        shared key would let one replica's upload corrupt another's
        verified copy."""
        return (f"{self.url.replace(':', '_')}_"
                f"{os.path.basename(v.file_name())}.dat")

    def _admin_tier_upload(self, req: Request) -> Response:
        """Move a sealed volume's .dat to an S3-compatible tier
        (reference volume_grpc_tier_upload.go)."""
        b = req.json()
        v = self.store.find_volume(b["volume_id"])
        if v is None:
            return Response({"error": "volume not found"}, status=404)
        try:
            info = v.tier_to(b["endpoint"], b["bucket"],
                             keep_local=b.get("keep_local", False),
                             key=self._tier_key(v))
        except (ValueError, RuntimeError, IOError) as e:
            return Response({"error": str(e)}, status=409)
        return Response({"tiered": v.id, "remote": info.get("remote")})

    def _admin_tier_download(self, req: Request) -> Response:
        """Pull a tiered volume's .dat back to local disk
        (reference volume_grpc_tier_download.go)."""
        b = req.json()
        v = self.store.find_volume(b["volume_id"])
        if v is None:
            return Response({"error": "volume not found"}, status=404)
        try:
            v.untier()
        except (ValueError, RuntimeError, IOError) as e:
            return Response({"error": str(e)}, status=409)
        return Response({"downloaded": v.id})

    def _admin_tier_status(self, req: Request) -> Response:
        """Per-rung census + move counters for tier_profile and
        volume.tier.status."""
        report = self.tiering_report()
        rungs = collections.Counter(
            v["rung"] for v in report["volumes"].values())
        return Response({"url": self.url, "rungs": dict(rungs),
                         **report})

    def _admin_tier_demote(self, req: Request) -> Response:
        """One rung down, BACKGROUND-classed: the S3 upload + readback
        verify inside tier_to must never ride the interactive QoS lane
        (this scope also stamps X-Weed-Class on the outbound PUTs)."""
        b = req.json()
        vid = b["volume_id"]
        v = self.store.find_volume(vid)
        if v is None:
            return Response({"error": "volume not found"}, status=404)
        size = 0
        try:
            with class_scope(BACKGROUND):
                size = v.content_size() if not v.is_tiered else 0
                info = v.tier_to(b["endpoint"], b["bucket"],
                                 keep_local=b.get("keep_local", False),
                                 key=self._tier_key(v))
        except (ValueError, RuntimeError, IOError) as e:
            self.tier_stats["failed"] += 1
            return Response({"error": str(e)}, status=409)
        self.tier_stats["demotes"] += 1
        self.tier_stats["bytes_demoted"] += size
        self._push_deltas()
        return Response({"demoted": vid, "rung": "cloud",
                         "remote": info.get("remote")})

    def _admin_tier_promote(self, req: Request) -> Response:
        """One rung up, BACKGROUND-classed: fetch from the tier,
        verify size + chained crc32c against the .vif record, reopen
        local (the re-heat path)."""
        b = req.json()
        vid = b["volume_id"]
        v = self.store.find_volume(vid)
        if v is None:
            return Response({"error": "volume not found"}, status=404)
        try:
            with class_scope(BACKGROUND):
                v.untier()
        except (ValueError, RuntimeError, IOError) as e:
            self.tier_stats["failed"] += 1
            return Response({"error": str(e)}, status=409)
        self.tier_stats["promotes"] += 1
        self.tier_stats["bytes_promoted"] += v.content_size()
        self._push_deltas()
        return Response({"promoted": vid, "rung": "hot"})

    def _admin_volume_digest(self, req: Request) -> Response:
        """Live (key,size) inventory + digest of one volume replica, for
        volume.check.disk (reference command_volume_check_disk.go
        compares replicas' idx contents)."""
        import hashlib
        vid = int(req.query["volumeId"])
        v = self.store.find_volume(vid)
        if v is None:
            return Response({"error": "volume not found"}, status=404)
        entries = v.live_entries()
        h = hashlib.md5()
        for k, s in entries:
            h.update(k.to_bytes(8, "big") + s.to_bytes(4, "big", signed=True))
        return Response({"volume_id": vid, "file_count": len(entries),
                         "digest": h.hexdigest(),
                         "keys": [[k, s] for k, s in entries]})

    def _admin_needle(self, req: Request) -> Response:
        """Fetch one needle's full record fields by key — the transfer
        unit of volume.check.disk -fix (reference readSourceNeedleBlob)."""
        vid = int(req.query["volumeId"])
        key = int(req.query["key"])
        v = self.store.find_volume(vid)
        if v is None:
            return Response({"error": "volume not found"}, status=404)
        try:
            n = v.read_needle(key)
        except Exception as e:
            return Response({"error": str(e)}, status=404)
        return Response({"key": key, "cookie": n.cookie,
                         "data": n.data.hex(),
                         "name": n.name.decode(errors="replace"),
                         "mime": n.mime.decode(errors="replace")})

    def _admin_needle_blob(self, req: Request) -> Response:
        """Raw needle record for lossless replica repair."""
        vid = int(req.query["volumeId"])
        key = int(req.query["key"])
        v = self.store.find_volume(vid)
        if v is None:
            return Response({"error": "volume not found"}, status=404)
        try:
            blob, size = v.read_needle_blob(key)
        except Exception as e:
            return Response({"error": str(e)}, status=404)
        return Response({"size": size, "blob": blob.hex()})

    def _admin_write_needle_blob(self, req: Request) -> Response:
        b = req.json()
        v = self.store.find_volume(b["volume_id"])
        if v is None:
            return Response({"error": "volume not found"}, status=404)
        try:
            v.write_needle_blob(bytes.fromhex(b["blob"]), b["size"])
        except Exception as e:
            return Response({"error": str(e)}, status=409)
        if self.store.needle_cache is not None:
            # repair path lands raw records without surfacing the key:
            # whole-volume drop keeps the cache strictly consistent
            self.store.needle_cache.invalidate_volume(v.id)
        return Response({})

    def _admin_volume_file(self, req: Request) -> Response:
        vid = int(req.query["volumeId"])
        v = self.store.find_volume(vid)
        if v is None:
            return Response({"error": "volume not found"}, status=404)
        ext = req.query["ext"]
        if ext not in (".dat", ".idx"):
            return Response({"error": "bad ext"}, status=400)
        v.sync()
        path = v.file_name() + ext
        with open(path, "rb") as f:
            return Response(
                f.read(), content_type="application/octet-stream",
                headers={weed_headers.FILE_MTIME:
                         str(os.stat(path).st_mtime)})

    # ---- EC rpcs (reference volume_grpc_erasure_coding.go) ----
    def _ec_generate(self, req: Request) -> Response:
        b = req.json()
        base = self.store.generate_ec_shards(
            b["volume_id"], pipelined=b.get("pipelined", True),
            code=b.get("code", ""))
        return Response({"base": os.path.basename(base)})

    def _ec_volume_coder(self, base: str) -> ErasureCoder:
        """The coder for the volume at `base`, per its .vif CodeSpec
        (store default when absent — legacy volumes are RS(10,4))."""
        from seaweedfs_tpu.storage.erasure_coding.ec_volume import \
            read_volume_info
        return self.store.coder_for_scheme(
            scheme_from_dict(read_volume_info(base).get("code")))

    def _ec_rebuild(self, req: Request) -> Response:
        b = req.json()
        vid = b["volume_id"]
        base = self._ec_base_name(vid, b.get("collection", ""))
        coder = self._ec_volume_coder(base)
        stats: dict = {}
        rebuilt = ecenc.rebuild_ec_files(base, coder,
                                         pipelined=b.get("pipelined", True),
                                         stats=stats)
        ecenc.rebuild_ecx_file(base)
        # shard_size lets the caller (the master's repair queue) account
        # the bytes this repair moved over the wire
        shard_size = 0
        for sid in rebuilt:
            p = base + layout.shard_ext(sid)
            if os.path.exists(p):
                shard_size = os.path.getsize(p)
                break
        sources = stats.get("sources") or []
        strategy = self._record_strategy(vid, coder, sources, "full")
        return Response({"rebuilt_shard_ids": rebuilt,
                         "shard_size": shard_size,
                         "read_bytes": stats.get(
                             "read_bytes", stats.get("bytes_in", 0)),
                         "sources": list(sources),
                         "strategy": strategy})

    def _record_strategy(self, vid: int, coder: ErasureCoder,
                         sources: list, mode: str) -> str:
        """Classify + remember the repair strategy a rebuild used:
        'local' when the planned source set is narrower than k (an LRC
        group repair), 'global' otherwise."""
        k = coder.scheme.data_shards
        plan_capable = hasattr(coder, "plan_rebuild")
        strategy = "local" if plan_capable and sources \
            and len(sources) < k else "global"
        self._ec_last_strategy[vid] = {
            "strategy": strategy, "sources": list(sources), "mode": mode}
        return strategy

    def _ec_base_name(self, vid: int, collection: str = "") -> str:
        name = f"{collection}_{vid}" if collection else str(vid)
        for loc in self.store.locations:
            base = os.path.join(loc.directory, name)
            if os.path.exists(base + ".ecx") or \
                    any(os.path.exists(base + layout.shard_ext(i))
                        for i in range(layout.TOTAL_SHARDS_COUNT)):
                return base
        return os.path.join(self.store.locations[0].directory, name)

    def _ec_copy(self, req: Request) -> Response:
        """Pull shard files (+ .ecx/.ecj/.vif) from a source server
        (reference VolumeEcShardsCopy:117-168)."""
        b = req.json()
        vid = b["volume_id"]
        src = b["source_data_node"]
        base = self._ec_base_name(vid, b.get("collection", ""))
        exts = [layout.shard_ext(sid) for sid in b.get("shard_ids", [])]
        if b.get("copy_ecx_file", True):
            exts += [".ecx"]
        exts += [e for e in (".ecj", ".vif") if b.get("copy_aux", True)]
        copied = 0
        for ext in exts:
            url = (f"http://{src}/admin/ec/shard_file?volumeId={vid}"
                   f"&ext={ext}&collection={b.get('collection', '')}")
            # idempotent GET: jittered budget-gated retries ride out a
            # transient peer blip mid-repair instead of failing the
            # whole copy step
            status, body, _ = self.retry.call(
                lambda: http_call("GET", url, timeout=120), dest=src)
            if status == 404 and ext in (".ecj", ".vif"):
                continue
            if status >= 400:
                return Response({"error": f"copy {ext}: HTTP {status}"},
                                status=500)
            with open(base + ext, "wb") as f:
                f.write(body)
            copied += len(body)
        # bytes moved over the wire: the master's repair queue charges
        # this against the cluster-wide repair bandwidth budget
        return Response({"bytes": copied})

    def _ec_shard_file(self, req: Request) -> Response:
        vid = int(req.query["volumeId"])
        ext = req.query["ext"]
        base = self._ec_base_name(vid, req.query.get("collection", ""))
        path = base + ext
        if not os.path.exists(path):
            return Response({"error": "not found"}, status=404)
        with open(path, "rb") as f:
            return Response(f.read(), content_type="application/octet-stream")

    def _ec_mount(self, req: Request) -> Response:
        b = req.json()
        self.store.mount_ec_shards(b.get("collection", ""), b["volume_id"],
                                   b["shard_ids"])
        self._push_deltas()
        return Response({})

    def _ec_unmount(self, req: Request) -> Response:
        b = req.json()
        self.store.unmount_ec_shards(b["volume_id"], b["shard_ids"])
        self._push_deltas()
        return Response({})

    def _ec_delete_shards(self, req: Request) -> Response:
        b = req.json()
        vid = b["volume_id"]
        base = self._ec_base_name(vid, b.get("collection", ""))
        for sid in b["shard_ids"]:
            p = base + layout.shard_ext(sid)
            if os.path.exists(p):
                os.remove(p)
        # when all shards gone, remove index files too (reference
        # VolumeEcShardsDelete removes .ecx/.ecj when no shards remain)
        if not any(os.path.exists(base + layout.shard_ext(i))
                   for i in range(layout.TOTAL_SHARDS_COUNT)):
            for ext in (".ecx", ".ecj", ".vif"):
                if os.path.exists(base + ext):
                    os.remove(base + ext)
        return Response({})

    def _ec_to_volume(self, req: Request) -> Response:
        """VolumeEcShardsToVolume: shards -> normal .dat/.idx
        (reference :381-413)."""
        b = req.json()
        vid = b["volume_id"]
        collection = b.get("collection", "")
        base = self._ec_base_name(vid, collection)
        dat_size = ecdec.find_dat_file_size(base, base)
        ecdec.write_dat_file(base, dat_size,
                             pipelined=b.get("pipelined", True))
        ecdec.write_idx_file_from_ec_index(base)
        # unmount EC view, load as normal volume
        self.store.unmount_ec_shards(
            vid, list(range(layout.TOTAL_SHARDS_COUNT)))
        from seaweedfs_tpu.storage.volume import Volume
        loc = next(l for l in self.store.locations
                   if os.path.dirname(base) == l.directory)
        vol = Volume(loc.directory, collection, vid)
        loc.add_volume(vol)
        self.store.new_volumes.append(self.store.volume_info(vol))
        self._push_deltas()
        return Response({"dat_size": dat_size})

    def _ec_blob_delete(self, req: Request) -> Response:
        b = req.json()
        ev = self.store.find_ec_volume(b["volume_id"])
        if ev is None:
            return Response({"error": "ec volume not found"}, status=404)
        if self.store.needle_cache is not None:
            self.store.needle_cache.invalidate(
                b["volume_id"], b["needle_id"])
        try:
            ev.delete_needle(b["needle_id"])
        finally:
            if self.store.needle_cache is not None:
                self.store.needle_cache.invalidate(
                    b["volume_id"], b["needle_id"])
        return Response({})

    def _ec_shard_read(self, req: Request) -> Response:
        vid = int(req.query["volumeId"])
        sid = int(req.query["shardId"])
        offset = int(req.query["offset"])
        size = int(req.query["size"])
        ev = self.store.find_ec_volume(vid)
        if ev is None or sid not in ev.shards:
            return Response({"error": "shard not found"}, status=404)
        return Response(ev.shards[sid].read_at(offset, size),
                        content_type="application/octet-stream")

    def _ec_shard_stat(self, req: Request) -> Response:
        """Shard inventory + size for one EC volume — lets a partial
        rebuilder learn the shard width without streaming a shard."""
        vid = int(req.query["volumeId"])
        base = self._ec_base_name(vid, req.query.get("collection", ""))
        sizes = {}
        for i in range(layout.TOTAL_SHARDS_COUNT):
            p = base + layout.shard_ext(i)
            if os.path.exists(p):
                sizes[i] = os.path.getsize(p)
        if not sizes:
            return Response({"error": "no shards"}, status=404)
        from seaweedfs_tpu.storage.erasure_coding.ec_volume import \
            read_volume_info
        out = {"volume_id": vid, "shards": sorted(sizes),
               "shard_size": max(sizes.values()),
               "code": scheme_to_dict(scheme_from_dict(
                   read_volume_info(base).get("code"))),
               "recover_stats": dict(self.store.ec_recover_stats)}
        last = self._ec_last_strategy.get(vid)
        if last:
            out["last_repair"] = last
        return Response(out)

    # ---- partial-column repair (storage/erasure_coding/partial.py) ----
    def _ec_partial_read(self, req: Request) -> Response:
        """One hop of a partial-column reduction chain: fold the local
        members' GF partial products, XOR in the accumulated column
        recursively requested from the rest of the chain, return ONE
        pre-reduced column upstream. A 409 means the plan is stale for
        this node (shard moved) — the caller falls back."""
        b = req.json()
        vid = int(b["volume_id"])
        offset = int(b["offset"])
        size = int(b["size"])
        n_rows = int(b.get("n_rows", 1))
        chain = b.get("chain") or []
        if not chain or size <= 0 or n_rows <= 0:
            return Response({"error": "bad partial plan"}, status=400)
        ev = self.store.find_ec_volume(vid)
        hop, rest = chain[0], chain[1:]
        rows, cols = [], []
        for sid, coeffs in hop["members"]:
            if len(coeffs) != n_rows:
                return Response({"error": "coeffs/n_rows mismatch"},
                                status=400)
            shard = ev.shards.get(int(sid)) if ev is not None else None
            if shard is None:
                return Response({"error": f"shard {sid} not local"},
                                status=409)
            data = shard.read_at(offset, size)
            if len(data) != size:
                return Response({"error": f"shard {sid} short read"},
                                status=409)
            rows.append(np.frombuffer(data, dtype=np.uint8))
            cols.append(np.asarray(coeffs, dtype=np.uint8))
        acc = np.zeros((n_rows, size), dtype=np.uint8)
        if rows:
            gf_partial_product(np.stack(cols, axis=1), np.stack(rows),
                               out=acc)
        shards_folded = len(rows)
        reasons: list[str] = []
        if rest:
            try:
                arr, dshards, _nbytes, dreasons = self._chain_partial(
                    vid, b.get("collection", ""), offset, size, n_rows,
                    rest)
            except RuntimeError as e:
                return Response({"error": str(e)}, status=502)
            acc ^= arr
            shards_folded += dshards
            reasons.extend(dreasons)
        headers = {ecpart.SHARDS_HEADER: str(shards_folded)}
        if reasons:
            headers[ecpart.FALLBACK_HEADER] = ",".join(reasons)
        self._m_req.inc("ec_partial_read")
        return Response(acc.tobytes(),
                        content_type="application/octet-stream",
                        headers=headers)

    def _chain_partial(self, vid: int, collection: str, offset: int,
                       size: int, n_rows: int, chain: list
                       ) -> tuple[np.ndarray, int, int, list]:
        """Request the accumulated partial column from a reduction
        chain. Breaker-screened; on any failure of the next hop, fall
        back to raw-streaming every remaining member's shard range and
        reducing HERE (ladder rung 1/2 in partial.py). Returns
        (array (n_rows, size), shards_folded, net_bytes_received,
        fallback_reasons); raises RuntimeError when some member shard
        is unobtainable by any means."""
        url = chain[0]["url"]
        expect = len(ecpart.chain_shard_ids(chain))
        if self.peer_health.allow(url):
            t0 = clockctl.monotonic()
            try:
                status, body, hdrs = http_call(
                    "POST", f"http://{url}{ecpart.PARTIAL_READ_PATH}",
                    json_body={"volume_id": vid, "collection": collection,
                               "offset": offset, "size": size,
                               "n_rows": n_rows, "chain": chain},
                    timeout=120)
                self.peer_health.record(url, True, clockctl.monotonic() - t0)
                if status == 200 and len(body) == n_rows * size:
                    arr = np.frombuffer(body, dtype=np.uint8) \
                        .reshape(n_rows, size).copy()
                    shards = int(hdrs.get(ecpart.SHARDS_HEADER, expect))
                    reasons = [r for r in
                               hdrs.get(ecpart.FALLBACK_HEADER,
                                        "").split(",") if r]
                    tracing.annotate("partial_read.net_bytes", len(body))
                    tracing.annotate("partial_read.shards", shards)
                    return arr, shards, len(body), reasons
            except (ConnectionError, OSError):
                self.peer_health.record(url, False)
        arr, shards, nbytes = self._raw_partial_fold(
            vid, offset, size, n_rows, chain)
        tracing.annotate("partial_read.net_bytes", nbytes)
        tracing.annotate("partial_read.fallback", f"chain:{url}")
        return arr, shards, nbytes, [f"chain:{url}"]

    def _raw_partial_fold(self, vid: int, offset: int, size: int,
                          n_rows: int, chain: list
                          ) -> tuple[np.ndarray, int, int]:
        """Full-shard-streaming fallback: fetch each remaining member's
        raw range (local file, planned holder, then any other holder)
        and fold the partial products locally."""
        acc = np.zeros((n_rows, size), dtype=np.uint8)
        shards = 0
        nbytes = 0
        ev = self.store.find_ec_volume(vid)
        for hop in chain:
            for sid, coeffs in hop["members"]:
                sid = int(sid)
                data = None
                local = ev.shards.get(sid) if ev is not None else None
                if local is not None:
                    data = local.read_at(offset, size)
                    if len(data) != size:
                        data = None
                if data is None:
                    data = self._fetch_shard_range(
                        vid, sid, offset, size, prefer=hop["url"])
                    if data is not None:
                        nbytes += len(data)
                if data is None:
                    raise RuntimeError(
                        f"shard {sid}: no reachable holder for "
                        "partial fold")
                gf_partial_product(
                    np.asarray(coeffs, dtype=np.uint8)[:, None],
                    np.frombuffer(data, dtype=np.uint8)[None, :],
                    out=acc)
                shards += 1
        return acc, shards, nbytes

    def _fetch_shard_range(self, vid: int, sid: int, offset: int,
                           size: int, prefer: str = "") -> Optional[bytes]:
        urls = [prefer] if prefer else []
        try:
            locs = self._shard_locations(vid)
        except (ConnectionError, HttpError):
            locs = {}
        rest = [u for u in locs.get(sid, []) if u not in urls]
        urls += self.peer_health.rank(
            rest, pressure=self._shard_pressure(vid))
        for u in urls:
            if not self.peer_health.allow(u) and len(urls) > 1:
                continue
            t0 = clockctl.monotonic()
            try:
                status, body, _ = http_call(
                    "GET",
                    f"http://{u}/admin/ec/shard_read"
                    f"?volumeId={vid}&shardId={sid}"
                    f"&offset={offset}&size={size}", timeout=60)
            except (ConnectionError, OSError):
                self.peer_health.record(u, False)
                continue
            self.peer_health.record(u, True, clockctl.monotonic() - t0)
            if status == 200 and len(body) == size:
                return body
        return None

    def _remote_partial_reader(self, vid: int, coeff_by_sid: dict,
                               offset: int, size: int,
                               n_rows: int) -> Optional[np.ndarray]:
        """Store hook for the scrubber: pull the XOR of remote shards'
        partial products as one pre-reduced column (remote-assisted
        parity recompute on spread deployments)."""
        try:
            locs = self._shard_locations(vid)
        except (ConnectionError, HttpError):
            return None
        chain = ecpart.plan_chain(locs, coeff_by_sid,
                                  health=self.peer_health,
                                  pressure=self._shard_pressure(vid))
        if not chain:
            return None
        try:
            with class_scope(BACKGROUND), \
                    deadline_scope(Deadline.after(60.0)):
                arr, shards, _n, _r = self._chain_partial(
                    vid, "", offset, size, n_rows, chain)
        except RuntimeError:
            return None
        if shards != len(coeff_by_sid):
            return None
        return arr

    def _ensure_ec_aux_files(self, vid: int, collection: str, base: str,
                             sources: dict) -> int:
        """Fetch .ecx (mandatory) and .ecj/.vif (best-effort) from any
        source holder when absent locally. Returns bytes copied."""
        urls: list[str] = []
        for us in sources.values():
            for u in us:
                if u not in urls:
                    urls.append(u)
        urls = self.peer_health.rank(urls,
                                     pressure=self._shard_pressure(vid))
        copied = 0
        for ext in (".ecx", ".ecj", ".vif"):
            if os.path.exists(base + ext):
                continue
            for u in urls:
                try:
                    status, body, _ = http_call(
                        "GET",
                        f"http://{u}/admin/ec/shard_file?volumeId={vid}"
                        f"&ext={ext}&collection={collection}", timeout=60)
                except (ConnectionError, OSError):
                    self.peer_health.record(u, False)
                    continue
                if status >= 400:
                    continue
                with open(base + ext, "wb") as f:
                    f.write(body)
                copied += len(body)
                break
        if not os.path.exists(base + ".ecx"):
            raise RuntimeError("no source holder could supply .ecx")
        return copied

    def _ec_rebuild_partial(self, req: Request) -> Response:
        """Network-frugal rebuild: reconstruct the missing shards from
        pre-reduced partial columns pulled through a reduction chain —
        ~1 shard-width received per lost shard instead of the k full
        shards the copy+rebuild choreography stages. Bit-identical to
        the serial rebuild (XOR folding is associative). The caller
        (master repair queue) falls back to /admin/ec/copy +
        /admin/ec/rebuild on any error here (ladder rung 3)."""
        b = req.json()
        vid = int(b["volume_id"])
        collection = b.get("collection", "")
        missing = sorted(int(s) for s in b.get("missing", []))
        sources = {int(s): [u for u in urls if not self._is_self(u)]
                   for s, urls in (b.get("sources") or {}).items()}
        sources = {s: u for s, u in sources.items() if u}
        batch = int(b.get("batch_size", 0)) or ecenc.DEFAULT_BATCH_SIZE
        if not missing:
            return Response({"error": "nothing to rebuild"}, status=400)
        base = self._ec_base_name(vid, collection)
        local = [i for i in range(layout.TOTAL_SHARDS_COUNT)
                 if os.path.exists(base + layout.shard_ext(i))]
        present = sorted((set(local) | set(sources)) - set(missing))
        received = 0
        # aux files first: the .vif names the volume's code family, and
        # the per-volume coder below plans the source set from it
        try:
            received += self._ensure_ec_aux_files(
                vid, collection, base, sources)
        except RuntimeError as e:
            return Response({"error": str(e)}, status=502)
        coder = self._ec_volume_coder(base)
        k = coder.scheme.data_shards
        plan_capable = hasattr(coder, "plan_rebuild")
        # a plan-capable (LRC) coder can repair a group loss from fewer
        # than k survivors; only the generic path needs the k floor
        if not plan_capable and len(present) < k:
            return Response(
                {"error": f"only {len(present)} shards known, need {k}"},
                status=409)
        if not (plan_capable or hasattr(coder, "rebuild_matrix")):
            from seaweedfs_tpu.ops.rs_cpu import CpuCoder
            coder = CpuCoder(coder.scheme)
        try:
            src_sids, mat = ecenc.plan_rebuild_sources(
                coder, present, missing)
        except (ValueError, np.linalg.LinAlgError) as e:
            return Response(
                {"error": f"unrecoverable from {present}: {e}"},
                status=409)
        src_sids = list(src_sids)
        shard_size = 0
        for s in src_sids:
            if s in local:
                shard_size = os.path.getsize(base + layout.shard_ext(s))
                break
        if not shard_size:
            shard_size = self._remote_shard_stat(vid, collection, sources)
        if not shard_size:
            return Response({"error": "cannot determine shard size"},
                            status=409)
        workers = int(getattr(self.store.coder, "workers", 1) or 1)
        miss_n = len(missing)
        fallbacks: list[str] = []
        # warm the holder-pressure map once (best-effort: a dead master
        # must not fail a rebuild whose sources came with the request) —
        # chain planning below tie-breaks equally-healthy holders by it
        try:
            self._shard_locations(vid)
        except (ConnectionError, HttpError):
            pass
        pressure = self._shard_pressure(vid)
        local_fhs = {s: open(base + layout.shard_ext(s), "rb")
                     for s in src_sids if s in local}
        remote_src = [s for s in src_sids if s not in local_fhs]
        outs = {m: open(base + layout.shard_ext(m) + ".tmp", "wb")
                for m in missing}
        try:
            for off in range(0, shard_size, batch):
                sz = min(batch, shard_size - off)
                acc = np.zeros((miss_n, sz), dtype=np.uint8)
                if local_fhs:
                    rows, cols = [], []
                    for j, s in enumerate(src_sids):
                        fh = local_fhs.get(s)
                        if fh is None:
                            continue
                        fh.seek(off)
                        buf = fh.read(sz)
                        if len(buf) != sz:
                            raise RuntimeError(
                                f"short local read shard {s}")
                        rows.append(np.frombuffer(buf, dtype=np.uint8))
                        cols.append(mat[:, j])
                    gf_partial_product(np.stack(cols, axis=1),
                                       np.stack(rows), out=acc,
                                       workers=workers)
                if remote_src:
                    coeff_by_sid = {
                        s: mat[:, src_sids.index(s)].tolist()
                        for s in remote_src}
                    chain = ecpart.plan_chain(
                        sources, coeff_by_sid, health=self.peer_health,
                        pressure=pressure)
                    if chain is None:
                        raise RuntimeError(
                            "no holder for some source shard")
                    arr, shards, nbytes, reasons = self._chain_partial(
                        vid, collection, off, sz, miss_n, chain)
                    if shards != len(remote_src):
                        raise RuntimeError(
                            f"chain folded {shards} shards, "
                            f"expected {len(remote_src)}")
                    received += nbytes
                    fallbacks.extend(reasons)
                    acc ^= arr
                for r, m in enumerate(missing):
                    outs[m].write(acc[r].tobytes())
        except Exception as e:
            for fh in outs.values():
                fh.close()
            for m in missing:
                p = base + layout.shard_ext(m) + ".tmp"
                if os.path.exists(p):
                    os.remove(p)
            return Response({"error": f"partial rebuild: {e}"},
                            status=502)
        finally:
            for fh in local_fhs.values():
                fh.close()
            for fh in outs.values():
                try:
                    fh.close()
                except OSError:
                    pass
        for m in missing:
            os.replace(base + layout.shard_ext(m) + ".tmp",
                       base + layout.shard_ext(m))
        ecenc.rebuild_ecx_file(base)
        self._m_req.inc("ec_rebuild_partial")
        mb = shard_size * miss_n / (1024.0 * 1024.0)
        mode = "partial+fallback" if fallbacks else "partial"
        strategy = self._record_strategy(vid, coder, src_sids, mode)
        return Response({
            "rebuilt_shard_ids": missing, "shard_size": shard_size,
            "network_bytes": received,
            "repair_network_bytes_per_mb":
                round(received / mb, 1) if mb else 0.0,
            "fallbacks": fallbacks,
            "strategy": strategy,
            "sources": src_sids,
            "code": scheme_to_dict(coder.scheme).get("family", "rs"),
            "mode": mode})

    def _remote_shard_stat(self, vid: int, collection: str,
                           sources: dict) -> int:
        urls: list[str] = []
        for us in sources.values():
            for u in us:
                if u not in urls:
                    urls.append(u)
        for u in self.peer_health.rank(
                urls, pressure=self._shard_pressure(vid)):
            try:
                resp = http_json(
                    "GET",
                    f"http://{u}/admin/ec/shard_stat?volumeId={vid}"
                    f"&collection={collection}", timeout=10)
            except (ConnectionError, HttpError, OSError):
                continue
            ss = int(resp.get("shard_size", 0))
            if ss > 0:
                return ss
        return 0

    # ---- EC client-side helpers ----
    SHARD_LOC_TTL = 5.0  # matches the replica-lookup cache tier

    def _shard_locations(self, vid: int) -> dict:
        """{shard_id: [peer urls]} for an EC volume via the master's
        /dir/lookup_ec, self excluded, behind a short-TTL cache — a
        degraded read touches up to k+ shards and must not pay one
        master round-trip per column. The same lookup carries each
        holder's heartbeat-reported qos_pressure; _shard_pressure()
        serves it from the same cache entry so chain planning can
        tie-break away from loaded holders for free."""
        now = clockctl.monotonic()
        cached = self._shard_loc_cache.get(vid)
        if cached is not None and cached[0] > now:
            return cached[1]
        info = self._master_json("GET", f"/dir/lookup_ec?volumeId={vid}",
                                 deadline=Deadline.after(5.0))
        locs: dict[int, list[str]] = {}
        pressure: dict[str, float] = {}
        for entry in info.get("shards", []):
            urls = []
            for l in entry["locations"]:
                if self._is_self(l["url"]):
                    continue
                urls.append(l["url"])
                pressure[l["url"]] = float(l.get("qos_pressure", 0.0))
            if urls:
                locs[entry["shard_id"]] = urls
        self._shard_loc_cache[vid] = (now + self.SHARD_LOC_TTL, locs,
                                      pressure)
        return locs

    def _shard_pressure(self, vid: int) -> dict:
        """{url: qos_pressure} from the cached lookup (empty when the
        cache is cold — callers treat missing as unloaded)."""
        cached = self._shard_loc_cache.get(vid)
        if cached is not None and len(cached) > 2:
            return cached[2]
        return {}

    def _remote_shard_reader(self, vid: int, shard_id: int, offset: int,
                             size: int) -> Optional[bytes]:
        """Find the shard's holders via the master and fetch the range
        (reference store_ec.go readRemoteEcShardInterval:270).
        Resilient mode fans out HEDGED across holders ranked by breaker
        health — a backup request fires after the primary's observed
        p95 and the first success wins; legacy mode walks the holders
        serially in lookup order (the bench comparator)."""
        try:
            locs = self._shard_locations(vid)
        except (ConnectionError, HttpError):
            return None
        urls = locs.get(shard_id) or []
        if not urls:
            return None

        def fetch(url: str) -> Optional[bytes]:
            status, body, _ = http_call(
                "GET",
                f"http://{url}/admin/ec/shard_read"
                f"?volumeId={vid}&shardId={shard_id}"
                f"&offset={offset}&size={size}", timeout=30)
            if status == 200 and len(body) == size:
                return body
            return None

        if not self.resilient_reads:
            for url in urls:
                try:
                    out = fetch(url)
                except ConnectionError:
                    continue
                if out is not None:
                    return out
            return None
        # cap this direct fetch under the edge budget: a blackholed
        # holder must leave room for the degraded-reconstruction
        # fallback that runs after we give up here
        from seaweedfs_tpu.utils.resilience import current_deadline
        dl = current_deadline()
        sub = dl.sub(max(0.5, 0.4 * dl.remaining())) \
            if dl is not None else None
        return hedged(fetch,
                      self.peer_health.rank(
                          urls, pressure=self._shard_pressure(vid)),
                      health=self.peer_health, deadline=sub)

    def _ec_delete_fanout(self, vid: int, key: int, cookie: int) -> int:
        """Cookie-check locally then fan the tombstone to every shard
        owner (reference store_ec_delete.go:16-110)."""
        n = self.store.read_ec_shard_needle(vid, key, cookie)
        size = len(n.data)
        try:
            info = self._master_json(
                "GET", f"/dir/lookup_ec?volumeId={vid}",
                deadline=Deadline.after(5.0))
        except (ConnectionError, HttpError):
            info = {"shards": []}
        done = set()
        ev = self.store.find_ec_volume(vid)
        if ev is not None:
            if self.store.needle_cache is not None:
                self.store.needle_cache.invalidate(vid, key)
            ev.delete_needle(key)
            if self.store.needle_cache is not None:
                self.store.needle_cache.invalidate(vid, key)
            done.add(self.url)
            done.add(f"{self.http.host}:{self.http.port}")
        for entry in info.get("shards", []):
            for loc in entry["locations"]:
                if loc["url"] in done or self._is_self(loc["url"]):
                    continue
                done.add(loc["url"])
                t0 = clockctl.monotonic()
                try:
                    http_json("POST",
                              f"http://{loc['url']}/admin/ec/blob_delete",
                              {"volume_id": vid, "needle_id": key},
                              deadline=Deadline.after(10.0))
                    self.peer_health.record(loc["url"], True,
                                            clockctl.monotonic() - t0)
                except ConnectionError:
                    self.peer_health.record(loc["url"], False)
                except HttpError:
                    pass
        return size
