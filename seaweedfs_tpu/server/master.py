"""Master server: topology bookkeeping, assignment, lookup, growth.

Functional equivalent of reference weed/server/master_server.go +
master_grpc_server*.go over HTTP/JSON:

  POST /heartbeat        full or delta heartbeat from a volume server
  GET  /dir/assign       pick/grow a writable volume, mint a fid
  GET  /dir/lookup       vid -> locations
  GET  /dir/lookup_ec    vid -> per-shard locations
  GET  /dir/status       topology dump (shell planners' input)
  POST /vol/grow         explicit growth
  POST /vol/vacuum       trigger vacuum check on all nodes
  GET  /cluster/status   leader info
  GET  /cluster/leases   assign-lease table (holder/epoch/range/expiry)
  POST /admin/lock, /admin/unlock   exclusive shell lock

Assign leases: the master grants volume servers epoch-stamped
fid-range leases ({vid, key_lo, key_hi, epoch, expires_at}) riding the
heartbeat reply, Raft-proposed before they are handed out so a grant
survives leader failover and a fresh leader resumes the sequence past
the high-water mark instead of double-granting. Holders mint fids
locally from their range; the master only re-enters the per-PUT path
when no leased holder is reachable.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from seaweedfs_tpu.cluster.sequence import MemorySequencer
from seaweedfs_tpu.cluster.topology import Topology
from seaweedfs_tpu.qos import (BACKGROUND, INTERACTIVE, WRITE, QosGovernor,
                               class_scope, classify, from_headers)
from seaweedfs_tpu.cluster.volume_growth import (NoFreeSpaceError,
                                                 grow_by_type)
from seaweedfs_tpu.storage.file_id import format_needle_id_cookie
from seaweedfs_tpu.utils import headers as weed_headers
from seaweedfs_tpu.utils import clockctl, glog, profiler, tracing
from seaweedfs_tpu.utils.httpd import (HttpServer, Request, Response,
                                       http_json)
from seaweedfs_tpu.utils.resilience import Deadline, PeerHealth
import random

# ---- assign-lease protocol knobs ----
# How long a fid-range lease stays valid. Long relative to the
# heartbeat pulse (2s) so a leader election (sub-second to a few
# seconds) never outlives the leases already in holders' hands.
LEASE_TTL_S = 30.0
# Keys per grant. 4096 fids per (vid, holder) per grant keeps renewal
# traffic to ~1 raft proposal per volume per TTL under realistic
# floods; abandoned remainders just burn cheap sequence ids.
LEASE_RANGE = 4096
# Renew when remaining lifetime falls below this fraction of the TTL
# or remaining range below this fraction of LEASE_RANGE.
LEASE_RENEW_FRACTION = 0.5
LEASE_RANGE_REFILL_FRACTION = 0.25
# Cap raft proposals per heartbeat so one node with many volumes
# can't stall the heartbeat handler; the rest renew next pulse.
LEASE_GRANTS_PER_PULSE = 8


class MasterServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 volume_size_limit_mb: int = 1024,
                 default_replication: str = "000",
                 garbage_threshold: float = 0.3,
                 jwt_signing_key: str = "",
                 whitelist: Optional[list] = None,
                 meta_dir: str = "", grpc_port: Optional[int] = None,
                 repair_rate_mbps: float = 0.0,
                 partial_repair: bool = True,
                 repair_coalesce_window_s: float = 0.0,
                 qos: bool = True,
                 tracing_enabled: bool = True,
                 trace_sample: float = 0.01,
                 profile_hz: float = profiler.DEFAULT_HZ,
                 tier_endpoint: str = "", tier_bucket: str = "tier"):
        self.topo = Topology(volume_size_limit=volume_size_limit_mb * 1024 * 1024)
        self.jwt_signing_key = jwt_signing_key
        from seaweedfs_tpu.utils.metrics import Registry
        from seaweedfs_tpu.utils.security import Guard
        self.metrics = Registry()
        self.guard = Guard(whitelist)
        self._m_assign = self.metrics.counter(
            "master", "assign_total", "assign requests")
        self._m_lookup = self.metrics.counter(
            "master", "lookup_total", "lookup requests")
        self._m_heartbeat = self.metrics.counter(
            "master", "received_heartbeats", "heartbeats received")
        # topology gauges refreshed at scrape time (reference
        # stats/metrics.go MasterVolumeLayout / data-node gauges)
        self._m_nodes = self.metrics.gauge(
            "master", "data_nodes", "registered volume servers")
        self._m_volumes = self.metrics.gauge(
            "master", "volumes", "volumes known to the topology")
        self._m_ec_shards = self.metrics.gauge(
            "master", "ec_shards", "ec shards known to the topology")
        self._m_is_leader = self.metrics.gauge(
            "master", "is_leader", "1 when this master leads")
        self.metrics.on_expose(self._refresh_gauges)
        # breaker/health table for the nodes this master dials
        # (vacuum, repair dispatch, collection delete, leader proxy)
        self.peer_health = PeerHealth(metrics=self.metrics)
        self.sequencer = MemorySequencer()
        self.default_replication = default_replication
        self.garbage_threshold = garbage_threshold
        self.http = HttpServer(host, port)
        self._grow_lock = threading.Lock()
        self._admin_lock_holder: Optional[str] = None
        self._admin_lock_ts = 0.0
        from seaweedfs_tpu.scrub import RepairQueue
        self.repair_queue = RepairQueue(
            self, repair_rate_mbps=repair_rate_mbps,
            partial_repair=partial_repair,
            coalesce_window_s=repair_coalesce_window_s)
        # the master's serving edge (lookups/assigns) gets the same
        # adaptive-concurrency governor as the volume servers' data
        # edges; cluster-control traffic is exempt (see QOS_EXEMPT)
        self.qos = QosGovernor(metrics=self.metrics, enabled=qos)
        self._m_qos_shed = self.metrics.counter(
            "master", "qos_shed_total", "requests shed at the master edge")
        self.http.admission_gate = self._admission_gate
        # distributed-tracing flight recorder; served at /debug/traces
        self.tracer = tracing.Tracer(
            node=f"master@{host}:{port}", enabled=tracing_enabled,
            sample_rate=trace_sample)
        self.http.tracer = self.tracer
        # RED edge histogram (one observation site in HttpServer) +
        # the cluster-wide aggregation/judgement it feeds
        from seaweedfs_tpu.stats.telemetry import ClusterTelemetry
        from seaweedfs_tpu.utils.metrics import RedRecorder
        self.red = RedRecorder(self.metrics, "master")
        self.http.red = self.red
        # wall-stack sampler + per-(class, tenant) resource ledger:
        # the master's own burn joins the cluster rollup it serves
        from seaweedfs_tpu.stats.ledger import ResourceLedger
        self.sampler = profiler.WallSampler(hz=profile_hz)
        self.ledger = ResourceLedger()
        self.http.ledger = self.ledger
        self.telemetry = ClusterTelemetry(
            on_transition=self._on_slo_transition)
        self._m_slo_burn = self.metrics.gauge(
            "master", "slo_burn_rate",
            "SLO error-budget burn rate", ("class", "window"))
        self._m_slo_alert = self.metrics.gauge(
            "master", "slo_alert",
            "1=fast_burn firing, 0.5=slow_burn, 0=ok", ("class",))
        self._register_routes()
        self._stop = threading.Event()
        self._pruner: Optional[threading.Thread] = None
        # ---- HA: raft consensus (reference weed/server/raft_server.go;
        # MaxVolumeId commands replicate like
        # topology/cluster_commands.go, sequence checkpoints ride the
        # snapshot). Single-master mode (no peers) has no raft node and
        # is trivially leader.
        self.peers: list[str] = []
        self.raft = None
        self._seq_ckpt = 0  # highest committed sequence checkpoint
        self._seq_synced_term = -1  # raft term our sequencer is synced to
        # ---- assign-lease table (replicated) ----
        # vid -> {vid, holder, key_lo, key_hi, epoch, expires_at, ...};
        # every entry was a committed raft "lease" command (or arrived
        # in a snapshot), so the table survives leader failover and a
        # fresh leader can honor — and avoid double-granting over —
        # ranges its predecessor handed out.
        self.leases: dict[int, dict] = {}
        self._lease_epoch = 0           # replicated grant counter
        self._lease_lock = threading.Lock()   # table/epoch mutations
        self._grant_lock = threading.Lock()   # serializes grant checks
        # leader-local: holder-reported mint cursor per vid, for the
        # /cluster/leases "remaining range" view (not replicated)
        self._lease_progress: dict[int, int] = {}
        self.lease_counters = {"grant": 0, "renew": 0, "expire": 0}
        self._m_lease = self.metrics.counter(
            "master", "lease_total", "assign-lease operations", ("op",))
        # ---- durable state (reference checkpoints MaxVolumeId + sequence
        # through raft snapshots, topology/cluster_commands.go) ----
        self.meta_dir = meta_dir
        self._load_state()
        self._grpc_port = grpc_port
        self._cluster_nodes: dict = {}
        # (type, url) -> {"metrics_url": ...}; separate from the
        # liveness map above because the gRPC plane also writes that
        # one with bare timestamps
        self._cluster_node_meta: dict = {}
        # epoch-stamped filer shard ring (filer/shard_ring.py): the
        # epoch bumps exactly when the live filer set changes, so
        # clients can detect drift from one integer compare
        self._filer_ring = None
        self._filer_ring_lock = threading.Lock()
        # live rebalancing (filer/rebalance.py): announce piggybacks
        # feed the planner; a plan dispatches move orders to the
        # source filer, and the ring only flips at commit time — after
        # the mover has the rows at the destination
        from seaweedfs_tpu.filer.rebalance import RebalancePlanner
        self.rebalance = RebalancePlanner()
        self.rebalance_dispatched: list[dict] = []
        # tiering autopilot (storage/tiering.py): heartbeat-piggybacked
        # read counters feed the planner; the mover executes rung
        # transitions as BACKGROUND token-bucketed jobs. Without a
        # tier_endpoint the cloud rung stays disabled (hot<->ec only).
        from seaweedfs_tpu.storage.tiering import TieringPlanner, TierMover
        self.tiering = TieringPlanner(cloud_enabled=bool(tier_endpoint))
        self.tier_mover = TierMover(self.tiering, endpoint=tier_endpoint,
                                    bucket=tier_bucket)
        self.tiering_dispatched: list[dict] = []
        self._grpc_server = None
        self.grpc_port: Optional[int] = None

    # ---- lifecycle ----
    def start(self) -> None:
        self.http.start()
        self.sampler.start()
        self.tracer.node = f"master@{self.http.host}:{self.http.port}"
        if self._grpc_port is not None:
            from seaweedfs_tpu.server.master_grpc import start_master_grpc
            self._grpc_server, self.grpc_port = start_master_grpc(
                self, self.http.host, self._grpc_port)
        self._pruner = threading.Thread(target=self._prune_loop, daemon=True,
                                        name="master-prune")
        self._pruner.start()
        glog.info("master server up at %s (peers=%s)", self.url,
                  ",".join(self.peers) if self.peers else "-")

    def stop(self) -> None:
        self._stop.set()
        self.sampler.stop()
        self.repair_queue.stop()
        self.metrics.stop_push()
        self._save_state()
        if self.raft is not None:
            self.raft.stop()
        if self._grpc_server is not None:
            self._grpc_server.stop(0)
        self.http.stop()

    @property
    def url(self) -> str:
        return f"{self.http.host}:{self.http.port}"

    def _prune_loop(self):
        ticks = 0
        while not self._stop.wait(self.topo.pulse_seconds):
            ticks += 1
            self.topo.prune_dead_nodes()
            self._expire_leases()
            self._save_state()
            self._feed_slo()
            if self.is_leader():
                # profiler scope: repair waves sample as background
                # work under route "repair", not anonymous thread time
                with profiler.scope(cls=BACKGROUND, route="repair"):
                    self.repair_queue.tick()
            if ticks % 12 == 0 and self.is_leader():
                self._auto_vacuum()

    def _auto_vacuum(self) -> None:
        """Compact garbage-heavy volumes cluster-wide (reference master
        vacuum loop, topology_vacuum.go). Vacuum is background traffic:
        a loaded node may shed it and the next pass retries."""
        with class_scope(BACKGROUND):
            self._auto_vacuum_inner()

    def _auto_vacuum_inner(self) -> None:
        for node in self.topo.all_nodes():
            for vid in list(node.volumes):
                try:
                    check = http_json(
                        "POST", f"http://{node.url}/admin/vacuum",
                        {"volume_id": vid, "check_only": True},
                        deadline=Deadline.after(10.0))
                    if check.get("garbage_ratio", 0) > self.garbage_threshold:
                        http_json("POST", f"http://{node.url}/admin/vacuum",
                                  {"volume_id": vid},
                                  timeout=600,
                                  deadline=Deadline.after(600.0))
                    self.peer_health.record(node.url, True)
                except ConnectionError as e:
                    self.peer_health.record(node.url, False)
                    glog.warning("auto-vacuum of %d on %s failed: %s",
                                 vid, node.url, e)
                    continue
                except Exception as e:
                    glog.warning("auto-vacuum of %d on %s failed: %s",
                                 vid, node.url, e)
                    continue

    def _state_path(self) -> str:
        import os
        return os.path.join(self.meta_dir, "master_state.json")

    def _load_state(self) -> None:
        if not self.meta_dir:
            return
        import json, os
        os.makedirs(self.meta_dir, exist_ok=True)
        try:
            with open(self._state_path()) as f:
                st = json.load(f)
            self.topo.max_volume_id = st.get("max_volume_id", 0)
            self.sequencer.set_max(st.get("sequence", 0))
            for vid_s, l in (st.get("leases") or {}).items():
                self.leases[int(vid_s)] = l
            self._lease_epoch = st.get("lease_epoch", 0)
        except (OSError, ValueError):
            pass

    def _save_state(self) -> None:
        if not self.meta_dir:
            return
        import json, os
        tmp = self._state_path() + ".tmp"
        try:
            with self._lease_lock:
                leases = {str(vid): l for vid, l in self.leases.items()}
                epoch = self._lease_epoch
            with open(tmp, "w") as f:
                json.dump({"max_volume_id": self.topo.max_volume_id,
                           "sequence": self.sequencer.peek(),
                           "leases": leases, "lease_epoch": epoch}, f)
            os.replace(tmp, self._state_path())
        except OSError:
            pass

    # ---- HA ----
    def set_peers(self, peers: list[str]) -> None:
        """Configure the master group (urls incl. self) and start raft."""
        from seaweedfs_tpu.cluster.raft import RaftNode
        self.peers = sorted(set(peers) | {self.url})
        if self.raft is not None:
            self.raft.stop()
        import os
        state_path = (os.path.join(self.meta_dir, "raft_state.json")
                      if self.meta_dir else "")
        self.raft = RaftNode(
            self.url, self.peers,
            apply_fn=self._apply_raft_command,
            snapshot_fn=self._raft_snapshot_state,
            restore_fn=self._restore_raft_snapshot,
            state_path=state_path)
        self.raft.start()

    def _raft_snapshot_state(self) -> dict:
        with self._lease_lock:
            leases = {str(vid): dict(l) for vid, l in self.leases.items()}
            epoch = self._lease_epoch
        return {"max_volume_id": self.topo.max_volume_id,
                # followers never mint ids, so their live counter is
                # stale — the committed checkpoint is the durable floor
                "sequence": max(self._seq_ckpt, self.sequencer.peek()),
                "leases": leases,
                "lease_epoch": epoch}

    def _apply_raft_command(self, cmd: dict) -> None:
        """State machine: committed log entries (every master applies)."""
        if cmd.get("type") == "max_volume_id":
            with self.topo.lock:
                self.topo.max_volume_id = max(self.topo.max_volume_id,
                                              cmd["value"])
        elif cmd.get("type") == "sequence":
            # record only; the live counter fast-forwards to the
            # checkpoint once per leadership change (assign_fid) so a
            # continuing leader doesn't burn a batch per checkpoint
            self._seq_ckpt = max(self._seq_ckpt, cmd["value"])
        elif cmd.get("type") == "lease":
            self._apply_lease(cmd["lease"])
        elif cmd.get("type") == "raft_config":
            # membership change committed through the log, so every
            # master (and a restarted one replaying it) converges on
            # the same peer set (reference cluster.raft.add/remove)
            if self.raft is not None:
                if cmd["op"] == "add":
                    self.raft.add_peer(cmd["peer"])
                elif cmd["op"] == "remove":
                    self.raft.remove_peer(cmd["peer"])

    def _restore_raft_snapshot(self, state: dict) -> None:
        with self.topo.lock:
            self.topo.max_volume_id = max(self.topo.max_volume_id,
                                          state.get("max_volume_id", 0))
        self._seq_ckpt = max(self._seq_ckpt, state.get("sequence", 0))
        with self._lease_lock:
            for vid_s, l in (state.get("leases") or {}).items():
                vid = int(vid_s)
                cur = self.leases.get(vid)
                if cur is None or l["epoch"] >= cur["epoch"]:
                    self.leases[vid] = dict(l)
            self._lease_epoch = max(self._lease_epoch,
                                    state.get("lease_epoch", 0))

    def _apply_lease(self, lease: dict) -> None:
        """State-machine apply of a committed lease grant: install the
        entry (newest epoch wins per vid) and floor the sequence
        checkpoint past its range, so a failed-over leader resumes
        minting beyond every key any predecessor leased out."""
        vid = int(lease["vid"])
        with self._lease_lock:
            cur = self.leases.get(vid)
            if cur is None or lease["epoch"] >= cur["epoch"]:
                self.leases[vid] = dict(lease)
            self._lease_epoch = max(self._lease_epoch, lease["epoch"])
            self._seq_ckpt = max(self._seq_ckpt, lease["key_hi"] + 1)

    def _raft_propose(self, cmd: dict) -> bool:
        """Replicate a command; returns True once committed. Callers
        minting ids/vids MUST fail when this fails — handing out an
        uncommitted id invites reuse after failover."""
        if self.raft is None:
            return True
        try:
            return self.raft.propose(cmd, timeout=5.0)
        except Exception:
            return False

    def _handle_raft(self, method: str):
        def handler(req: Request) -> Response:
            if self.raft is None:
                return Response({"error": "raft not configured"},
                                status=503)
            return Response(getattr(self.raft, method)(req.json()))
        return handler

    @property
    def leader(self) -> str:
        if self.raft is not None:
            return self.raft.leader_id or self.url
        return self.url

    def is_leader(self) -> bool:
        if self.raft is not None:
            from seaweedfs_tpu.cluster.raft import LEADER
            return self.raft.state == LEADER
        return True

    def _not_leader(self) -> Response:
        return Response({"error": "not leader", "leader": self.leader},
                        status=409)

    def _handle_raft_ps(self, req: Request) -> Response:
        """Raft membership view (reference shell cluster.raft.ps)."""
        if self.raft is None:
            return Response({"id": self.url, "peers": [],
                             "leader": self.url, "term": 0,
                             "state": "single"})
        return Response(self.raft.membership())

    def _handle_raft_change(self, op: str):
        """cluster.raft.add/remove: commit a membership change through
        the log (leader-only; followers 409 to the leader)."""
        def handler(req: Request) -> Response:
            if self.raft is None:
                return Response({"error": "raft not configured"},
                                status=503)
            if not self.is_leader():
                return self._not_leader()
            peer = (req.json() or {}).get("peer", "")
            if not peer:
                return Response({"error": "missing peer"}, status=400)
            if op == "remove" and peer == self.raft.id:
                return Response(
                    {"error": "cannot remove the leader; transfer "
                     "leadership first (stop this master)"}, status=400)
            ok = self._raft_propose(
                {"type": "raft_config", "op": op, "peer": peer})
            if not ok:
                return Response({"error": "config change not committed"},
                                status=503)
            return Response(self.raft.membership())
        return handler

    # ---- routes ----
    def _register_routes(self) -> None:
        r = self.http.add
        r("POST", "/heartbeat", self._handle_heartbeat)
        r("GET", "/dir/assign", self._handle_assign)
        r("POST", "/dir/assign", self._handle_assign)
        r("GET", "/dir/lookup", self._handle_lookup)
        r("GET", "/dir/lookup_ec", self._handle_lookup_ec)
        r("GET", "/dir/status", self._handle_dir_status)
        r("POST", "/vol/grow", self._handle_grow)
        r("GET", "/cluster/status", self._handle_cluster_status)
        r("GET", "/cluster/leases", self._handle_cluster_leases)
        r("GET", "/cluster/health", self._handle_cluster_health)
        r("GET", "/cluster/qos", self._handle_cluster_qos)
        r("GET", "/cluster/telemetry", self._handle_cluster_telemetry)
        r("GET", "/cluster/raft/ps", self._handle_raft_ps)
        r("POST", "/cluster/raft/add", self._handle_raft_change("add"))
        r("POST", "/cluster/raft/remove",
          self._handle_raft_change("remove"))
        r("POST", "/admin/lock", self._handle_lock)
        r("POST", "/admin/unlock", self._handle_unlock)
        r("GET", "/metrics", self._handle_metrics)
        r("GET", "/col/list", self._handle_col_list)
        r("POST", "/cluster/register", self._handle_cluster_register)
        r("POST", "/dir/leave", self._handle_dir_leave)
        r("GET", "/cluster/nodes", self._handle_cluster_nodes)
        r("GET", "/cluster/filers", self._handle_cluster_filers)
        r("GET", "/cluster/rebalance", self._handle_rebalance_status)
        r("POST", "/cluster/rebalance/kick", self._handle_rebalance_kick)
        r("POST", "/cluster/rebalance/commit",
          self._handle_rebalance_commit)
        r("GET", "/cluster/tiering", self._handle_tiering_status)
        r("POST", "/cluster/tiering/kick", self._handle_tiering_kick)
        r("POST", "/col/delete", self._handle_col_delete)
        r("GET", "/ui", self._handle_ui)
        r("GET", "/", self._handle_ui)
        r("POST", "/scrub/report", self._handle_scrub_report)
        r("GET", "/ec/repair/status", self._handle_repair_status)
        r("POST", "/ec/repair/kick", self._handle_repair_kick)
        r("GET", "/admin/qos", self._admin_qos)
        r("POST", "/admin/qos", self._admin_qos_configure)
        # folded-stack window from the wall sampler (prof_collect)
        r("GET", "/admin/profile", profiler.make_profile_handler(
            self.sampler, lambda: self.url, "master"))
        r("POST", "/raft/vote", self._handle_raft("on_request_vote"))
        r("POST", "/raft/append", self._handle_raft("on_append_entries"))
        r("POST", "/raft/snapshot", self._handle_raft("on_install_snapshot"))
        from seaweedfs_tpu.utils.debug import install_debug_routes
        install_debug_routes(self.http)

    # Shedding cluster-control traffic would destabilize the cluster
    # the governor is trying to protect: heartbeats/raft keep liveness,
    # scrub reports and repair control keep integrity moving, and the
    # observability/registration endpoints must answer while degraded.
    # The governed edge is the SERVING one: lookups, assigns, growth,
    # directory status.
    QOS_EXEMPT = ("/heartbeat", "/raft/", "/cluster/", "/metrics", "/ui",
                  "/debug", "/scrub/report", "/ec/repair/", "/admin/lock",
                  "/admin/unlock", "/admin/qos", "/admin/profile",
                  "/dir/leave", "/col/")

    def _admission_gate(self, method: str, path: str, headers, client):
        """HttpServer admission hook for the master's serving edge —
        same contract as the volume server's: classify (propagated
        header wins), ask the governor, shed with 503 + Retry-After."""
        if not self.qos.enabled or path == "/":
            return None
        for p in self.QOS_EXEMPT:
            if path.startswith(p):
                return None
        cls = from_headers(headers) or self._classify_master(method, path)
        grant = self.qos.admit(cls)
        if not grant.ok:
            self._m_qos_shed.inc()
            return Response(
                {"error": "overloaded", "class": cls}, status=503,
                headers={"Retry-After": f"{grant.retry_after:.2f}"})
        return grant.release

    @staticmethod
    def _classify_master(method: str, path: str) -> str:
        # assigns and growth consume topology capacity like writes;
        # lookups sit on every read path and stay interactive
        if path.startswith(("/dir/assign", "/vol/")):
            return WRITE
        if path.startswith("/dir/"):
            return INTERACTIVE
        return classify(method, path)

    def _admin_qos(self, req: Request) -> Response:
        return Response({"url": self.url, **self.qos.snapshot()})

    def _admin_qos_configure(self, req: Request) -> Response:
        if not self.is_leader():
            return self._not_leader()
        return Response({"url": self.url,
                         **self.qos.configure(**(req.json() or {}))})

    def _refresh_gauges(self) -> None:
        # runs before every exposition (scrape AND push-gateway loop)
        with self.topo.lock:
            nodes = self.topo.all_nodes()
            self._m_nodes.set(value=len(nodes))
            self._m_volumes.set(
                value=sum(len(n.volumes) for n in nodes))
            self._m_ec_shards.set(
                value=sum(n.ec_shard_count() for n in nodes))
        self._m_is_leader.set(value=1.0 if self.is_leader() else 0.0)

    def _handle_metrics(self, req: Request) -> Response:
        return Response(self.metrics.expose_text(),
                        content_type="text/plain; version=0.0.4")

    # ---- integrity & repair (scrub reports feed the repair queue) ----
    def _handle_scrub_report(self, req: Request) -> Response:
        """A volume server found corruption. Leader-only: the queue
        lives with the leader; followers redirect like /heartbeat."""
        if not self.is_leader():
            return self._not_leader()
        return Response(self.repair_queue.report(req.json() or {}))

    def _handle_repair_status(self, req: Request) -> Response:
        return Response(self.repair_queue.status())

    def _handle_repair_kick(self, req: Request) -> Response:
        if not self.is_leader():
            return self._not_leader()
        return Response(self.repair_queue.kick())

    def _handle_dir_leave(self, req: Request) -> Response:
        """A volume server announcing a graceful exit: drop its volumes
        from the topology immediately instead of waiting out the
        liveness window (reference master_grpc_server.go UnRegister)."""
        url = req.json().get("url", "")
        for node in self.topo.all_nodes():
            if node.url == url or node.id == url:
                self.topo.unregister_data_node(node)
                return Response({"unregistered": url})
        return Response({"error": f"unknown volume server {url}"},
                        status=404)

    def _handle_cluster_register(self, req: Request) -> Response:
        """Filer/broker membership announcements (reference
        weed/cluster/cluster.go + master ListClusterNodes). A node
        that announces a metrics_url makes its telemetry/hotkeys
        endpoints pullable (filer/S3 serve those on the private
        metrics listener, which the topology doesn't know)."""
        b = req.json()
        ntype, url = b.get("type", "filer"), b["url"]
        self._cluster_nodes[(ntype, url)] = clockctl.now()
        if b.get("metrics_url"):
            self._cluster_node_meta[(ntype, url)] = {
                "metrics_url": b["metrics_url"]}
        if ntype == "filer":
            # bump the ring epoch NOW rather than lazily at read time,
            # so a client pulling right after a membership change can't
            # observe new members under the old epoch
            self._current_filer_ring()
            if b.get("shard_load"):
                self.rebalance.observe(url, b["shard_load"])
                self._maybe_rebalance()
        return Response({})

    # ---- live shard rebalancing (filer/rebalance.py) ----
    def _maybe_rebalance(self, force: bool = False) -> Optional[dict]:
        """Ask the planner for a plan against the current ring; when
        one emits, dispatch move orders to each source filer in a
        short-lived thread (the mover runs there; announce handling
        must not block on a migration).  Leader-only, like repair."""
        if not self.is_leader():
            return None
        with self._filer_ring_lock:
            ring = self._filer_ring
        plan = self.rebalance.plan(ring, force=force)
        if plan is None:
            return None
        glog.info("rebalance plan: hot=%s (%.1fx mean) -> %s: %s",
                  plan["hot"], plan["imbalance"], plan["cold"],
                  [m["dir"] for m in plan["moves"]])
        threading.Thread(target=self._dispatch_moves,
                         args=(plan["moves"],),
                         name="rebalance-dispatch", daemon=True).start()
        return plan

    def _dispatch_moves(self, moves: list[dict]) -> None:
        from seaweedfs_tpu.utils.httpd import http_json
        for mv in moves:
            try:
                out = http_json(
                    "POST",
                    f"http://{mv['from']}/__api/shard/migrate",
                    {"dir": mv["dir"], "to": mv["to"]}, timeout=10)
                self.rebalance_dispatched.append(
                    {**mv, "accepted": bool(out.get("started"))})
                if not out.get("started"):
                    # mover busy: let the next planner round retry
                    self.rebalance.note_failed(mv["dir"])
            except Exception as e:
                glog.warning("rebalance dispatch %s -> %s failed: %s",
                             mv["dir"], mv["to"], e)
                self.rebalance.note_failed(mv["dir"])

    def _handle_rebalance_status(self, req: Request) -> Response:
        with self._filer_ring_lock:
            ring = self._filer_ring
        return Response({
            "planner": self.rebalance.status(),
            "dispatched": self.rebalance_dispatched[-16:],
            "overrides": dict(ring.overrides) if ring else {},
            "ring_epoch": ring.epoch if ring else 0,
        })

    def _handle_rebalance_kick(self, req: Request) -> Response:
        if not self.is_leader():
            return self._not_leader()
        plan = self._maybe_rebalance(force=True)
        return Response({"plan": plan})

    def _handle_rebalance_commit(self, req: Request) -> Response:
        """The mover finished copying: flip ownership.  Layer the
        {dir: dest} override over the ring under the ring lock — a
        forward-only epoch bump — and return the new ring so the
        caller can adopt it without a second round-trip."""
        if not self.is_leader():
            return self._not_leader()
        b = req.json() or {}
        directory, dest = b.get("dir", ""), b.get("to", "")
        if not directory or not dest:
            return Response({"error": "dir and to required"}, status=400)
        with self._filer_ring_lock:
            ring = self._filer_ring
            if ring is None or dest not in ring:
                return Response(
                    {"error": f"{dest} not a ring member"}, status=409)
            self._filer_ring = ring.with_overrides({directory: dest})
            out = self._filer_ring.to_dict()
        self.rebalance.note_committed(directory)
        glog.info("rebalance commit: %s -> %s (ring epoch %d)",
                  directory, dest, out["epoch"])
        return Response(out)

    # ---- tiering autopilot (storage/tiering.py) ----
    def _maybe_tier(self, force: bool = False) -> Optional[dict]:
        """Leader-gated: ask the planner for rung transitions and hand
        them to the mover. One plan in flight at a time — the mover
        refuses a start while busy, and un-dispatched moves just wait
        for the next heartbeat round."""
        if not self.is_leader() or self.tier_mover.busy:
            return None
        plan = self.tiering.plan()
        if plan is None:
            return None
        glog.info("tiering plan: %s",
                  [(m["vid"], m["from"], m["to"]) for m in plan["moves"]])
        self.tiering_dispatched.extend(plan["moves"])
        if not self.tier_mover.start(plan):
            for mv in plan["moves"]:
                self.tiering.note_failed(mv["vid"])
            return None
        return plan

    def _handle_tiering_status(self, req: Request) -> Response:
        return Response({
            "planner": self.tiering.status(),
            "mover": self.tier_mover.status(),
            "dispatched": self.tiering_dispatched[-16:],
        })

    def _handle_tiering_kick(self, req: Request) -> Response:
        if not self.is_leader():
            return self._not_leader()
        plan = self._maybe_tier(force=True)
        return Response({"plan": plan})

    def _handle_cluster_nodes(self, req: Request) -> Response:
        ntype = req.query.get("type", "")
        now = clockctl.now()
        nodes = [{"type": t, "url": u}
                 for (t, u), seen in self._cluster_nodes.items()
                 if now - seen < 60 and (not ntype or t == ntype)]
        return Response({"cluster_nodes": nodes})

    def _live_filers(self) -> list[str]:
        now = clockctl.now()
        return sorted(u for (t, u), seen in self._cluster_nodes.items()
                      if t == "filer" and now - seen < 60)

    def _current_filer_ring(self):
        from seaweedfs_tpu.filer.shard_ring import ring_if_changed
        with self._filer_ring_lock:
            new = ring_if_changed(self._filer_ring, self._live_filers())
            if new is not None:
                self._filer_ring = new
            return self._filer_ring

    def _handle_cluster_filers(self, req: Request) -> Response:
        """The filer shard ring: {"epoch": N, "filers": [...]}.
        wdclient pulls this once and re-pulls on X-Weed-Shard epoch
        mismatch; filer servers pull it to learn their own ring."""
        return Response(self._current_filer_ring().to_dict())

    def _handle_col_list(self, req: Request) -> Response:
        # only collections that still HOLD volumes: stale delta
        # processing can re-create an empty layout key after a
        # collection delete (get_layout is get-or-create)
        cols = sorted({c for (c, _, _, _), lo in self.topo.layouts.items()
                       if c and lo.locations})
        return Response({"collections": [{"name": c} for c in cols]})

    def _handle_col_delete(self, req: Request) -> Response:
        collection = req.query.get("collection", "")
        if not collection:
            return Response({"error": "collection required"}, status=400)
        with self.topo.lock:
            doomed = []
            for node in self.topo.all_nodes():
                for vid, v in list(node.volumes.items()):
                    if v.get("collection", "") == collection:
                        doomed.append((node, vid, v))
        # the HTTP deletes run OUTSIDE the topology lock: the volume
        # server's delete handler pushes a delta heartbeat back at this
        # master, which needs the same lock (holding it here deadlocks
        # until the pusher's timeout)
        deleted = []
        for node, vid, v in doomed:
            try:
                http_json("POST",
                          f"http://{node.url}/admin/delete_volume",
                          {"volume_id": vid},
                          deadline=Deadline.after(30.0))
            except Exception as e:
                glog.warning("collection delete: volume %d on %s: %s",
                             vid, node.url, e)
            deleted.append(vid)
        with self.topo.lock:
            for node, vid, v in doomed:
                if node.volumes.pop(vid, None) is not None:
                    self.topo._unregister_volume(v, node)
            for key in [k for k in self.topo.layouts
                        if k[0] == collection]:
                del self.topo.layouts[key]
        return Response({"deleted_volume_ids": sorted(set(deleted))})

    def _handle_ui(self, req: Request) -> Response:
        rows = []
        for node in self.topo.all_nodes():
            rows.append(
                f"<tr><td>{node.id}</td><td>{len(node.volumes)}</td>"
                f"<td>{node.ec_shard_count()}</td>"
                f"<td>{node.max_volume_count}</td></tr>")
        html = (
            "<html><head><title>seaweedfs-tpu master</title></head><body>"
            f"<h1>Master {self.url}</h1>"
            f"<p>leader: {self.leader} | max volume id: "
            f"{self.topo.max_volume_id}</p>"
            "<table border=1><tr><th>node</th><th>volumes</th>"
            "<th>ec shards</th><th>capacity</th></tr>"
            + "".join(rows) + "</table></body></html>")
        return Response(html, content_type="text/html")

    def _handle_heartbeat(self, req: Request) -> Response:
        if not self.is_leader():
            return self._not_leader()
        hb = req.json()
        self._m_heartbeat.inc()
        if hb.get("is_delta"):
            node = self.topo.find_node(f"{hb['ip']}:{hb['port']}")
            if node is None:
                return Response({"error": "unknown node, send full"},
                                status=409)
            self.topo.incremental_sync(node, hb)
        else:
            node = self.topo.sync_data_node_registration(hb)
        # tiering telemetry piggyback: per-volume read counters + rung
        # state feed the planner; a plan (if any) dispatches off-thread
        tiering = (hb.get("telemetry") or {}).get("tiering")
        if tiering and node is not None:
            self.tiering.observe(f"{hb['ip']}:{hb['port']}", tiering)
            self._maybe_tier()
        if node is not None and node.draining:
            # graceful drain announced: exempt the node's volumes from
            # the degraded repair scan so a rolling restart never looks
            # like a failure (refreshed on every draining heartbeat)
            vids = set(node.volumes) | set(node.ec_shards)
            if vids:
                self.repair_queue.note_drain(vids)
        # mirror reference reply: volume size limit + leader
        reply = {
            "volume_size_limit": self.topo.volume_size_limit,
            "leader": self.url,
            "metrics_address": "",
            "jwt_signing_key": self.jwt_signing_key,
        }
        # assign-lease piggyback: grants/renewals owed to this holder
        # ride the reply (a draining node gets none — its leases lapse
        # and writes fall back to healthy holders or the master)
        if node is not None and not node.draining:
            grants = self._lease_grants_for(node, hb.get("lease_req"))
            if grants:
                reply["leases"] = grants
        return Response(reply)

    def _sync_sequence(self, timeout: float = 2.0) -> Optional[dict]:
        """Fast-forward the live sequencer past the committed
        checkpoint once per leadership term (a fresh leader must never
        re-mint ids its predecessor handed out or leased away).
        Returns an assign-shaped error dict when raft leadership isn't
        ready, else None. timeout<=0 makes the check non-blocking for
        callers that must not stall (heartbeat grant path)."""
        if self.raft is None:
            return None
        if not self.raft.is_ready():
            # a fresh leader must commit its no-op barrier first so
            # inherited checkpoints are applied before minting ids
            if timeout <= 0 or not self.raft.wait_ready(timeout=timeout):
                return {"error": "raft leader not ready",
                        "leader": self.leader}
        term = self.raft.current_term
        if self._seq_synced_term != term:
            # once per leadership change: jump past every id any
            # previous leader may have handed out
            self.sequencer.set_max(self._seq_ckpt)
            self._seq_synced_term = term
        return None

    # ---- assign leases (grant/renew ride the heartbeat reply) ----
    def _commit_lease(self, lease: dict) -> bool:
        """Replicate a grant before handing it out; a lease the log
        didn't commit must never reach a holder (it would vanish on
        failover and the new leader could re-grant the same range)."""
        if not self._raft_propose({"type": "lease", "lease": lease}):
            return False
        if self.raft is None:
            # single-master mode: no log to apply from, install directly
            self._apply_lease(lease)
        return True

    def _lease_grants_for(self, node, lease_req) -> list:
        """Grants/renewals owed to one heartbeating holder. lease_req
        is the holder's per-vid lease view ({vid: {"next_key": n,
        "epoch": e}}, {} when it holds none) — None means the node
        doesn't speak leases and gets nothing."""
        if lease_req is None or not isinstance(lease_req, dict):
            return []
        if self._sync_sequence(timeout=0.0) is not None:
            return []  # mid-election: grant on a later pulse
        out = []
        now = clockctl.now()
        with self._grant_lock:
            for vid_s, want in lease_req.items():
                if len(out) >= LEASE_GRANTS_PER_PULSE:
                    break
                vid = int(vid_s)
                want = want if isinstance(want, dict) else {}
                vinfo = node.volumes.get(vid)
                if vinfo is None or vinfo.get("read_only"):
                    continue
                if vinfo.get("ttl"):
                    continue  # TTL volumes keep master-routed assigns
                if vinfo.get("size", 0) >= self.topo.volume_size_limit:
                    continue
                cur = self.leases.get(vid)
                if cur is not None and cur["expires_at"] > now \
                        and cur["holder"] != node.url:
                    continue  # another holder's live lease on this vid
                renewing = (cur is not None and cur["holder"] == node.url
                            and cur["expires_at"] > now)
                if renewing:
                    next_key = int(want.get("next_key", cur["key_lo"]))
                    self._lease_progress[vid] = next_key
                    left = cur["key_hi"] - next_key + 1
                    if (cur["expires_at"] - now
                            > LEASE_TTL_S * LEASE_RENEW_FRACTION
                            and left > LEASE_RANGE
                            * LEASE_RANGE_REFILL_FRACTION):
                        continue  # healthy lease: nothing owed
                key_lo = self.sequencer.next_file_id(LEASE_RANGE)
                with self.topo.lock:
                    replicas = [
                        {"url": n.url, "publicUrl": n.public_url}
                        for n in self.topo.lookup(
                            vinfo.get("collection", ""), vid)
                        if n.url != node.url]
                from seaweedfs_tpu.storage.super_block import \
                    ReplicaPlacement
                lease = {"vid": vid, "holder": node.url,
                         "holder_public": node.public_url,
                         "key_lo": key_lo,
                         "key_hi": key_lo + LEASE_RANGE - 1,
                         "epoch": self._lease_epoch + 1,
                         "expires_at": now + LEASE_TTL_S,
                         "collection": vinfo.get("collection", ""),
                         "replication": str(ReplicaPlacement.from_byte(
                             vinfo.get("replica_placement", 0))),
                         "replicas": replicas}
                if not self._commit_lease(lease):
                    break  # raft can't commit: no grants this pulse
                self._lease_progress[vid] = key_lo
                op = "renew" if renewing else "grant"
                self.lease_counters[op] += 1
                self._m_lease.inc(op)
                out.append(lease)
        return out

    def _expire_leases(self) -> None:
        """Drop lapsed entries (pulse cadence). Expiry is the only
        revocation: the master never claws a live range back, it just
        stops renewing, and the holder's own clockctl check refuses to
        mint past expires_at."""
        now = clockctl.now()
        with self._lease_lock:
            dead = [vid for vid, l in self.leases.items()
                    if l["expires_at"] <= now]
            for vid in dead:
                del self.leases[vid]
        for vid in dead:
            self._lease_progress.pop(vid, None)
            self.lease_counters["expire"] += 1
            self._m_lease.inc("expire")

    def _handle_cluster_leases(self, req: Request) -> Response:
        """The assign-lease table: per-vid holder, epoch, remaining
        range (from the holder's last-reported mint cursor) and expiry.
        Served from the replicated table, so followers answer too —
        clients refresh their lease directory from here even while the
        leader is dark."""
        now = clockctl.now()
        with self._lease_lock:
            leases = [dict(l) for _, l in sorted(self.leases.items())]
        for l in leases:
            nxt = self._lease_progress.get(l["vid"], l["key_lo"])
            l["remaining_keys"] = max(0, l["key_hi"] - nxt + 1)
            l["remaining_s"] = round(l["expires_at"] - now, 3)
        return Response({
            "master": self.url,
            "leader": self.leader,
            "is_leader": self.is_leader(),
            "lease_ttl_s": LEASE_TTL_S,
            "default_replication": self.default_replication,
            "counters": dict(self.lease_counters),
            "leases": leases,
        })

    def assign_fid(self, count: int = 1, collection: str = "",
                   replication: str = "", ttl: str = "",
                   data_center: str = "", disk_type: str = "") -> dict:
        """Core assignment: pick/grow a writable volume, mint a fid.
        Returns the reply dict or {"error": ...} (used by both the HTTP
        and gRPC planes)."""
        err = self._sync_sequence()
        if err is not None:
            return err
        replication = replication or self.default_replication
        layout = self.topo.get_layout(collection, replication, ttl,
                                      disk_type)
        with self._grow_lock:
            # grow when there is nothing writable, and ALSO when every
            # writable volume touches a draining node: a rolling
            # restart must not funnel new writes onto the server that
            # is about to close its listener
            if layout.clean_volume_count() == 0:
                try:
                    grow_by_type(self.topo, collection, replication, ttl,
                                 self._allocate_rpc, count=1,
                                 preferred_dc=data_center, disk=disk_type)
                except NoFreeSpaceError as e:
                    if layout.active_volume_count() == 0:
                        return {"error": str(e)}
                    # no room to grow but draining copies still serve:
                    # pick_for_write's fallback takes the slow path
                # replicate the new MaxVolumeId so a failed-over leader
                # never re-issues a vid (cluster_commands.go)
                if not self._raft_propose({"type": "max_volume_id",
                                           "value":
                                           self.topo.max_volume_id}):
                    return {"error": "raft: volume id not committed",
                            "leader": self.leader}
        try:
            vid, nodes = layout.pick_for_write()
        except LookupError as e:
            return {"error": str(e)}
        key = self.sequencer.next_file_id(count)
        if self.raft is not None and key + count >= self._seq_ckpt:
            # checkpoint the sequence ahead of use so a failed-over
            # leader resumes past every id this one may have handed
            # out; minting beyond an uncommitted checkpoint is unsafe,
            # so the assign fails if the commit does
            new_ckpt = key + count + 1000
            if not self._raft_propose({"type": "sequence",
                                       "value": new_ckpt}):
                return {"error": "raft: sequence checkpoint not committed",
                        "leader": self.leader}
            self._seq_ckpt = max(self._seq_ckpt, new_ckpt)
        cookie = random.getrandbits(32)
        fid = f"{vid},{format_needle_id_cookie(key, cookie)}"
        node = nodes[0]
        self._m_assign.inc()
        reply = {
            "fid": fid,
            "url": node.url,
            "publicUrl": node.public_url,
            "count": count,
            "replicas": [{"url": n.url, "publicUrl": n.public_url}
                         for n in nodes[1:]],
        }
        if self.jwt_signing_key:
            from seaweedfs_tpu.utils.security import gen_jwt
            reply["auth"] = gen_jwt(self.jwt_signing_key, fid)
        return reply

    def _handle_assign(self, req: Request) -> Response:
        if not self.is_leader():
            return self._not_leader()
        reply = self.assign_fid(
            count=int(req.query.get("count") or 1),
            collection=req.query.get("collection", ""),
            replication=req.query.get("replication", ""),
            ttl=req.query.get("ttl", ""),
            data_center=req.query.get("dataCenter", ""),
            disk_type=req.query.get("disk", ""))
        if "error" in reply:
            # a not-ready fresh leader answers 503 + its leader hint so
            # clients re-resolve and retry instead of treating it as a
            # hard failure (wdclient._call follows the hint)
            return Response(reply,
                            status=503 if "leader" in reply else 500)
        return Response(reply)

    def _allocate_rpc(self, node, vid, collection, rp, ttl,
                      disk: str = "") -> bool:
        from seaweedfs_tpu.storage.super_block import (ReplicaPlacement,
                                                       TTL)
        try:
            # growth rides the assign path: write class, not the
            # background that classify() would infer from /admin
            with class_scope(WRITE):
                http_json("POST",
                          f"http://{node.url}/admin/allocate_volume",
                          {"volume_id": vid, "collection": collection,
                           "replication": rp, "ttl": ttl,
                           "disk_type": disk},
                          deadline=Deadline.after(30.0))
            self.peer_health.record(node.url, True)
        except Exception as e:
            if isinstance(e, ConnectionError):
                self.peer_health.record(node.url, False)
            glog.error("volume growth: allocate %d on %s failed: %s",
                       vid, node.url, e)
            return False
        # register immediately (like the reference's RegisterVolumeLayout
        # after AllocateVolume) instead of waiting for the next heartbeat
        vinfo = {"id": vid, "size": 0, "collection": collection,
                 "replica_placement": ReplicaPlacement.parse(rp).to_byte(),
                 "read_only": False, "file_count": 0, "delete_count": 0,
                 "deleted_byte_count": 0, "disk_type": disk or "hdd",
                 "ttl": TTL.parse(ttl).to_uint32(), "version": 3}
        with self.topo.lock:
            node.volumes[vid] = vinfo
            self.topo._register_volume(vinfo, node)
        return True

    def _proxy_to_leader(self, req: Request) -> Optional[Response]:
        """Followers answer read endpoints by proxying to the leader
        (reference master.follower / master proxy-to-leader): volume
        servers heartbeat only to the leader, so a follower's topology
        is empty — serving it locally would 404 every lookup. The
        X-Weed-Proxied guard stops loops during elections."""
        if self.is_leader():
            return None
        if req.headers.get(weed_headers.PROXIED):
            return None  # second hop: answer locally rather than loop
        leader = self.leader
        if not leader or leader == self.url:
            return None
        import json
        import urllib.parse

        from seaweedfs_tpu.utils.httpd import http_call
        qs = urllib.parse.urlencode(req.query)
        try:
            status, body, _ = http_call(
                "GET", f"http://{leader}{req.path}?{qs}",
                headers={weed_headers.PROXIED: "1"},
                deadline=Deadline.after(10.0))
            parsed = json.loads(body) if body else {}
        except (ConnectionError, ValueError):
            # leader unreachable or spoke garbage (e.g. a stale
            # leader_id now pointing at something else): best-effort
            # local answer instead of a 500
            return None
        return Response(parsed, status=status)

    def _handle_lookup(self, req: Request) -> Response:
        proxied = self._proxy_to_leader(req)
        if proxied is not None:
            return proxied
        vid_str = req.query.get("volumeId", "")
        vid = int(vid_str.split(",")[0]) if vid_str else 0
        collection = req.query.get("collection", "")
        nodes = self.topo.lookup(collection, vid)
        self._m_lookup.inc()
        if not nodes:
            return Response(
                {"volumeId": vid_str, "error": "volume id not found"},
                status=404)
        return Response({
            "volumeId": vid_str,
            "locations": [{"url": n.url, "publicUrl": n.public_url}
                          for n in nodes],
        })

    def _handle_lookup_ec(self, req: Request) -> Response:
        proxied = self._proxy_to_leader(req)
        if proxied is not None:
            return proxied
        vid = int(req.query.get("volumeId", 0))
        shards = self.topo.lookup_ec_shards(vid)
        if shards is None:
            return Response({"error": "ec volume not found"}, status=404)
        # each location carries its holder's heartbeat-reported QoS
        # pressure so chain planners can tie-break away from loaded
        # holders without extra round trips
        return Response({
            "volumeId": vid,
            "shards": [
                {"shard_id": sid,
                 "locations": [
                     {"url": n.url, "publicUrl": n.public_url,
                      "qos_pressure": round(
                          getattr(n, "qos_pressure", 0.0), 4)}
                     for n in nodes]}
                for sid, nodes in enumerate(shards)],
        })

    def _handle_dir_status(self, req: Request) -> Response:
        proxied = self._proxy_to_leader(req)
        if proxied is not None:
            return proxied
        return Response({"Topology": self.topo.to_info(),
                         "VolumeSizeLimitMB":
                         self.topo.volume_size_limit // (1024 * 1024),
                         "Version": "seaweedfs-tpu 0.1"})

    def _handle_grow(self, req: Request) -> Response:
        if not self.is_leader():
            return self._not_leader()
        if self.raft is not None and not self.raft.is_ready():
            # same barrier as assign_fid: a fresh leader must apply
            # inherited max_volume_id commits before minting new vids
            if not self.raft.wait_ready(timeout=2.0):
                return Response({"error": "raft leader not ready"},
                                status=503)
        count = int(req.query.get("count") or 1)
        collection = req.query.get("collection", "")
        replication = (req.query.get("replication")
                       or self.default_replication)
        ttl = req.query.get("ttl", "")
        try:
            vids = grow_by_type(self.topo, collection, replication, ttl,
                                self._allocate_rpc, count=count,
                                disk=req.query.get("disk", ""))
        except NoFreeSpaceError as e:
            return Response({"error": str(e)}, status=500)
        if not self._raft_propose({"type": "max_volume_id",
                                   "value": self.topo.max_volume_id}):
            return Response({"error": "raft: volume id not committed",
                             "leader": self.leader}, status=500)
        return Response({"count": len(vids), "volume_ids": vids})

    def _handle_cluster_status(self, req: Request) -> Response:
        return Response({
            "IsLeader": self.is_leader(),
            "Leader": self.leader,
            "Peers": self.peers,
            "MaxVolumeId": self.topo.max_volume_id,
        })

    def _handle_cluster_health(self, req: Request) -> Response:
        """Resilience rollup for the cluster.health shell command: per
        registered node (liveness, scrub state, load), this master's
        breaker/health table, and the repair bandwidth budget."""
        now = clockctl.now()
        with self.topo.lock:
            nodes = [{
                "url": n.url,
                "last_seen_s": round(now - n.last_seen, 1),
                "scrubbing": bool(getattr(n, "scrubbing", False)),
                "qos_pressure": round(getattr(n, "qos_pressure", 0.0), 4),
                "volumes": len(n.volumes),
                "ec_shards": n.ec_shard_count(),
            } for n in self.topo.all_nodes()]
        st = self.repair_queue.status()
        return Response({
            "master": self.url,
            "leader": self.leader,
            "is_leader": self.is_leader(),
            "nodes": nodes,
            "peers": self.peer_health.snapshot(),
            "repair": {
                "rate_bytes_per_sec":
                    st.get("repair_rate_bytes_per_sec", 0),
                "budget_remaining_bytes":
                    st.get("budget_remaining_bytes"),
                "active": st.get("active", 0),
                "queued": st.get("queued", 0),
            },
        })

    def _handle_cluster_qos(self, req: Request) -> Response:
        """Cluster QoS rollup for the cluster.qos shell command:
        per-node overload pressure (from heartbeats) and how far the
        repair budget has backed off in response."""
        now = clockctl.now()
        with self.topo.lock:
            nodes = [{
                "url": n.url,
                "last_seen_s": round(now - n.last_seen, 1),
                "qos_pressure": round(getattr(n, "qos_pressure", 0.0), 4),
            } for n in self.topo.all_nodes()]
        st = self.repair_queue.status()
        return Response({
            "master": self.url,
            "is_leader": self.is_leader(),
            "cluster_pressure": max(
                (n["qos_pressure"] for n in nodes), default=0.0),
            "master_edge": self.qos.snapshot(),
            "nodes": nodes,
            "repair": {
                "base_rate_bytes_per_sec":
                    st.get("base_rate_bytes_per_sec", 0),
                "rate_bytes_per_sec":
                    st.get("repair_rate_bytes_per_sec", 0),
                "cluster_qos_pressure":
                    st.get("cluster_qos_pressure", 0.0),
            },
        })

    # ---- cluster telemetry plane (RED quantiles, hot keys, SLO) ----
    def telemetry_snapshot(self) -> dict:
        """This master's own edge contribution to the merged view."""
        return {"node": self.url, "server": "master",
                "red": self.red.snapshot(),
                "ledger": self.ledger.snapshot()}

    def _on_slo_transition(self, t, cls, old, new, detail) -> None:
        glog.info("slo: class=%s %s -> %s (%s)", cls, old, new, detail)

    def _telemetry_node_snaps(self) -> list:
        """Everything reachable without network: our own edge plus
        the per-volume-server snapshots riding heartbeats."""
        snaps = [self.telemetry_snapshot()]
        with self.topo.lock:
            for n in self.topo.all_nodes():
                t = getattr(n, "telemetry", None)
                if t:
                    snaps.append(t)
        return snaps

    def _pull_peer_telemetry(self, unreachable: list) -> list:
        """Filer/S3 snapshots via the /cluster/register membership
        table (they announce a metrics_url; /admin/telemetry lives
        there because their main ports are user namespace)."""
        snaps = []
        now = clockctl.now()
        for (ntype, url), seen in list(self._cluster_nodes.items()):
            if now - seen >= 60:
                continue
            meta = self._cluster_node_meta.get((ntype, url)) or {}
            target = meta.get("metrics_url")
            if not target:
                continue
            try:
                snaps.append(http_json(
                    "GET", f"http://{target}/admin/telemetry",
                    deadline=Deadline.after(3.0)))
            except Exception as e:
                unreachable.append({"node": url, "type": ntype,
                                    "error": type(e).__name__})
        return snaps

    def _refresh_slo_gauges(self, slo_view: dict) -> None:
        for cls, judged in slo_view.items():
            self._m_slo_burn.set(cls, "fast",
                                 value=judged["fast_burn"])
            self._m_slo_burn.set(cls, "slow",
                                 value=judged["slow_burn"])
            self._m_slo_alert.set(cls, value={
                "ok": 0.0, "slow_burn": 0.5,
                "fast_burn": 1.0}[judged["state"]])

    def _feed_slo(self) -> None:
        """Pulse-cadence SLO evaluation from heartbeat-held snapshots
        only (no network) — burn-rate windows accumulate even when
        nobody scrapes /cluster/telemetry."""
        try:
            view = self.telemetry.rollup(clockctl.monotonic(),
                                         self._telemetry_node_snaps())
            self._refresh_slo_gauges(view["slo"])
        except Exception as e:
            glog.vlog(1, "slo feed failed: %s", e)

    def _handle_cluster_telemetry(self, req: Request) -> Response:
        """Merged cluster view: per-class p50/p99 + error rates from
        exact histogram merging, cluster top-k hot keys, bucket
        exemplar trace ids, and the SLO burn-rate judgement."""
        unreachable: list = []
        snaps = self._telemetry_node_snaps()
        if req.query.get("peers", "true") != "false":
            snaps += self._pull_peer_telemetry(unreachable)
        view = self.telemetry.rollup(
            clockctl.monotonic(), snaps,
            top_k=int(req.query.get("k", 10)))
        self._refresh_slo_gauges(view["slo"])
        view.update({"master": self.url,
                     "is_leader": self.is_leader(),
                     "unreachable": unreachable})
        return Response(view)

    def _handle_lock(self, req: Request) -> Response:
        body = req.json() or {}
        client = body.get("client", "unknown")
        now = clockctl.now()
        if (self._admin_lock_holder
                and self._admin_lock_holder != client
                and now - self._admin_lock_ts < 60):
            return Response({"error":
                             f"locked by {self._admin_lock_holder}"},
                            status=409)
        self._admin_lock_holder = client
        self._admin_lock_ts = now
        return Response({"holder": client})

    def _handle_unlock(self, req: Request) -> Response:
        self._admin_lock_holder = None
        return Response({})
