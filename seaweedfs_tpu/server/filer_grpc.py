"""gRPC plane for the filer (reference weed/pb/filer.proto).

Serves the filer_pb.SeaweedFiler RPCs — entry CRUD, streaming
ListEntries, AtomicRenameEntry, KV, and the streaming SubscribeMetadata
CDC feed — over grpc generic method handlers, dispatching to the same
Filer core the HTTP plane uses. filer.sync and the mount meta cache
consume SubscribeMetadata when the peer speaks gRPC (HTTP long-poll
remains as fallback).
"""

from __future__ import annotations

import json
from concurrent import futures
from typing import Iterator, Optional

import grpc

from seaweedfs_tpu.utils import clockctl
from seaweedfs_tpu.filer.entry import Attr, Entry, FileChunk
from seaweedfs_tpu.pb import filer_pb2 as pb

SERVICE = "weedtpu_filer_pb.SeaweedFiler"


def _entry_to_pb(e: Entry) -> pb.Entry:
    out = pb.Entry(name=e.name, is_directory=e.is_directory)
    a = e.attr
    out.attributes.file_size = a.file_size
    out.attributes.mtime = int(a.mtime)
    out.attributes.file_mode = a.mode
    out.attributes.uid = a.uid
    out.attributes.gid = a.gid
    out.attributes.crtime = int(a.crtime)
    out.attributes.mime = a.mime
    out.attributes.replication = a.replication
    out.attributes.collection = a.collection
    out.attributes.ttl_sec = a.ttl_sec
    out.attributes.user_name = a.user_name
    out.attributes.symlink_target = a.symlink_target
    for c in e.chunks:
        out.chunks.add(file_id=c.fid, offset=c.offset, size=c.size,
                       mtime=c.mtime_ns, e_tag=c.etag,
                       is_chunk_manifest=c.is_chunk_manifest,
                       cipher_key=c.cipher_key.hex())
    for k, v in (e.extended or {}).items():
        out.extended[k] = v if isinstance(v, bytes) else str(v).encode()
    if e.content:
        out.content = e.content
    if e.hard_link_id:
        out.hard_link_id = e.hard_link_id.encode()
    return out


def _chunk_from_pb(c: "pb.FileChunk") -> FileChunk:
    return FileChunk(
        fid=c.file_id, offset=c.offset, size=c.size, mtime_ns=c.mtime,
        etag=c.e_tag, is_chunk_manifest=c.is_chunk_manifest,
        cipher_key=bytes.fromhex(c.cipher_key) if c.cipher_key else b"")


def _entry_from_pb(directory: str, p: pb.Entry) -> Entry:
    full = directory.rstrip("/") + "/" + p.name if p.name else directory
    a = p.attributes
    entry = Entry(
        full_path=full or "/",
        attr=Attr(mtime=float(a.mtime), crtime=float(a.crtime),
                  mode=a.file_mode or 0o660, uid=a.uid, gid=a.gid,
                  mime=a.mime, ttl_sec=a.ttl_sec, user_name=a.user_name,
                  symlink_target=a.symlink_target,
                  file_size=a.file_size, is_directory=p.is_directory,
                  collection=a.collection, replication=a.replication),
        content=bytes(p.content),
        hard_link_id=p.hard_link_id.decode() if p.hard_link_id else "")
    for c in p.chunks:
        entry.chunks.append(_chunk_from_pb(c))
    entry.extended = {k: bytes(v) for k, v in p.extended.items()}
    return entry


def _event_entry_to_pb(d: Optional[dict]) -> Optional[pb.Entry]:
    if not d:
        return None
    e = Entry.from_dict(d)
    return _entry_to_pb(e)


class FilerGrpc:
    def __init__(self, filer_server):
        self.fs = filer_server
        self.filer = filer_server.filer

    # ---- entry CRUD ----
    def lookup(self, request, context):
        path = request.directory.rstrip("/") + "/" + request.name
        e = self.filer.find_entry(path)
        if e is None:
            context.abort(grpc.StatusCode.NOT_FOUND, "not found")
        return pb.LookupDirectoryEntryResponse(entry=_entry_to_pb(e))

    def list_entries(self, request, context
                     ) -> Iterator[pb.ListEntriesResponse]:
        limit = request.limit or 1024
        entries = self.filer.list_entries(
            request.directory or "/",
            start_name=request.start_from_file_name,
            include_start=request.inclusive_start_from,
            limit=limit, prefix=request.prefix)
        for e in entries:
            yield pb.ListEntriesResponse(entry=_entry_to_pb(e))

    def create_entry(self, request, context):
        try:
            self.filer.create_entry(
                _entry_from_pb(request.directory, request.entry))
        except IsADirectoryError as e:
            return pb.CreateEntryResponse(error=str(e) or "is a directory")
        return pb.CreateEntryResponse()

    def update_entry(self, request, context):
        self.filer.update_entry(
            _entry_from_pb(request.directory, request.entry))
        return pb.UpdateEntryResponse()

    def delete_entry(self, request, context):
        path = request.directory.rstrip("/") + "/" + request.name
        try:
            self.filer.delete_entry(
                path, recursive=request.is_recursive,
                ignore_recursive_error=request.ignore_recursive_error)
        except FileNotFoundError:
            return pb.DeleteEntryResponse(error="not found")
        except OSError as e:  # non-empty without recursive
            return pb.DeleteEntryResponse(error=str(e))
        return pb.DeleteEntryResponse()

    def atomic_rename(self, request, context):
        old = request.old_directory.rstrip("/") + "/" + request.old_name
        new = request.new_directory.rstrip("/") + "/" + request.new_name
        try:
            self.filer.rename_entry(old, new)
        except FileNotFoundError:
            context.abort(grpc.StatusCode.NOT_FOUND, "not found")
        return pb.AtomicRenameEntryResponse()

    # ---- KV ----
    def kv_get(self, request, context):
        val = self.filer.store.kv_get(bytes(request.key))
        if val is None:
            return pb.KvGetResponse(error="not found")
        return pb.KvGetResponse(value=val)

    def kv_put(self, request, context):
        if request.delete:
            self.filer.store.kv_delete(bytes(request.key))
        else:
            self.filer.store.kv_put(bytes(request.key),
                                    bytes(request.value))
        return pb.KvPutResponse()

    # ---- meta subscription (CDC) ----
    def subscribe_metadata(self, request, context
                           ) -> Iterator[pb.SubscribeMetadataResponse]:
        """Streaming CDC feed (reference filer_grpc_server_sub_meta.go):
        replays persisted events since since_ns, then follows the live
        log until the client disconnects."""
        since = request.since_ns
        prefix = request.path_prefix or "/"
        log = self.filer.meta_log
        while context.is_active():
            # snapshot BEFORE reading: everything <= latest that read_since
            # omits is prefix-filtered, so the cursor may skip it — without
            # this, a subscriber whose prefix never matches busy-spins
            latest = log.latest_tsns()
            events = log.read_since(since, path_prefix=prefix, limit=1024)
            for ev in events:
                d = ev if isinstance(ev, dict) else ev.to_dict()
                resp = pb.SubscribeMetadataResponse(
                    directory=d.get("directory", ""),
                    ts_ns=d.get("tsns", 0))
                old_pb = _event_entry_to_pb(d.get("old_entry"))
                new_pb = _event_entry_to_pb(d.get("new_entry"))
                if old_pb is not None:
                    resp.event_notification.old_entry.CopyFrom(old_pb)
                if new_pb is not None:
                    resp.event_notification.new_entry.CopyFrom(new_pb)
                since = max(since, d.get("tsns", 0))
                yield resp
            if not events:
                since = max(since, latest)
                # block until new events or a short timeout, then re-check
                log.wait_for_events(since, timeout=1.0)
        return

    # ---- volume plane proxies (the pure-gRPC write path: reference
    # filer.proto:36 AssignVolume + LookupVolume; a client assigns
    # here, POSTs the payload to the returned url, then CreateEntry) ----
    def assign_volume(self, request, context):
        rule = None
        if request.path:
            try:
                # _current_filer_conf reloads per-path rules on a TTL so
                # fs.configure changes reach the gRPC path too
                rule = self.fs._current_filer_conf().match_storage_rule(
                    request.path)
            except Exception:
                rule = None
        collection = request.collection or (rule.collection if rule else "")
        replication = request.replication or \
            (rule.replication if rule else "")
        # TTL grammar has no seconds unit (reference needle.TTL:
        # m/h/d/w/M/y) — round seconds up to whole minutes
        ttl = f"{-(-request.ttl_sec // 60)}m" if request.ttl_sec else \
            (rule.ttl if rule else "")
        try:
            a = self.fs.mc.assign(count=max(request.count, 1),
                                  collection=collection,
                                  replication=replication, ttl=ttl,
                                  data_center=request.data_center)
        except Exception as e:
            return pb.AssignVolumeResponse(error=str(e))
        if a.get("error"):
            return pb.AssignVolumeResponse(error=a["error"])
        return pb.AssignVolumeResponse(
            file_id=a["fid"], url=a["url"],
            public_url=a.get("publicUrl", a["url"]),
            count=a.get("count", 1), collection=collection,
            replication=replication)

    def lookup_volume(self, request, context):
        resp = pb.LookupVolumeResponse()
        for vid_str in request.volume_ids:
            try:
                vid = int(vid_str.split(",")[0])
            except ValueError:
                continue
            locs = pb.Locations()
            for loc in self.fs.mc.lookup_volume(vid):
                locs.locations.append(pb.Location(
                    url=loc.get("url", ""),
                    public_url=loc.get("publicUrl", loc.get("url", ""))))
            resp.locations_map[vid_str].CopyFrom(locs)
        return resp

    def append_to_entry(self, request, context):
        """reference filer_grpc_server.go AppendToEntry: extend an
        entry's chunk list at its current tail (log-style appends; the
        mq broker writes segments this way). The read-modify-write runs
        under the filer lock so concurrent appenders can't compute the
        same tail offset."""
        import time as _time

        from seaweedfs_tpu.filer.entry import Attr
        path = request.directory.rstrip("/") + "/" + request.entry_name
        with self.fs.filer._lock:
            entry = self.fs.filer.find_entry(path)
            if entry is None:
                entry = Entry(full_path=path,
                              attr=Attr(mtime=clockctl.now(),
                                        crtime=clockctl.now(), mode=0o644))
            elif entry.content:
                # inline content can't coexist with chunks (the read
                # path prefers content): spill it to a chunk first
                fc = self.fs._save_chunk(entry.content, 0, "", "")
                entry.chunks = [fc]
                entry.content = b""
            offset = entry.file_size()
            for c in request.chunks:
                fc = _chunk_from_pb(c)
                fc.offset = offset
                if not fc.mtime_ns:
                    fc.mtime_ns = _time.time_ns()
                offset += fc.size
                entry.chunks.append(fc)
            entry.attr.file_size = offset
            try:
                self.fs.filer.create_entry(entry)
            except Exception as e:
                return pb.AppendToEntryResponse(error=str(e))
        return pb.AppendToEntryResponse()

    def collection_list(self, request, context):
        from seaweedfs_tpu.utils.httpd import http_json
        try:
            out = http_json("GET",
                            f"http://{self.fs.master_url}/col/list")
        except ConnectionError as e:
            context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
        return pb.CollectionListResponse(
            collections=[c["name"] if isinstance(c, dict) else c
                         for c in out.get("collections", [])])

    def delete_collection(self, request, context):
        from seaweedfs_tpu.utils.httpd import http_json
        try:
            http_json("POST", f"http://{self.fs.master_url}/col/delete"
                              f"?collection={request.collection}")
        except ConnectionError as e:
            context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
        return pb.DeleteCollectionResponse()

    def ping(self, request, context):
        import time as _time
        start = _time.time_ns()
        remote = start
        if request.target:
            from seaweedfs_tpu.utils.httpd import http_call
            try:
                http_call("GET", f"http://{request.target}/status",
                          timeout=5)
                remote = _time.time_ns()
            except Exception as e:
                context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
        return pb.PingResponse(start_time_ns=start,
                               remote_time_ns=remote,
                               stop_time_ns=_time.time_ns())

    def cache_remote_object(self, request, context):
        """reference filer_grpc_server_remote.go: materialize a
        remote-mounted entry's bytes as local chunks."""
        path = request.directory.rstrip("/") + "/" + request.name
        entry = self.fs.filer.find_entry(path)
        if entry is None:
            context.abort(grpc.StatusCode.NOT_FOUND, path)
        if entry.remote is not None and not entry.chunks \
                and not entry.content:
            try:
                rule = self.fs._current_filer_conf().match_storage_rule(
                    path)
                self.fs.remote_mounts.cache_entry(
                    entry, lambda data: self.fs._upload_chunks(
                        data, rule.collection, rule.replication,
                        rule.ttl))
                entry = self.fs.filer.find_entry(path)
            except Exception as e:
                context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
        return pb.CacheRemoteObjectToLocalClusterResponse(
            entry=_entry_to_pb(entry))

    # ---- misc ----
    def statistics(self, request, context):
        """Aggregate capacity from the master topology (reference
        filer_grpc_server.go Statistics proxies to the master)."""
        try:
            topo = self.fs.mc.topology()
        except Exception:
            return pb.StatisticsResponse()
        from seaweedfs_tpu.cluster.topology import aggregate_topology_info
        agg = aggregate_topology_info(topo.get("Topology", topo))
        limit = topo.get("VolumeSizeLimitMB", 0) * 1024 * 1024
        return pb.StatisticsResponse(total_size=agg["slots"] * limit,
                                     used_size=agg["used_bytes"],
                                     file_count=agg["file_count"])

    def get_configuration(self, request, context):
        return pb.GetFilerConfigurationResponse(
            masters=[self.fs.master_url] if getattr(self.fs, "master_url",
                                                    "") else [],
            version="seaweedfs-tpu")

    def handlers(self) -> grpc.GenericRpcHandler:
        def unary(fn, req_cls, resp_cls):
            return grpc.unary_unary_rpc_method_handler(
                fn, request_deserializer=req_cls.FromString,
                response_serializer=resp_cls.SerializeToString)

        def ustream(fn, req_cls, resp_cls):
            return grpc.unary_stream_rpc_method_handler(
                fn, request_deserializer=req_cls.FromString,
                response_serializer=resp_cls.SerializeToString)

        rpcs = {
            "LookupDirectoryEntry": unary(
                self.lookup, pb.LookupDirectoryEntryRequest,
                pb.LookupDirectoryEntryResponse),
            "ListEntries": ustream(self.list_entries, pb.ListEntriesRequest,
                                   pb.ListEntriesResponse),
            "CreateEntry": unary(self.create_entry, pb.CreateEntryRequest,
                                 pb.CreateEntryResponse),
            "UpdateEntry": unary(self.update_entry, pb.UpdateEntryRequest,
                                 pb.UpdateEntryResponse),
            "DeleteEntry": unary(self.delete_entry, pb.DeleteEntryRequest,
                                 pb.DeleteEntryResponse),
            "AtomicRenameEntry": unary(self.atomic_rename,
                                       pb.AtomicRenameEntryRequest,
                                       pb.AtomicRenameEntryResponse),
            "SubscribeMetadata": ustream(self.subscribe_metadata,
                                         pb.SubscribeMetadataRequest,
                                         pb.SubscribeMetadataResponse),
            "SubscribeLocalMetadata": ustream(
                self.subscribe_metadata, pb.SubscribeMetadataRequest,
                pb.SubscribeMetadataResponse),
            "AppendToEntry": unary(self.append_to_entry,
                                   pb.AppendToEntryRequest,
                                   pb.AppendToEntryResponse),
            "CollectionList": unary(self.collection_list,
                                    pb.CollectionListRequest,
                                    pb.CollectionListResponse),
            "DeleteCollection": unary(self.delete_collection,
                                      pb.DeleteCollectionRequest,
                                      pb.DeleteCollectionResponse),
            "Ping": unary(self.ping, pb.PingRequest, pb.PingResponse),
            "CacheRemoteObjectToLocalCluster": unary(
                self.cache_remote_object,
                pb.CacheRemoteObjectToLocalClusterRequest,
                pb.CacheRemoteObjectToLocalClusterResponse),
            "AssignVolume": unary(self.assign_volume,
                                  pb.AssignVolumeRequest,
                                  pb.AssignVolumeResponse),
            "LookupVolume": unary(self.lookup_volume,
                                  pb.LookupVolumeRequest,
                                  pb.LookupVolumeResponse),
            "KvGet": unary(self.kv_get, pb.KvGetRequest, pb.KvGetResponse),
            "KvPut": unary(self.kv_put, pb.KvPutRequest, pb.KvPutResponse),
            "Statistics": unary(self.statistics, pb.StatisticsRequest,
                                pb.StatisticsResponse),
            "GetFilerConfiguration": unary(
                self.get_configuration, pb.GetFilerConfigurationRequest,
                pb.GetFilerConfigurationResponse),
        }
        return grpc.method_handlers_generic_handler(SERVICE, rpcs)


class S3ConfigGrpc:
    """weedtpu_s3_pb.SeaweedTpuS3 — the S3 admin Configure RPC
    (reference weed/pb/s3.proto), registered on the filer gRPC server:
    the S3 gateway and IAM server read identity config from the filer
    (/etc/iam/identity.json), so configuring it IS a filer write.

    Accepts either a binary weedtpu_iam_pb.S3ApiConfiguration or the
    legacy JSON identity file, persists canonical JSON."""

    def __init__(self, filer_server):
        self.fs = filer_server

    def configure(self, request, context):
        from seaweedfs_tpu.gateway.iam_server import IdentityStore
        from seaweedfs_tpu.pb import iam_pb2, s3_pb2
        content = request.s3_configuration_file_content
        try:
            conf = json.loads(content)
            if not isinstance(conf, dict) or "identities" not in conf:
                raise ValueError("missing identities")
        except (UnicodeDecodeError, ValueError):
            try:
                api = iam_pb2.S3ApiConfiguration.FromString(content)
            except Exception:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                              "neither S3ApiConfiguration proto nor "
                              "identity JSON")
            conf = {"identities": [
                {"name": i.name,
                 "credentials": [{"accessKey": c.access_key,
                                  "secretKey": c.secret_key}
                                 for c in i.credentials],
                 "actions": list(i.actions)} for i in api.identities]}
        IdentityStore(self.fs.filer).save(conf)
        return s3_pb2.S3ConfigureResponse()

    def handlers(self):
        from seaweedfs_tpu.pb import s3_pb2
        rpcs = {
            "Configure": grpc.unary_unary_rpc_method_handler(
                self.configure,
                request_deserializer=s3_pb2.S3ConfigureRequest.FromString,
                response_serializer=(
                    s3_pb2.S3ConfigureResponse.SerializeToString)),
        }
        return grpc.method_handlers_generic_handler(
            "weedtpu_s3_pb.SeaweedTpuS3", rpcs)


def start_filer_grpc(filer_server, host: str = "127.0.0.1",
                     port: int = 0, tls="auto") -> tuple[grpc.Server, int]:
    from seaweedfs_tpu.utils import tls as tlsmod
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=32))
    server.add_generic_rpc_handlers((FilerGrpc(filer_server).handlers(),
                                     S3ConfigGrpc(filer_server).handlers()))
    cfg = tlsmod.load_tls_config("filer") if tls == "auto" else tls
    if cfg is not None:
        bound = server.add_secure_port(
            f"{host}:{port}", tlsmod.server_credentials(cfg))
    else:
        bound = server.add_insecure_port(f"{host}:{port}")
    server.start()
    return server, bound


class GrpcFilerClient:
    """Client for the filer gRPC plane (filer.sync, mount meta cache)."""

    def __init__(self, address: str, tls="auto"):
        from seaweedfs_tpu.utils.tls import make_channel
        self.channel = make_channel(address, role="client", tls=tls)

    def _unary(self, method: str, request, resp_cls, timeout: float = 30):
        fn = self.channel.unary_unary(
            f"/{SERVICE}/{method}",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=resp_cls.FromString)
        return fn(request, timeout=timeout)

    def lookup(self, directory: str, name: str) -> pb.Entry:
        return self._unary("LookupDirectoryEntry",
                           pb.LookupDirectoryEntryRequest(
                               directory=directory, name=name),
                           pb.LookupDirectoryEntryResponse).entry

    def list_entries(self, directory: str, prefix: str = "",
                     limit: int = 1024) -> list[pb.Entry]:
        fn = self.channel.unary_stream(
            f"/{SERVICE}/ListEntries",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.ListEntriesResponse.FromString)
        return [r.entry for r in fn(pb.ListEntriesRequest(
            directory=directory, prefix=prefix, limit=limit), timeout=60)]

    def create_entry(self, directory: str, entry: pb.Entry) -> None:
        r = self._unary("CreateEntry", pb.CreateEntryRequest(
            directory=directory, entry=entry), pb.CreateEntryResponse)
        if r.error:
            raise RuntimeError(r.error)

    def delete_entry(self, directory: str, name: str,
                     recursive: bool = False,
                     delete_data: bool = True) -> None:
        self._unary("DeleteEntry", pb.DeleteEntryRequest(
            directory=directory, name=name, is_recursive=recursive,
            is_delete_data=delete_data), pb.DeleteEntryResponse)

    def rename(self, old_dir: str, old_name: str, new_dir: str,
               new_name: str) -> None:
        self._unary("AtomicRenameEntry", pb.AtomicRenameEntryRequest(
            old_directory=old_dir, old_name=old_name,
            new_directory=new_dir, new_name=new_name),
            pb.AtomicRenameEntryResponse)

    def assign_volume(self, count: int = 1, collection: str = "",
                      replication: str = "", ttl_sec: int = 0,
                      path: str = "") -> pb.AssignVolumeResponse:
        r = self._unary("AssignVolume", pb.AssignVolumeRequest(
            count=count, collection=collection, replication=replication,
            ttl_sec=ttl_sec, path=path), pb.AssignVolumeResponse)
        if r.error:
            raise RuntimeError(r.error)
        return r

    def lookup_volume(self, volume_ids: list[str]
                      ) -> dict[str, list[str]]:
        r = self._unary("LookupVolume", pb.LookupVolumeRequest(
            volume_ids=volume_ids), pb.LookupVolumeResponse)
        return {vid: [l.url for l in locs.locations]
                for vid, locs in r.locations_map.items()}

    def statistics(self) -> pb.StatisticsResponse:
        return self._unary("Statistics", pb.StatisticsRequest(),
                           pb.StatisticsResponse)

    def append_to_entry(self, directory: str, name: str,
                        chunks: list) -> None:
        r = self._unary("AppendToEntry", pb.AppendToEntryRequest(
            directory=directory, entry_name=name, chunks=chunks),
            pb.AppendToEntryResponse)
        if r.error:
            raise RuntimeError(r.error)

    def collection_list(self) -> list[str]:
        r = self._unary("CollectionList", pb.CollectionListRequest(),
                        pb.CollectionListResponse)
        return list(r.collections)

    def delete_collection(self, name: str) -> None:
        self._unary("DeleteCollection",
                    pb.DeleteCollectionRequest(collection=name),
                    pb.DeleteCollectionResponse)

    def ping(self, target: str = "", target_type: str = ""
             ) -> pb.PingResponse:
        return self._unary("Ping", pb.PingRequest(
            target=target, target_type=target_type), pb.PingResponse,
            timeout=10)

    def get_configuration(self) -> pb.GetFilerConfigurationResponse:
        return self._unary("GetFilerConfiguration",
                           pb.GetFilerConfigurationRequest(),
                           pb.GetFilerConfigurationResponse)

    def kv_get(self, key: bytes) -> Optional[bytes]:
        r = self._unary("KvGet", pb.KvGetRequest(key=key), pb.KvGetResponse)
        return None if r.error else bytes(r.value)

    def kv_put(self, key: bytes, value: bytes) -> None:
        self._unary("KvPut", pb.KvPutRequest(key=key, value=value),
                    pb.KvPutResponse)

    def subscribe_metadata(self, since_ns: int = 0, path_prefix: str = "/",
                           client_name: str = "client"):
        """Returns the (blocking) response iterator; cancel() the returned
        call to stop."""
        fn = self.channel.unary_stream(
            f"/{SERVICE}/SubscribeMetadata",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.SubscribeMetadataResponse.FromString)
        return fn(pb.SubscribeMetadataRequest(
            client_name=client_name, path_prefix=path_prefix,
            since_ns=since_ns))

    def close(self):
        self.channel.close()
