"""Raw TCP data path for the volume server.

Functional equivalent of reference
weed/server/volume_server_tcp_handlers_write.go (enabled by
`weed benchmark -useTcp` / the volume server's TCP listener): a
persistent connection that skips HTTP parsing entirely for the
hot write/read path. Framing (all big-endian):

  request:  op(1: W/R/D) fid_len(u16) fid body_len(u32) body
  response: status(1: 0=ok) body_len(u32) body

The write path goes through Store.write_volume_needle like the HTTP
handler, but without headers, query parsing, or JWT (the TCP port is an
internal/benchmark surface, like the reference's)."""

from __future__ import annotations

import socket
import struct
import threading
from typing import Optional

from seaweedfs_tpu.storage.file_id import FileId
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import (CookieMismatchError, DeletedError,
                                          NotFoundError)

_HDR = struct.Struct(">BH")
_LEN = struct.Struct(">I")


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


class TcpDataServer:
    """Accept loop + per-connection request loop over a Store."""

    def __init__(self, store, host: str = "127.0.0.1", port: int = 0):
        self.store = store
        self._srv = socket.create_server((host, port))
        self.host, self.port = self._srv.getsockname()[:2]
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True,
                                        name="volume-tcp-accept")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True,
                             name="volume-tcp-conn").start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while not self._stop.is_set():
                head = _recv_exact(conn, _HDR.size)
                op, fid_len = _HDR.unpack(head)
                fid = _recv_exact(conn, fid_len).decode()
                body_len = _LEN.unpack(_recv_exact(conn, _LEN.size))[0]
                body = _recv_exact(conn, body_len) if body_len else b""
                status, payload = self._dispatch(chr(op), fid, body)
                conn.sendall(bytes([status]) + _LEN.pack(len(payload))
                             + payload)
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, op: str, fid: str, body: bytes
                  ) -> tuple[int, bytes]:
        try:
            f = FileId.parse(fid)
            vid, key, cookie = f.volume_id, f.key, f.cookie
        except (ValueError, IndexError):
            return 1, b"bad fid"
        try:
            if op == "W":
                n = Needle(id=key, cookie=cookie, data=body)
                n.set_flags_from_fields()
                self.store.write_volume_needle(vid, n)
                return 0, b""
            if op == "R":
                n = self.store.read_volume_needle(vid, key, cookie)
                return 0, n.data
            if op == "D":
                self.store.delete_volume_needle(vid, key, cookie)
                return 0, b""
        except (NotFoundError, DeletedError) as e:
            return 2, str(e).encode()
        except CookieMismatchError as e:
            return 3, str(e).encode()
        except Exception as e:  # keep the connection alive on errors
            return 1, f"{type(e).__name__}: {e}".encode()
        return 1, b"unknown op"


class TcpClient:
    """Persistent-connection client (benchmark -useTcp side)."""

    def __init__(self, host: str, port: int):
        self._sock = socket.create_connection((host, port))
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()

    def _roundtrip(self, op: str, fid: str, body: bytes = b""
                   ) -> tuple[int, bytes]:
        f = fid.encode()
        with self._lock:
            self._sock.sendall(_HDR.pack(ord(op), len(f)) + f
                               + _LEN.pack(len(body)) + body)
            status = _recv_exact(self._sock, 1)[0]
            plen = _LEN.unpack(_recv_exact(self._sock, _LEN.size))[0]
            payload = _recv_exact(self._sock, plen) if plen else b""
        return status, payload

    def write(self, fid: str, data: bytes) -> None:
        status, payload = self._roundtrip("W", fid, data)
        if status != 0:
            raise IOError(f"tcp write {fid}: {payload.decode()}")

    def read(self, fid: str) -> bytes:
        status, payload = self._roundtrip("R", fid)
        if status != 0:
            raise IOError(f"tcp read {fid}: {payload.decode()}")
        return payload

    def delete(self, fid: str) -> None:
        status, payload = self._roundtrip("D", fid)
        if status != 0:
            raise IOError(f"tcp delete {fid}: {payload.decode()}")

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
