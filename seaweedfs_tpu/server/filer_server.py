"""Filer HTTP server: a file-system namespace over the object store.

Functional equivalent of reference weed/server/filer_server*.go:

  POST/PUT <path>      upload: body is split into chunks, each assigned +
                       uploaded to volume servers (auto-chunking,
                       reference filer_server_handlers_write_autochunk.go);
                       small files are inlined in the entry
  GET  <path>          file -> stream assembled chunks; dir -> JSON listing
  DELETE <path>        delete entry (+ ?recursive=true), chunks GC'd
  POST /__api/rename   {"from":..., "to":...}
  GET  /__api/meta_events?since_ns=N&prefix=/  meta change log (CDC)
"""

from __future__ import annotations

import json
import os
import threading
import time
from concurrent.futures import (FIRST_COMPLETED, CancelledError,
                                ThreadPoolExecutor, as_completed, wait)
from typing import Optional

from seaweedfs_tpu.client import operation
from seaweedfs_tpu.client.wdclient import MasterClient
from seaweedfs_tpu.filer.entry import Attr, Entry, FileChunk
from seaweedfs_tpu.filer.filechunk_manifest import (MANIFEST_BATCH,
                                                    has_chunk_manifest,
                                                    maybe_manifestize,
                                                    resolve_chunk_manifest)
from seaweedfs_tpu.filer.filechunks import (non_overlapping_visible_intervals,
                                            view_from_visibles)
from seaweedfs_tpu.filer.entry_cache import EntryCache
from seaweedfs_tpu.filer.filer import Filer
from seaweedfs_tpu.filer.filer_conf import FilerConf, PathConf
from seaweedfs_tpu.filer.filerstore import make_store
from seaweedfs_tpu.filer.shard_ring import (ShardRing, format_shard_header,
                                            parent_dir)
from seaweedfs_tpu.qos import (BACKGROUND, QosGovernor, class_scope,
                               classify, current_class, from_headers)
from seaweedfs_tpu.utils import headers as weed_headers
from seaweedfs_tpu.utils import clockctl, glog, profiler, tracing
from seaweedfs_tpu.utils.httpd import (HttpError, HttpServer,
                                       RangeNotSatisfiable, Request,
                                       Response, http_call,
                                       parse_byte_range)
from seaweedfs_tpu.utils.resilience import (Deadline, PeerHealth,
                                            current_deadline,
                                            deadline_scope, hedged)

CHUNK_SIZE = 4 * 1024 * 1024
INLINE_LIMIT = 2048  # small content stored in the entry itself
READ_DEADLINE_S = 30.0  # edge deadline for a filer GET without one
# Concurrent chunk uploads per filer process (reference
# filer_server_handlers_write_upload.go uploads via a bounded
# goroutine pool); shared across requests so a burst of PUTs can't
# multiply into unbounded sockets/threads.
UPLOAD_WORKERS = int(os.environ.get("SEAWEEDFS_TPU_FILER_UPLOAD_WORKERS",
                                    "8"))
# streaming ingest memory cap: at most this many chunk uploads in
# flight while the NEXT chunk buffer fills — peak body memory is
# (STREAM_INFLIGHT + 1) * CHUNK_SIZE regardless of object size
STREAM_INFLIGHT = 2
# fids are pre-minted in waves this big while streaming (the object's
# total chunk count is unknown until EOF); unwritten leftovers are
# just unused fids
STREAM_ASSIGN_WAVE = 8


def _read_full(stream, n: int) -> bytes:
    """Read exactly n bytes from a BodyStream (short only at end of
    body): chunked transfer encoding hands out one wire chunk per
    read, so a single read() can come up short mid-body."""
    out = stream.read(n)
    if len(out) >= n or not out:
        return out
    parts = [out]
    got = len(out)
    while got < n:
        piece = stream.read(n - got)
        if not piece:
            break
        parts.append(piece)
        got += len(piece)
    return b"".join(parts)


def _ttl_seconds(ttl: str) -> int:
    """Parse '3m'/'4h'/'5d'/'6w'-style TTLs (reference needle/volume_ttl.go)."""
    if not ttl:
        return 0
    units = {"s": 1, "m": 60, "h": 3600, "d": 86400, "w": 604800}
    if ttl[-1] in units:
        try:
            return int(ttl[:-1]) * units[ttl[-1]]
        except ValueError:
            return 0
    try:
        return int(ttl) * 60
    except ValueError:
        return 0


class FilerServer:
    def __init__(self, master_url: str, host: str = "127.0.0.1",
                 port: int = 0, store: str = "memory",
                 store_dir: Optional[str] = None,
                 default_replication: str = "", cipher: bool = False,
                 announce: bool = True, grpc_port: Optional[int] = None,
                 qos: bool = True,
                 tracing_enabled: bool = True,
                 trace_sample: float = 0.01,
                 profile_hz: float = profiler.DEFAULT_HZ,
                 sharding: bool = False,
                 entry_cache: bool = True,
                 assign_leases: bool = True):
        # qos=False disables admission control entirely (the
        # bit-for-bit comparator, same convention as parallel_uploads)
        # cipher=True encrypts every chunk (AES-256-GCM, per-chunk key in
        # the chunk metadata) so volume servers hold only ciphertext
        # (reference `weed filer -encryptVolumeData`)
        # assign_leases routes _upload_chunks/_stream_chunks fid
        # assigns through the direct-to-volume lease lane inside
        # MasterClient.assign (fallback: master /dir/assign) — writes
        # keep flowing through a master leader outage while volume
        # servers hold valid leases. False = every assign round-trips
        # the master, the bench comparator.
        self.cipher = cipher
        # announce=False: gateway mode (remote metadata store) — don't
        # register as a filer or aggregate peers
        self.announce = announce
        # announce cadence doubles as the rebalance-telemetry cadence;
        # benches/tests shorten it to speed planner convergence
        self.announce_interval_s = 15.0
        self._grpc_port_arg = grpc_port
        self._grpc_server = None
        self.grpc_port: Optional[int] = None
        self.master_url = master_url
        self.assign_leases = assign_leases
        self.mc = MasterClient(master_url, assign_leases=assign_leases)
        kwargs = {}
        if store == "sqlite":
            kwargs["path"] = (store_dir or ".") + "/filer.db"
        elif store == "lsm":
            kwargs["path"] = (store_dir or ".") + "/filer_lsm"
        elif store == "remote":
            # gateway mode: metadata lives on another filer
            # (filer/remote_store.py); store_dir carries its address
            kwargs["filer_addr"] = store_dir
        elif store in ("redis", "etcd", "mysql", "postgres", "mongodb",
                       "cassandra", "elastic"):
            # store_dir carries the database address "host:port"
            # (reference filer.toml [redis2] address / [etcd] servers /
            # [mysql]/[postgres] hostname+port / [mongodb] uri); a
            # non-address value (e.g. the CLI's default -dir ".")
            # means localhost on the protocol's standard port
            default_port = {"redis": 6379, "etcd": 2379, "mysql": 3306,
                            "postgres": 5432, "mongodb": 27017,
                            "cassandra": 9042, "elastic": 9200}[store]
            addr = store_dir if store_dir and ":" in store_dir \
                else f"127.0.0.1:{default_port}"
            db_host, _, db_port = addr.rpartition(":")
            kwargs["host"] = db_host or "127.0.0.1"
            kwargs["port"] = int(db_port)
        self.filer = Filer(make_store(store, **kwargs),
                           delete_chunks_fn=self._delete_chunks,
                           read_chunk_fn=self._read_chunk,
                           entry_cache=entry_cache)
        # horizontal metadata scale-out: when sharding=True this filer
        # is one member of a consistent-hash ring over DIRECTORIES
        # (filer/shard_ring.py) — it serves only the namespace slices
        # it owns and 307-redirects (or forwards) the rest.  Opt-in:
        # plain multi-filer deployments (meta aggregation, sync)
        # replicate the whole namespace everywhere and must not start
        # bouncing requests just because several filers registered.
        self.sharding = sharding
        self.shard_ring: Optional[ShardRing] = None
        self._ring_pinned = False
        # positive facts about CANONICAL ancestor rows this shard has
        # already ensured on their owners; invalidated by peer meta
        # events so a remote delete re-triggers the ensure walk
        self._remote_parents = EntryCache(capacity=4096, neg_capacity=0)
        # live rebalancing executor: streams one directory's rows to a
        # new owner in the background on master move orders, then the
        # ring flips at commit (filer/rebalance.py)
        from seaweedfs_tpu.filer.rebalance import DirectoryMover
        self.mover = DirectoryMover(self)
        self.filer_conf = FilerConf.load(self.filer.store)
        self._filer_conf_loaded = clockctl.now()
        self._filer_conf_write_lock = threading.Lock()
        from seaweedfs_tpu.filer.remote_mount import RemoteMounts
        self.remote_mounts = RemoteMounts(self.filer)
        self.default_replication = default_replication
        from seaweedfs_tpu.filer.reader_cache import ReaderCache
        from seaweedfs_tpu.utils.chunk_cache import TieredChunkCache
        self.chunk_cache = TieredChunkCache()
        # single-flight + prefetch in front of volume fetches
        # (reference filer/reader_cache.go backing reader_at.go)
        self.reader_cache = ReaderCache(self._fetch_chunk_remote,
                                        self.chunk_cache)
        # reference stats/metrics.go filer subsystem: request counter +
        # latency histogram per handler type
        from seaweedfs_tpu.utils.metrics import Registry
        self.metrics = Registry()
        self._m_req = self.metrics.counter(
            "filer", "request_total", "filer requests", ("type",))
        self._m_lat = self.metrics.histogram(
            "filer", "request_seconds", "filer request latency", ("type",))
        self._m_shard = self.metrics.counter(
            "filer", "shard_route_total", "sharded routing outcomes",
            ("outcome",))
        # parallel_uploads=False keeps the serial per-chunk
        # assign+upload loop as the bench comparator
        self.parallel_uploads = True
        # streaming_ingest=False buffers whole bodies before chunking
        # — the bit-for-bit comparator for the streaming path (same
        # convention as parallel_uploads/qos)
        self.streaming_ingest = True
        # volume_redirect=False proxies every GET through this filer —
        # the bit-identity comparator for the 302 volume-direct path
        # (eligible single-chunk entries answer with a JWT-stamped
        # volume URL instead of relaying the payload)
        self.volume_redirect = True
        # below this size the proxy hop is cheaper than a client
        # round-trip + new connection, and the filer's reader cache /
        # deadline-bounded hedged fetches keep serving the hot small
        # tail — only bulk reads skip the filer
        self.volume_redirect_min = 256 * 1024
        self._upload_pool: Optional[ThreadPoolExecutor] = None
        self._upload_pool_lock = threading.Lock()
        # per-volume-server breakers/latency for hedged chunk fetches
        self.peer_health = PeerHealth(metrics=self.metrics)
        # admission control at the filer edge: class-weighted adaptive
        # concurrency + per-tenant buckets keyed by client IP
        self.qos = QosGovernor(metrics=self.metrics, enabled=qos)
        self.http = HttpServer(host, port)
        self.http.admission_gate = self._admission_gate
        # metrics ride their own listener (reference filer -metricsPort):
        # every path on the main port is user namespace, so a /metrics
        # route there would shadow a stored file of that name
        self.metrics_http = HttpServer(host, 0)
        self.metrics_http.add("GET", "/metrics", self._handle_metrics)
        # tracing: spans are minted on the MAIN port's dispatch, but the
        # flight recorder is served from the metrics listener (the main
        # port is user namespace — /debug/traces there would shadow a
        # stored file of that name, same reason as /metrics above)
        self.tracer = tracing.Tracer(
            node=f"filer@{host}:{port}", enabled=tracing_enabled,
            sample_rate=trace_sample)
        self.http.tracer = self.tracer
        self.metrics_http.tracer = self.tracer
        # cluster telemetry plane: RED histogram at the dispatch edge +
        # hot path/tenant sketches, both served from the metrics
        # listener (main port is user namespace) and merged master-side
        from seaweedfs_tpu.stats.hotkeys import HotKeys
        from seaweedfs_tpu.utils.metrics import RedRecorder
        self.red = RedRecorder(self.metrics, "filer")
        self.http.red = self.red
        # "dir" feeds the master's RebalancePlanner: per-directory
        # temperature rides the announce piggyback (filer/rebalance.py)
        self.hotkeys = HotKeys(dims=("path", "tenant", "dir"))
        self.metrics_http.add("GET", "/admin/hotkeys",
                              self.hotkeys.handler(self.url))
        self.metrics_http.add("GET", "/admin/telemetry",
                              self._handle_telemetry)
        # continuous profiling + per-(class, tenant) ledger; tenant at
        # the filer edge = client IP, matching the governor's buckets.
        # /admin/profile serves from the metrics listener (main port
        # is user namespace), but tagging happens on the MAIN port's
        # dispatch — same split as tracing.
        from seaweedfs_tpu.stats.ledger import ResourceLedger
        self.sampler = profiler.WallSampler(hz=profile_hz)
        self.ledger = ResourceLedger()
        self.http.ledger = self.ledger
        # ledger -> governor feedback: a tenant dominating the window's
        # burn gets a per-tenant rate cap without operator action
        # (stats/autocap.py); ticked from the announce loop
        from seaweedfs_tpu.stats.autocap import LedgerAutoCapper
        self.autocap = LedgerAutoCapper(self.ledger, self.qos)
        self.metrics_http.add("GET", "/admin/profile",
                              profiler.make_profile_handler(
                                  self.sampler, lambda: self.url,
                                  "filer"))
        from seaweedfs_tpu.utils.debug import install_debug_routes
        install_debug_routes(self.metrics_http)
        self._register_routes()

    def start(self) -> None:
        self.http.start()
        self.metrics_http.start()
        self.sampler.start()
        self.tracer.node = f"filer@{self.http.host}:{self.http.port}"
        glog.info("filer server up at %s (store=%s, metrics=%s)",
                  self.url, self.filer.store.name, self.metrics_url)
        if self._grpc_port_arg is not None:
            from seaweedfs_tpu.server.filer_grpc import start_filer_grpc
            self._grpc_server, self.grpc_port = start_filer_grpc(
                self, self.http.host, self._grpc_port_arg)
        # external event publishing when notification.toml enables a
        # backend (reference filer.go NotifyUpdateEvent)
        from seaweedfs_tpu.notification.queue import (attach_to_filer,
                                                      make_queue_from_config)
        self._notify_queue = make_queue_from_config()
        if self._notify_queue is not None:
            attach_to_filer(self.filer, self._notify_queue)
        if not self.announce:
            return
        self._announce_stop = threading.Event()
        threading.Thread(target=self._announce_loop,
                         name="filer-announce", daemon=True).start()
        # merged view of every peer filer's change log (reference
        # filer/meta_aggregator.go; peers from master cluster membership)
        from seaweedfs_tpu.filer.meta_aggregator import MetaAggregator
        self.meta_aggregator = MetaAggregator(
            self.url, self._list_peer_filers, self.filer.meta_log)
        # peer mutations invalidate OUR caches: a remote create/delete
        # must kill any local hot/negative fact about that path
        self.meta_aggregator.listeners.append(self._on_peer_meta_event)
        self.meta_aggregator.start()

    def _list_peer_filers(self) -> list[str]:
        from seaweedfs_tpu.utils.httpd import http_json
        out = http_json(
            "GET", f"http://{self.master_url}/cluster/nodes?type=filer",
            timeout=5)
        return [n["url"] for n in out.get("cluster_nodes", [])]

    def _announce_loop(self) -> None:
        from seaweedfs_tpu.utils.httpd import http_json

        def announce():
            body = {"type": "filer", "url": self.url,
                    "metrics_url": self.metrics_url}
            if self.sharding:
                # temperature piggyback for the master's rebalance
                # planner: cumulative op count (the planner diffs
                # successive reports into a rate) + hottest directories
                body["shard_load"] = {
                    "ops": self.hotkeys.sketches["dir"].total,
                    "dirs": self.hotkeys.top(8).get("dir", [])}
            try:
                http_json("POST",
                          f"http://{self.master_url}/cluster/register",
                          body, timeout=5)
            except Exception as e:
                glog.vlog(1, "filer announce to master %s failed: %s",
                          self.master_url, e)

        announce()
        self._adopt_ring()
        while not self._announce_stop.wait(self.announce_interval_s):
            announce()
            self._adopt_ring()
            self.autocap.maybe_tick()

    # ------------------------------------------------------------------
    # namespace sharding (filer/shard_ring.py)

    def set_shard_ring(self, ring: Optional[ShardRing],
                       pin: bool = False) -> None:
        """Install the filer ring.  pin=True stops the announce loop
        from adopting master-published rings (tests/tools drive the
        topology by hand)."""
        self.shard_ring = ring
        if pin:
            self._ring_pinned = True
        # new epoch, new ownership: every "I already ensured this
        # ancestor on its owner" fact may now point at the wrong shard
        self._remote_parents.clear()

    def _adopt_ring(self) -> None:
        """Pull the master's filer ring; install only forward epochs."""
        if not self.sharding or self._ring_pinned:
            return
        from seaweedfs_tpu.utils.httpd import http_json
        try:
            out = http_json(
                "GET", f"http://{self.master_url}/cluster/filers",
                timeout=5)
            ring = ShardRing.from_dict(out)
        except Exception as e:
            glog.vlog(1, "filer ring pull from master failed: %s", e)
            return
        cur = self.shard_ring
        if cur is None or ring.epoch > cur.epoch:
            self.set_shard_ring(ring)
            glog.info("filer %s adopted ring epoch %d (%d members)",
                      self.url, ring.epoch, len(ring))

    def _shard_active(self) -> bool:
        # a member not (yet) in the ring serves everything locally —
        # redirecting by a ring that excludes us would bounce forever
        ring = self.shard_ring
        return (self.sharding and ring is not None and len(ring) > 1
                and self.url in ring)

    def _shard_redirect(self, req: Request,
                        path: str) -> Optional[Response]:
        """None when this shard should serve `path`; otherwise the
        response that moves the request to the owner.

        GET/HEAD/PUT/POST are 307-redirected (bodies are streamed, so
        the filer can't replay them to a peer); DELETE is forwarded
        in-place so dumb clients still work.  Redirects carry
        ``X-Weed-Shard: <epoch>:<owner>`` so shard-aware clients
        (wdclient.filer_call) detect ring drift and re-resolve.  The
        ``X-Weed-Shard-Forwarded`` loop guard forces local service:
        during an epoch change two shards may briefly disagree about
        ownership, and serving the forwarder's view beats bouncing."""
        if not self._shard_active():
            return None
        ring = self.shard_ring
        owner = ring.owner_for_path(path)
        if not owner or owner == self.url:
            self._m_shard.inc("local")
            return None
        if req.headers.get(weed_headers.SHARD_FORWARDED):
            self._m_shard.inc("forced_local")
            return None
        from urllib.parse import quote, urlencode
        qs = urlencode(req.query)
        loc = f"http://{owner}{quote(path)}" + (f"?{qs}" if qs else "")
        hdr = format_shard_header(ring.epoch, owner)
        if req.method == "DELETE":
            self._m_shard.inc("forward")
            status, body, hdrs = http_call(
                "DELETE", loc,
                headers={weed_headers.SHARD_FORWARDED: "1"}, timeout=60)
            return Response(
                body, status=status,
                content_type=hdrs.get("Content-Type")
                or "application/json",
                headers={weed_headers.SHARD: hdr})
        self._m_shard.inc("redirect")
        return Response(
            {"error": "wrong shard", "owner": owner,
             "ring_epoch": ring.epoch},
            status=307,
            headers={weed_headers.SHARD: hdr, "Location": loc})

    def _on_peer_meta_event(self, peer: str, ev: dict) -> None:
        """MetaAggregator listener: a peer's mutation invalidates our
        hot/negative entries AND our remote-parent facts for the
        touched paths (a peer deleting a directory we 'ensured' means
        the next local create must re-run the ensure walk)."""
        cache = self.filer.entry_cache
        for d in (ev.get("old_entry"), ev.get("new_entry")):
            if not d:
                continue
            p = d.get("full_path", "")
            if not p:
                continue
            if cache is not None:
                cache.invalidate(p)
            self._remote_parents.invalidate(p)

    def _ensure_parents_remote(self, dir_path: str) -> None:
        """After a local create: make sure every ancestor directory's
        CANONICAL row exists on the shard owning its parent, else the
        new subtree is invisible to listings walking down from the
        root.  Positive facts are cached (_remote_parents, invalidated
        by peer meta events), so the warm-path cost is one dict hit.
        Failures are logged, not raised — the entry itself is durable,
        and the next write under the same directory retries."""
        if not self._shard_active():
            return
        from urllib.parse import quote
        ring = self.shard_ring
        d = dir_path if dir_path.startswith("/") else "/" + dir_path
        try:
            while d and d != "/":
                cached, fact = self._remote_parents.get(d)
                if cached and fact is not None:
                    # inductively, everything above was ensured too
                    break
                token = self._remote_parents.begin(d)
                owner = ring.owner_for_path(d)
                if owner == self.url:
                    if self.filer.find_entry(d) is None:
                        self.filer.mkdirs(d)
                else:
                    status, body, _ = http_call(
                        "POST", f"http://{owner}{quote(d)}?mkdir=true",
                        headers={weed_headers.SHARD_FORWARDED: "1"},
                        timeout=30)
                    if status >= 400:
                        raise HttpError(status, body)
                self._remote_parents.put(d, {"full_path": d}, token)
                d = parent_dir(d)
        except Exception as e:
            glog.warning("ensure-parents for %s failed: %s", dir_path, e)

    def _list_entries_routed(self, dir_path: str, start_name: str = "",
                             limit: int = 1024) -> list[Entry]:
        """Listing of dir_path from the shard that owns it (children
        rows live on owner(dir), so a listing is always single-shard);
        local when unsharded or self-owned."""
        if self._shard_active():
            owner = self.shard_ring.owner(dir_path)
            if owner and owner != self.url:
                from urllib.parse import urlencode
                qs = urlencode({"dir": dir_path, "start": start_name,
                                "limit": limit, "resolved": "true"})
                status, body, _ = http_call(
                    "GET", f"http://{owner}/__api/list?{qs}",
                    headers={weed_headers.SHARD_FORWARDED: "1"},
                    timeout=30)
                if status != 200:
                    raise HttpError(status, body)
                return [Entry.from_dict(d)
                        for d in json.loads(body).get("entries", [])]
        return self.filer.list_entries(dir_path, start_name=start_name,
                                       limit=limit)

    def _delete_entry_sharded(self, path: str, recursive: bool) -> None:
        """Recursive delete across shards: the canonical children of
        `path` live on owner(path); each child's delete is routed to
        ITS row's owner (which recurses the same way).  The final
        local sweep removes this shard's canonical row plus any
        skeleton remnants beneath it — those are directories only, so
        chunk GC is untouched."""
        entry = self.filer.find_entry(path)
        if entry is None:
            # creates racing a concurrent sweep can strand child rows
            # beneath a directory row the sweep already removed (a
            # peer's stale positive parent-cache skips re-creating the
            # ancestor row): clear them anyway, so a repeat recursive
            # delete converges to empty instead of 404-ing past the
            # orphans forever
            if recursive:
                self._sweep_children(path, True)
            raise FileNotFoundError(path)
        if entry.is_directory:
            self._sweep_children(path, recursive)
        self.filer.delete_entry(path, recursive=True)

    def _sweep_children(self, path: str, recursive: bool) -> None:
        """Delete every canonical child of `path`, each routed to its
        row's owner, until a listing comes back empty."""
        from urllib.parse import quote
        child_owner = self.shard_ring.owner(path)
        while True:
            children = self._list_entries_routed(path, limit=256)
            if not children:
                return
            if not recursive:
                raise OSError(f"directory {path} not empty")
            for child in children:
                if child_owner == self.url:
                    try:
                        self._delete_entry_sharded(child.full_path, True)
                    except FileNotFoundError:
                        pass  # raced another deleter: already gone
                else:
                    status, body, _ = http_call(
                        "DELETE",
                        f"http://{child_owner}"
                        f"{quote(child.full_path)}?recursive=true",
                        headers={weed_headers.SHARD_FORWARDED: "1"},
                        timeout=60)
                    if status >= 400 and status != 404:
                        raise HttpError(status, body)

    def _rename_sharded(self, frm: str, to: str) -> None:
        """Cross-shard rename: children first (a reader never sees the
        new tree without its leaves), then the row itself moves — a
        meta-only insert at the destination's owner (chunks ride
        along verbatim) followed by a LOCAL row delete without chunk
        GC.  Runs on owner(parent(frm)), i.e. where frm's row lives."""
        entry = self.filer.find_entry(frm)
        if entry is None:
            raise FileNotFoundError(frm)
        ring = self.shard_ring
        if entry.is_directory:
            child_owner = ring.owner(frm)
            children = self._list_entries_routed(frm, limit=1 << 20)
            for child in children:
                c_to = to + child.full_path[len(frm):]
                if child_owner == self.url:
                    self._rename_sharded(child.full_path, c_to)
                else:
                    status, body, _ = http_call(
                        "POST", f"http://{child_owner}/__api/rename",
                        json_body={"from": child.full_path, "to": c_to},
                        headers={weed_headers.SHARD_FORWARDED: "1"},
                        timeout=60)
                    if status >= 400:
                        raise HttpError(status, body)
        row = entry.to_dict()
        row["full_path"] = to
        self._ensure_parents_remote(parent_dir(to))
        to_owner = ring.owner_for_path(to)
        if to_owner == self.url:
            self.filer.mkdirs(parent_dir(to))
            old = self.filer.store.inner.find_entry(to)
            self.filer.store.inner.insert_entry(Entry.from_dict(row))
            self.filer._notify(parent_dir(to),
                               old.to_dict() if old else None, row)
        else:
            status, body, _ = http_call(
                "POST", f"http://{to_owner}/__api/entry",
                json_body={"entry": row, "meta_only": True},
                headers={weed_headers.SHARD_FORWARDED: "1"}, timeout=60)
            if status >= 400:
                raise HttpError(status, body)
        # drop the source ROW only — its chunks now belong to `to`
        self.filer.store.inner.delete_entry(frm)
        self.filer._notify(parent_dir(frm), entry.to_dict(), None)

    def _shard_status(self) -> dict:
        ring = self.shard_ring
        out = {
            "url": self.url,
            "sharding": self.sharding,
            "active": self._shard_active(),
            "ring": ring.to_dict() if ring is not None else None,
            "routing": {k[0]: v
                        for k, v in self._m_shard._values.items()},
            "remote_parents": self._remote_parents.snapshot(),
            "autocap": self.autocap.snapshot(),
            "mover": self.mover.status(),
        }
        if self.filer.entry_cache is not None:
            out["entry_cache"] = self.filer.entry_cache.snapshot()
        return out

    def _api_shard_status(self, req: Request) -> Response:
        return Response(self._shard_status())

    def _api_shard_ring_set(self, req: Request) -> Response:
        b = req.json()
        ring = ShardRing.from_dict(b)
        self.set_shard_ring(ring, pin=bool(b.get("pin")))
        return Response({"epoch": ring.epoch, "members": len(ring)})

    def _api_shard_migrate(self, req: Request) -> Response:
        """Master move order: migrate `dir`'s child rows to filer `to`
        in the background (filer/rebalance.py DirectoryMover).  Only
        the current owner may execute — rows move FROM here."""
        b = req.json() or {}
        directory, dest = b.get("dir", ""), b.get("to", "")
        if not directory or not dest:
            return Response({"error": "dir and to required"}, status=400)
        ring = self.shard_ring
        if not self._shard_active() or dest not in ring:
            return Response({"error": "not an active shard member"},
                            status=409)
        if ring.owner(directory) != self.url:
            return Response({"error": "not the owner",
                             "owner": ring.owner(directory)}, status=409)
        started = self.mover.start(directory, dest)
        return Response({"started": started,
                         "status": self.mover.status()})

    def stop(self) -> None:
        self.sampler.stop()
        if hasattr(self, "_announce_stop"):
            self._announce_stop.set()
        if hasattr(self, "meta_aggregator"):
            self.meta_aggregator.stop()
        if self._grpc_server is not None:
            self._grpc_server.stop(0)
        self.http.stop()
        self.metrics_http.stop()
        self.metrics.stop_push()
        # only after the HTTP plane is down: in-flight mutations must
        # not hit a closed notification socket
        if getattr(self, "_notify_queue", None) is not None:
            self._notify_queue.close()
        if self._upload_pool is not None:
            self._upload_pool.shutdown(wait=False)
        self.reader_cache.close()
        self.filer.close()

    @property
    def url(self) -> str:
        return f"{self.http.host}:{self.http.port}"

    @property
    def metrics_url(self) -> str:
        return f"{self.metrics_http.host}:{self.metrics_http.port}"

    # ---- chunk GC ----
    def _delete_chunks(self, fids: list[str]) -> None:
        def work():
            # GC is background traffic: volume servers may shed it
            # under load and the next pass will retry
            with class_scope(BACKGROUND):
                for fid in fids:
                    try:
                        operation.delete_file(self.mc, fid)
                    except Exception as e:
                        glog.warning("chunk gc: delete %s failed: %s",
                                     fid, e)
        threading.Thread(target=work, name="chunk-gc",
                         daemon=True).start()

    # ---- routes ----
    def _register_routes(self) -> None:
        r = self.http.add
        r("GET", "/__api/qos", self._api_qos)
        r("POST", "/__api/qos", self._api_qos_configure)
        r("POST", "/__api/rename", self._api_rename)
        r("POST", "/__api/entry", self._api_put_entry)
        r("GET", "/__api/entry", self._api_get_entry)
        r("DELETE", "/__api/entry", self._api_delete_entry_row)
        r("GET", "/__api/list", self._api_list_entries)
        r("GET", "/__api/kv", self._api_kv_get)
        r("POST", "/__api/kv", self._api_kv_put)
        r("POST", "/__api/hardlink", self._api_hardlink)
        r("GET", "/__api/filer_conf", self._api_filer_conf_get)
        r("POST", "/__api/filer_conf", self._api_filer_conf_set)
        r("GET", "/__api/meta_events", self._api_meta_events)
        r("GET", "/__api/shard/status", self._api_shard_status)
        r("POST", "/__api/shard/ring", self._api_shard_ring_set)
        r("POST", "/__api/shard/migrate", self._api_shard_migrate)
        r("GET", r"/__api/chunk/(\S+)", self._api_chunk_blob)
        r("GET", "/__api/remote/status", self._api_remote_status)
        r("POST", "/__api/remote/configure", self._api_remote_configure)
        r("POST", "/__api/remote/mount", self._api_remote_mount)
        r("POST", "/__api/remote/mount_buckets",
          self._api_remote_mount_buckets)
        r("POST", "/__api/remote/unmount", self._api_remote_unmount)
        r("POST", "/__api/remote/pull", self._api_remote_pull)
        r("POST", "/__api/remote/cache", self._api_remote_cache)
        r("POST", "/__api/remote/uncache", self._api_remote_uncache)
        r("POST", "/__api/remote/writeback", self._api_remote_writeback)
        r("POST", "/__api/remote/rm", self._api_remote_rm)
        for method in ("POST", "PUT"):
            r(method, "/.*", self._timed(
                "write", self._signed(self._handle_write)))
        r("GET", "/.*", self._timed("read", self._handle_read))
        r("HEAD", "/.*", self._timed("head", self._handle_read))
        r("DELETE", "/.*", self._timed(
            "delete", self._signed(self._handle_delete)))

    def _handle_metrics(self, req: Request) -> Response:
        return Response(self.metrics.expose_text(),
                        content_type="text/plain; version=0.0.4")

    def telemetry_snapshot(self) -> dict:
        snap = {"node": self.url, "server": "filer",
                "red": self.red.snapshot(),
                "hotkeys": self.hotkeys.snapshot(),
                "ledger": self.ledger.snapshot(),
                "autocap": self.autocap.snapshot()}
        if self.filer.entry_cache is not None:
            snap["entry_cache"] = self.filer.entry_cache.snapshot()
        if self.shard_ring is not None:
            snap["shard"] = self._shard_status()
        return snap

    def _handle_telemetry(self, req: Request) -> Response:
        return Response(self.telemetry_snapshot())

    # ---- QoS admission ----
    # exempt: the operator's escape hatch plus long-polls, whose
    # held-open slots would both exhaust the limit and poison the
    # adaptive limiter's latency estimate with 30s samples
    QOS_EXEMPT = ("/__api/qos", "/__api/meta_events", "/__api/shard")

    def _admission_gate(self, method, path, headers, client):
        if not self.qos.enabled:
            return None
        for prefix in self.QOS_EXEMPT:
            if path.startswith(prefix):
                return None
        cls = from_headers(headers) or classify(method, path)
        grant = self.qos.admit(cls, tenant=client)
        if not grant.ok:
            self._m_req.inc("qos_shed")
            return Response(
                {"error": "overloaded", "class": cls,
                 "reason": grant.reason},
                status=503,
                headers={"Retry-After": f"{grant.retry_after:.2f}"})
        return grant.release

    def _api_qos(self, req: Request) -> Response:
        return Response({"url": self.url, **self.qos.snapshot()})

    def _api_qos_configure(self, req: Request) -> Response:
        return Response({"url": self.url,
                         **self.qos.configure(**(req.json() or {}))})

    def _timed(self, kind: str, handler):
        def wrapped(req: Request) -> Response:
            self._m_req.inc(kind)
            # hot-key sketches: which paths are hammered and by whom
            # (tenant = client IP, the same key the QoS buckets use)
            self.hotkeys.record("path", req.path.rstrip("/") or "/")
            # the dir sketch is CLIENT temperature — the rebalance
            # planner's input.  Forwarded requests are internal
            # plumbing (peer parent-ensures, mover pushes); counting
            # them would mark namespace-interior directories hot and
            # invite the planner to migrate them
            if not req.headers.get(weed_headers.SHARD_FORWARDED):
                self.hotkeys.record("dir",
                                    parent_dir(req.path.rstrip("/")
                                               or "/"))
            h = getattr(req, "handler", None)
            if h is not None:
                self.hotkeys.record("tenant", h.client_address[0])
            with self._m_lat.time(kind):
                return handler(req)
        return wrapped

    def _signed(self, handler):
        """A replicator identifies its writes with
        X-Weed-Sync-Signature so the reverse sync direction can exclude
        them from the event stream (reference filer.sync signatures)."""
        def wrapped(req: Request) -> Response:
            sig = req.headers.get(weed_headers.SYNC_SIGNATURE)
            if not sig:
                return handler(req)
            try:
                self.filer.set_signature(int(sig))
            except ValueError:
                return handler(req)
            try:
                return handler(req)
            finally:
                self.filer.set_signature(0)
        return wrapped

    # ---- write ----
    def _handle_write(self, req: Request) -> Response:
        path = req.path.rstrip("/") or "/"
        misroute = self._shard_redirect(req, path)
        if misroute is not None:
            return misroute
        if req.query.get("mkdir") == "true":
            self.filer.mkdirs(path)
            self._ensure_parents_remote(path)
            return Response({"path": path}, status=201)
        # per-path rules from filer.conf fill in what the request omits
        rule = self._current_filer_conf().match_storage_rule(path)
        if rule.read_only:
            return Response({"error": f"{rule.location_prefix} is read-only"},
                            status=403)
        collection = req.query.get("collection", "") or rule.collection
        replication = (req.query.get("replication", "")
                       or rule.replication or self.default_replication)
        ttl = req.query.get("ttl", "") or rule.ttl
        mime = (req.headers.get("Content-Type")
                or "application/octet-stream")
        content, chunks, size = self._ingest_body(
            req, collection, replication, ttl, disk_type=rule.disk_type)
        now = clockctl.now()
        entry = Entry(full_path=path,
                      attr=Attr(mtime=now, crtime=now, mime=mime,
                                file_size=size,
                                collection=collection,
                                ttl_sec=_ttl_seconds(ttl),
                                replication=replication))
        entry.content = content
        entry.chunks = chunks
        try:
            self.filer.create_entry(entry)
        except IsADirectoryError:
            # the chunks just uploaded have no owning entry: GC them
            self._delete_chunks([c.fid for c in chunks])
            return Response({"error": "is a directory"}, status=409)
        # make the new subtree reachable from listings on other shards
        self._ensure_parents_remote(entry.dir_path)
        return Response({"name": entry.name, "size": size}, status=201)

    def _ingest_body(self, req: Request, collection: str,
                     replication: str, ttl: str = "",
                     disk_type: str = "", hasher=None
                     ) -> tuple[bytes, list[FileChunk], int]:
        """Consume one request body into ``(inline_content, chunks,
        size)`` — the single ingest point the filer PUT, S3 PUT/part,
        and WebDAV PUT all ride. With a live ``req.stream`` (and
        streaming_ingest on) the body is chunked AS IT ARRIVES under
        the STREAM_INFLIGHT buffer cap; otherwise the buffered
        comparator path. ``hasher`` (e.g. hashlib.md5) is fed every
        body byte in order — the S3 ETag without a second pass."""
        stream = getattr(req, "stream", None)
        if stream is None or not self.streaming_ingest:
            data = req.body
            if hasher is not None:
                hasher.update(data)
            if len(data) <= INLINE_LIMIT and not self.cipher:
                return data, [], len(data)
            return b"", self._upload_chunks(
                data, collection, replication, ttl,
                disk_type=disk_type), len(data)
        head = _read_full(stream, INLINE_LIMIT + 1)
        if hasher is not None:
            hasher.update(head)
        if len(head) <= INLINE_LIMIT and not self.cipher:
            return head, [], len(head)
        chunks, size = self._stream_chunks(head, stream, collection,
                                           replication, ttl, disk_type,
                                           hasher=hasher)
        return b"", chunks, size

    def _get_upload_pool(self) -> ThreadPoolExecutor:
        if self._upload_pool is None:
            with self._upload_pool_lock:
                if self._upload_pool is None:
                    self._upload_pool = ThreadPoolExecutor(
                        max_workers=UPLOAD_WORKERS,
                        thread_name_prefix="chunk-upload")
        return self._upload_pool

    def _upload_chunks(self, data: bytes, collection: str,
                       replication: str, ttl: str = "",
                       disk_type: str = "") -> list[FileChunk]:
        """Split into CHUNK_SIZE pieces, assign + upload each
        (reference filer_server_handlers_write_upload.go:32-140). Wide
        chunk lists collapse into manifest chunks (filechunk_manifest.go).
        disk_type routes the assigns to that storage tier (per-path
        filer.conf rule, reference -disk).

        Multi-chunk uploads run concurrently: fids are minted in
        batches (master assign count=N), the pieces go through the
        shared bounded pool, and the chunk list is assembled by index
        so offsets/ordering are identical to the serial loop. On the
        first error the remaining uploads are cancelled, every chunk
        that already landed is deleted (no orphans), and the error
        propagates. The S3 gateway PUT/multipart and WebDAV paths ride
        this same code."""
        offsets = list(range(0, len(data), CHUNK_SIZE))
        save_one = lambda blob: self._save_chunk(  # noqa: E731
            blob, 0, collection, replication, ttl, disk_type)
        if len(offsets) <= 1 or not self.parallel_uploads:
            chunks = [self._save_chunk(data[off:off + CHUNK_SIZE], off,
                                       collection, replication, ttl,
                                       disk_type)
                      for off in offsets]
            return maybe_manifestize(save_one, chunks)
        assigns = self.mc.assign_many(len(offsets), collection=collection,
                                      replication=replication, ttl=ttl,
                                      disk=disk_type)
        if assigns and assigns[0].get("error"):
            raise HttpError(500, assigns[0]["error"].encode())
        if len(assigns) < len(offsets) or any(a.get("error")
                                              for a in assigns):
            # partial batch (JWT-mode flip mid-call or master error
            # tail): the serial path handles its own assigns fine
            chunks = [self._save_chunk(data[off:off + CHUNK_SIZE], off,
                                       collection, replication, ttl,
                                       disk_type)
                      for off in offsets]
            return maybe_manifestize(save_one, chunks)
        pool = self._get_upload_pool()
        chunks: list[Optional[FileChunk]] = [None] * len(offsets)
        # contextvars don't cross the pool: capture the request's QoS
        # class AND trace span here and re-enter both in each worker so
        # the chunk PUTs carry the same X-Weed-Class / X-Weed-Trace as
        # their parent (the deadline header rides the same pattern via
        # Deadline propagation)
        upload_cls = current_class()
        upload_span = tracing.current_span()
        if upload_span is not None:
            upload_span.annotate("chunks.fanout", len(offsets))

        def upload_in_class(a, piece, off):
            with class_scope(upload_cls), tracing.span_scope(upload_span):
                return self._upload_one_chunk(a, piece, off)

        futures = {
            pool.submit(upload_in_class, assigns[i],
                        data[off:off + CHUNK_SIZE], off): i
            for i, off in enumerate(offsets)}
        first_err: Optional[Exception] = None
        for fut in as_completed(futures):
            try:
                chunks[futures[fut]] = fut.result()
            except CancelledError:
                pass
            except Exception as e:
                if first_err is None:
                    first_err = e
                    for g in futures:
                        g.cancel()
        if first_err is not None:
            # as_completed drained every future, so `chunks` now holds
            # exactly the uploads that landed — GC them
            self._delete_chunks([c.fid for c in chunks if c is not None])
            if isinstance(first_err, HttpError):
                raise first_err
            raise HttpError(500, f"chunk upload failed: "
                                 f"{first_err}".encode())
        return maybe_manifestize(save_one, chunks)

    def _stream_chunks(self, prefix: bytes, stream, collection: str,
                       replication: str, ttl: str = "",
                       disk_type: str = "", hasher=None
                       ) -> tuple[list[FileChunk], int]:
        """Bounded-memory streaming twin of _upload_chunks: chunk i+1
        fills from the socket while chunks i and i-1 upload through
        the shared pool — at most STREAM_INFLIGHT uploads in flight,
        so peak body memory is ~3 chunk buffers for a 5GB PUT and a
        5KB one alike. fids are pre-minted in STREAM_ASSIGN_WAVE
        batches (total chunk count is unknown until EOF). Chunk
        boundaries are the same CHUNK_SIZE grid as the buffered path,
        so the stored object is bit-identical. On the first upload
        error OR a client disconnect mid-stream, outstanding uploads
        are cancelled, every chunk that already landed is deleted (no
        orphans), and the error propagates."""
        save_one = lambda blob: self._save_chunk(  # noqa: E731
            blob, 0, collection, replication, ttl, disk_type)
        upload_cls = current_class()
        upload_span = tracing.current_span()

        def upload_in_class(a, piece, off):
            with class_scope(upload_cls), tracing.span_scope(upload_span):
                return self._upload_one_chunk(a, piece, off)

        def next_piece(lead: bytes) -> bytes:
            want = CHUNK_SIZE - len(lead)
            more = _read_full(stream, want) if want > 0 else b""
            if hasher is not None and more:
                hasher.update(more)
            return (lead + more) if lead else more

        assigns: list[dict] = []

        def next_assign() -> dict:
            if not assigns:
                wave = self.mc.assign_many(
                    STREAM_ASSIGN_WAVE, collection=collection,
                    replication=replication, ttl=ttl, disk=disk_type)
                assigns.extend(a for a in wave if not a.get("error"))
            if assigns:
                return assigns.pop(0)
            # batch minting degraded (JWT-mode flip, master error
            # tail): fall back to a single assign, which raises its
            # own error if the master really is down
            a = self.mc.assign(collection=collection,
                               replication=replication, ttl=ttl,
                               disk=disk_type)
            if a.get("error"):
                raise HttpError(500, a["error"].encode())
            return a

        pool = self._get_upload_pool() if self.parallel_uploads else None
        chunks: list[Optional[FileChunk]] = []
        futures: dict = {}  # future -> chunk index
        first_err: Optional[Exception] = None
        size = 0

        def harvest(done) -> None:
            nonlocal first_err
            for fut in done:
                i = futures.pop(fut)
                try:
                    chunks[i] = fut.result()
                except CancelledError:
                    pass
                except Exception as e:
                    if first_err is None:
                        first_err = e

        try:
            piece = next_piece(prefix)
            while piece and first_err is None:
                off = size
                size += len(piece)
                if pool is None:
                    chunks.append(self._save_chunk(
                        piece, off, collection, replication, ttl,
                        disk_type))
                else:
                    chunks.append(None)
                    futures[pool.submit(upload_in_class, next_assign(),
                                        piece, off)] = len(chunks) - 1
                    while len(futures) >= STREAM_INFLIGHT:
                        done, _ = wait(list(futures),
                                       return_when=FIRST_COMPLETED)
                        harvest(done)
                        if first_err is not None:
                            break
                if first_err is not None:
                    break
                piece = next_piece(b"")
        except Exception as e:
            # the socket died mid-stream (client disconnect, lying
            # Content-Length) or a serial upload failed
            if first_err is None:
                first_err = e
        if first_err is not None:
            for fut in futures:
                fut.cancel()
        if futures:
            # normal EOF: the last ≤STREAM_INFLIGHT uploads are still
            # in flight — wait them out (cancel only on error above)
            wait(list(futures))
            harvest(list(futures))
        if first_err is not None:
            self._delete_chunks([c.fid for c in chunks if c is not None])
            if isinstance(first_err, (HttpError, ConnectionError)):
                raise first_err
            raise HttpError(500, f"chunk upload failed: "
                                 f"{first_err}".encode())
        return maybe_manifestize(save_one, chunks), size

    def _upload_one_chunk(self, a: dict, piece: bytes,
                          offset: int) -> FileChunk:
        """Encrypt (when enabled) + upload one piece against an
        already-minted assignment."""
        key = b""
        if self.cipher:
            from seaweedfs_tpu.utils import cipher as _cipher
            blob, key = _cipher.encrypt(piece)
        else:
            blob = piece
        operation.upload_to(a["fid"], a["url"], blob,
                            auth=a.get("auth", ""))
        return FileChunk(fid=a["fid"], offset=offset, size=len(piece),
                         cipher_key=key, mtime_ns=time.time_ns())

    def _save_chunk(self, piece: bytes, offset: int, collection: str,
                    replication: str, ttl: str = "",
                    disk_type: str = "") -> FileChunk:
        a = self.mc.assign(collection=collection, replication=replication,
                           ttl=ttl, disk=disk_type)
        if a.get("error"):
            raise HttpError(500, a["error"].encode())
        return self._upload_one_chunk(a, piece, offset)

    # ---- read ----
    def _handle_read(self, req: Request) -> Response:
        path = req.path.rstrip("/") or "/"
        misroute = self._shard_redirect(req, path)
        if misroute is not None:
            return misroute
        entry = self.filer.find_entry(path)
        if entry is None:
            return Response({"error": "not found"}, status=404)
        if entry.is_directory:
            limit = int(req.query.get("limit", 1024))
            last = req.query.get("lastFileName", "")
            entries = self._list_entries_routed(path, start_name=last,
                                                limit=limit)
            return Response({
                "Path": path,
                "Entries": [self._entry_json(e) for e in entries],
                "ShouldDisplayLoadMore": len(entries) == limit,
            })
        # zero-copy read plane: an eligible single-chunk entry's
        # payload never relays through this filer — the client is
        # pointed straight at a volume replica (which serves it via
        # sendfile). ?proxy=1 forces the relay (comparator/debug).
        if req.method == "GET" and self.volume_redirect \
                and req.query.get("proxy") != "1":
            loc = self.volume_direct_url(entry)
            if loc is not None:
                self._m_req.inc("read_redirect")
                return Response(b"", status=302,
                                content_type="text/plain",
                                headers={"Location": loc})
        mime = entry.attr.mime or "application/octet-stream"
        headers = {"Content-Disposition":
                   f'inline; filename="{entry.name}"'}
        # edge deadline: honors an inbound X-Weed-Deadline (propagated
        # budget) or mints the default; every chunk fetch below inherits
        # the remaining time instead of its own full 30s
        with deadline_scope(Deadline.from_headers(req.headers,
                                                  default=READ_DEADLINE_S)):
            if req.method == "GET" and req.headers.get("Range"):
                total = entry.file_size()
                try:
                    rng = parse_byte_range(req.headers["Range"], total)
                except RangeNotSatisfiable:
                    headers["Content-Range"] = f"bytes */{total}"
                    return Response(b"", status=416, content_type=mime,
                                    headers=headers)
                if rng is not None:
                    lo, hi = rng
                    piece = self._read_entry_range(entry, lo,
                                                   hi - lo + 1)
                    headers["Content-Range"] = \
                        f"bytes {lo}-{hi}/{total}"
                    return Response(piece, status=206,
                                    content_type=mime, headers=headers)
            data = self._read_entry_bytes(entry)
        return Response(data, content_type=mime, headers=headers)

    def volume_direct_url(self, entry: Entry) -> Optional[str]:
        """The JWT-stamped volume URL an entry's payload can be GET
        directly from, or None when the read must proxy. Eligibility —
        the payload must be ONE plaintext stored chunk that IS the
        whole file: no inline content, exactly one chunk covering
        [0, file_size), no per-chunk cipher key, no manifest
        indirection, no remote mount, and at least volume_redirect_min
        bytes (smaller reads stay on the proxy where the reader cache
        and deadline-bounded hedged fetches serve the hot tail). The
        replica choice follows this filer's learned peer health, and a
        failed lookup falls back to the proxy path rather than
        redirecting into the void."""
        if entry.content or entry.remote or not entry.chunks:
            return None
        if entry.file_size() < self.volume_redirect_min:
            return None
        if len(entry.chunks) != 1 or has_chunk_manifest(entry.chunks):
            return None
        c = entry.chunks[0]
        if c.cipher_key or c.offset != 0 \
                or c.size != entry.file_size():
            return None
        try:
            vid = int(c.fid.split(",")[0])
            peers = [l["url"] for l in self.mc.lookup_volume(vid)]
        except Exception:
            return None
        if not peers:
            return None
        peer = self.peer_health.rank(peers)[0]
        jwt = self._read_jwt_for(c.fid)
        return f"http://{peer}/{c.fid}" + (f"?jwt={jwt}" if jwt else "")

    def _read_jwt_for(self, fid: str) -> str:
        """Sign a read token with the shared jwt.signing.read key when
        configured (reference security.toml; volume servers verify)."""
        if not hasattr(self, "_jwt_read_key"):
            from seaweedfs_tpu.utils import config as _cfg
            conf = _cfg.load_configuration("security")
            self._jwt_read_key = _cfg.get(conf, "jwt.signing.read.key",
                                          "") or ""
        if not self._jwt_read_key:
            return ""
        from seaweedfs_tpu.utils.security import gen_jwt
        return gen_jwt(self._jwt_read_key, fid)

    def _fetch_chunk_remote(self, fid: str) -> bytes:
        """One real network fetch of a chunk's stored bytes (the
        ReaderCache guarantees a single flight per fid).

        Replica holders are breaker-ranked (learned per-peer health
        fronts the fastest live server) and straggler-hedged: if the
        first pick stalls past the adaptive hedge delay, a backup
        fetch races it on the next-ranked peer — same machinery the
        volume servers use for degraded EC reads."""
        jwt = self._read_jwt_for(fid)
        dl = current_deadline() or Deadline.after(READ_DEADLINE_S)
        vid = int(fid.split(",")[0])
        peers = [l["url"] for l in self.mc.lookup_volume(vid)]

        def fetch(peer: str) -> Optional[bytes]:
            target = (f"http://{peer}/{fid}"
                      + (f"?jwt={jwt}" if jwt else ""))
            status, body, _ = http_call("GET", target, deadline=dl)
            return body if status == 200 else None

        out = hedged(fetch, self.peer_health.rank(peers),
                     health=self.peer_health, deadline=dl)
        if out is None:
            # the holder set may have changed (moved/grown volume):
            # don't let a stale lookup cache pin the failure
            self.mc.invalidate(vid)
            raise HttpError(500, f"chunk {fid} unreachable".encode())
        return out

    def _read_chunk_blob(self, fid: str) -> bytes:
        """Raw stored bytes of a chunk (ciphertext when encrypted);
        cached as stored, fetched single-flight."""
        return self.reader_cache.get(fid)

    def _read_chunk(self, chunk: FileChunk) -> bytes:
        """Plaintext bytes of a chunk (decrypts with the per-chunk key
        from the metadata — reference util/cipher.go Decrypt)."""
        blob = self._read_chunk_blob(chunk.fid)
        if chunk.cipher_key:
            from seaweedfs_tpu.utils import cipher as _cipher
            blob = _cipher.decrypt(blob, chunk.cipher_key)
        return blob

    def _read_entry_bytes(self, entry: Entry) -> bytes:
        if not entry.content and not entry.chunks and entry.remote:
            # remote-mounted, not cached locally: read through
            # (reference filer/read_remote.go)
            return self.remote_mounts.read_through(entry)
        if entry.content or not entry.chunks:
            return entry.content
        chunks = entry.chunks
        if has_chunk_manifest(chunks):
            chunks = resolve_chunk_manifest(self._read_chunk, chunks)
        size = entry.file_size()
        visibles = non_overlapping_visible_intervals(chunks)
        views = view_from_visibles(visibles, 0, size)
        chunk_by_fid = {c.fid: c for c in chunks}
        out = bytearray(size)
        for view in views:
            blob = self._read_chunk(chunk_by_fid[view.fid])
            piece = blob[view.offset_in_chunk:
                         view.offset_in_chunk + view.size]
            out[view.logic_offset:view.logic_offset + view.size] = piece
        return bytes(out)

    def _read_entry_range(self, entry: Entry, lo: int,
                          length: int) -> bytes:
        """``entry`` bytes [lo, lo+length) fetching ONLY the chunks
        that overlap the window — a Range GET of one 4MB chunk out of
        a multi-GB file costs one chunk fetch, not an assembly of the
        whole object."""
        if length <= 0:
            return b""
        if not entry.content and not entry.chunks and entry.remote:
            return self.remote_mounts.read_through(entry)[lo:lo + length]
        if entry.content or not entry.chunks:
            return entry.content[lo:lo + length]
        chunks = entry.chunks
        if has_chunk_manifest(chunks):
            chunks = resolve_chunk_manifest(self._read_chunk, chunks)
        visibles = non_overlapping_visible_intervals(chunks)
        views = view_from_visibles(visibles, lo, length)
        chunk_by_fid = {c.fid: c for c in chunks}
        out = bytearray(length)
        for view in views:
            blob = self._read_chunk(chunk_by_fid[view.fid])
            piece = blob[view.offset_in_chunk:
                         view.offset_in_chunk + view.size]
            out[view.logic_offset - lo:
                view.logic_offset - lo + view.size] = piece
        return bytes(out)

    @staticmethod
    def _entry_json(e: Entry) -> dict:
        return {
            "FullPath": e.full_path,
            "Mtime": e.attr.mtime,
            "Crtime": e.attr.crtime,
            "Mode": e.attr.mode,
            "Mime": e.attr.mime,
            "IsDirectory": e.is_directory,
            "FileSize": e.file_size(),
            "chunks": [c.to_dict() for c in e.chunks],
        }

    # ---- delete ----
    FILER_CONF_TTL = 5.0

    def _current_filer_conf(self) -> FilerConf:
        """Rules are shared multi-process state (KV in the store, which
        may itself be remote); re-read on a short TTL so gateways and
        peers observe fs.configure changes."""
        now = clockctl.now()
        if now - self._filer_conf_loaded > self.FILER_CONF_TTL:
            try:
                self.filer_conf = FilerConf.load(self.filer.store)
            except Exception:
                pass  # keep the last-known rules on transient errors
            self._filer_conf_loaded = now
        return self.filer_conf

    def _check_writable(self, path: str) -> Optional[Response]:
        rule = self._current_filer_conf().match_storage_rule(path)
        if rule.read_only:
            return Response(
                {"error": f"{rule.location_prefix} is read-only"},
                status=403)
        return None

    def _handle_delete(self, req: Request) -> Response:
        path = req.path.rstrip("/") or "/"
        misroute = self._shard_redirect(req, path)
        if misroute is not None:
            return misroute
        denied = self._check_writable(path)
        if denied:
            return denied
        recursive = req.query.get("recursive") == "true"
        try:
            if self._shard_active():
                self._delete_entry_sharded(path, recursive)
            else:
                self.filer.delete_entry(path, recursive=recursive)
        except FileNotFoundError:
            return Response({"error": "not found"}, status=404)
        except OSError as e:
            return Response({"error": str(e)}, status=409)
        return Response(b"", status=204, content_type="text/plain")

    # ---- api ----
    def _api_rename(self, req: Request) -> Response:
        b = req.json()
        denied = (self._check_writable(b["from"])
                  or self._check_writable(b["to"]))
        if denied:
            return denied
        if self._shard_active():
            frm, to = b["from"], b["to"]
            # the rename runs where frm's ROW lives: owner(parent(frm))
            owner = self.shard_ring.owner_for_path(frm)
            if (owner and owner != self.url
                    and not req.headers.get(weed_headers.SHARD_FORWARDED)):
                self._m_shard.inc("forward")
                status, body, hdrs = http_call(
                    "POST", f"http://{owner}/__api/rename", json_body=b,
                    headers={weed_headers.SHARD_FORWARDED: "1"},
                    timeout=60)
                return Response(
                    body, status=status,
                    content_type=hdrs.get("Content-Type")
                    or "application/json",
                    headers={weed_headers.SHARD: format_shard_header(
                        self.shard_ring.epoch, owner)})
            try:
                self._rename_sharded(frm, to)
            except FileNotFoundError:
                return Response({"error": "not found"}, status=404)
            return Response({"path": to})
        try:
            entry = self.filer.rename_entry(b["from"], b["to"])
        except FileNotFoundError:
            return Response({"error": "not found"}, status=404)
        return Response({"path": entry.full_path})

    def _api_put_entry(self, req: Request) -> Response:
        """Write an entry record (metadata import: fs.meta.load,
        filer.sync sinks — reference filer_pb CreateEntry). meta_only
        writes the row verbatim at the store level, bypassing chunk GC
        and hard-link accounting (remote store adapters own those)."""
        b = req.json()
        entry = Entry.from_dict(b["entry"])
        denied = self._check_writable(entry.full_path)
        if denied:
            return denied
        if b.get("meta_only"):
            # row-level write, but STILL logged: sync/backup/mount
            # subscribers must see gateway-written entries (reference
            # CreateEntry always notifies)
            old = self.filer.store.inner.find_entry(entry.full_path)
            self.filer.store.inner.insert_entry(entry)
            self.filer._notify(entry.dir_path,
                               old.to_dict() if old else None,
                               entry.to_dict())
        else:
            self.filer.create_entry(entry)
        return Response({"path": entry.full_path}, status=201)

    def _api_get_entry(self, req: Request) -> Response:
        """Full entry metadata incl. chunks (reference
        LookupDirectoryEntry). raw=true returns the unresolved store row."""
        if req.query.get("raw") == "true":
            entry = self.filer.store.inner.find_entry(req.query["path"])
        else:
            entry = self.filer.find_entry(req.query["path"])
        if entry is None:
            return Response({"error": "not found"}, status=404)
        return Response({"entry": entry.to_dict()})

    def _api_delete_entry_row(self, req: Request) -> Response:
        """Metadata-row delete (no chunk GC — the caller owns it). The
        surface a remote FilerStore adapter needs (filer/remote_store.py).
        Deletions are logged so subscribers see them."""
        path = req.query["path"]
        denied = self._check_writable(path)
        if denied:
            return denied
        inner = self.filer.store.inner
        if req.query.get("children") == "true":
            doomed = inner.list_directory_entries(path, limit=1 << 20)
            inner.delete_folder_children(path)
            for child in doomed:
                self.filer._notify(path, child.to_dict(), None)
        else:
            old = inner.find_entry(path)
            inner.delete_entry(path)
            if old is not None:
                self.filer._notify(old.dir_path, old.to_dict(), None)
        return Response({})

    def _api_list_entries(self, req: Request) -> Response:
        """Full RAW entry rows of one directory (listing JSON on GET
        <dir> is trimmed for humans; store adapters resolve hard links
        themselves — same contract as entry?raw=true). resolved=true
        serves the RESOLVED view instead (hard links followed, through
        the entry cache) — what a peer shard wants for cross-shard
        listings."""
        if req.query.get("resolved") == "true":
            entries = self.filer.list_entries(
                req.query["dir"],
                start_name=req.query.get("start", ""),
                limit=int(req.query.get("limit", 1024)))
        else:
            entries = self.filer.store.inner.list_directory_entries(
                req.query["dir"],
                start_name=req.query.get("start", ""),
                include_start=req.query.get("include_start") == "true",
                limit=int(req.query.get("limit", 1024)),
                prefix=req.query.get("prefix", ""))
        return Response({"entries": [e.to_dict() for e in entries]})

    def _api_kv_get(self, req: Request) -> Response:
        val = self.filer.store.kv_get(req.query["key"].encode())
        if val is None:
            return Response({"error": "not found"}, status=404)
        return Response({"value": val.hex()})

    def _api_kv_put(self, req: Request) -> Response:
        b = req.json()
        if b.get("delete"):
            self.filer.store.kv_delete(b["key"].encode())
        else:
            self.filer.store.kv_put(b["key"].encode(),
                                    bytes.fromhex(b["value"]))
        return Response({})

    def _api_hardlink(self, req: Request) -> Response:
        b = req.json()
        denied = self._check_writable(b["to"])
        if denied:
            return denied
        try:
            entry = self.filer.add_hard_link(b["from"], b["to"])
        except FileNotFoundError:
            return Response({"error": "not found"}, status=404)
        except IsADirectoryError:
            return Response({"error": "is a directory"}, status=409)
        return Response({"path": entry.full_path,
                         "hard_link_id": entry.hard_link_id})

    def _api_filer_conf_get(self, req: Request) -> Response:
        return Response({"locations": [r.to_dict()
                                       for r in self._current_filer_conf().rules]})

    def _api_filer_conf_set(self, req: Request) -> Response:
        b = req.json()
        # serialize load->mutate->save per process, and mutate a
        # freshly-loaded conf so we never clobber rules a peer wrote
        # since our last TTL refresh (cross-process races remain, as in
        # the reference's read-modify-write of /etc/seaweedfs/filer.conf)
        with self._filer_conf_write_lock:
            conf = FilerConf.load(self.filer.store)
            if b.get("delete"):
                conf.delete_rule(b["location_prefix"])
            else:
                conf.set_rule(PathConf.from_dict(b))
            conf.save(self.filer.store)
            self.filer_conf = conf
            self._filer_conf_loaded = clockctl.now()
        return Response({"locations": [r.to_dict()
                                       for r in self.filer_conf.rules]})

    # ---- remote mounts (reference weed/filer remote_storage +
    #      shell remote.* + command/filer_remote_sync.go) ----
    def _api_remote_status(self, req: Request) -> Response:
        return Response({
            "remotes": [c.to_public_dict()
                        for c in self.remote_mounts.list_confs().values()],
            "mappings": self.remote_mounts.list_mappings()})

    def _api_remote_configure(self, req: Request) -> Response:
        from seaweedfs_tpu.remote_storage.remote_storage import RemoteConf
        b = req.json()
        if b.get("delete"):
            self.remote_mounts.delete_conf(b["name"])
        else:
            self.remote_mounts.configure(RemoteConf.from_dict(b))
        return self._api_remote_status(req)

    def _api_remote_mount(self, req: Request) -> Response:
        b = req.json()
        try:
            self.remote_mounts.mount(b["dir"], b["remote_name"],
                                     b.get("remote_path", ""))
        except KeyError as e:
            return Response({"error": str(e)}, status=404)
        return self._api_remote_status(req)

    def _api_remote_mount_buckets(self, req: Request) -> Response:
        b = req.json()
        try:
            mounted = self.remote_mounts.mount_buckets(
                b["remote_name"], b.get("bucket_pattern", ""))
        except KeyError as e:
            return Response({"error": str(e)}, status=404)
        except (ValueError, ConnectionError) as e:
            return Response({"error": str(e)}, status=400)
        return Response({"mounted": mounted})

    def _api_remote_unmount(self, req: Request) -> Response:
        self.remote_mounts.unmount(req.json()["dir"])
        return self._api_remote_status(req)

    def _api_remote_pull(self, req: Request) -> Response:
        try:
            n = self.remote_mounts.pull_metadata(req.json()["dir"])
        except KeyError as e:
            return Response({"error": str(e)}, status=404)
        return Response({"pulled": n})

    def _remote_entry_or_error(self, req: Request):
        path = req.json()["path"]
        entry = self.filer.find_entry(path)
        if entry is None:
            return None, Response({"error": "not found"}, status=404)
        return entry, None

    def _api_remote_cache(self, req: Request) -> Response:
        entry, err = self._remote_entry_or_error(req)
        if err:
            return err
        # same placement rules as a normal write to this path
        rule = self._current_filer_conf().match_storage_rule(entry.full_path)
        replication = rule.replication or self.default_replication
        entry = self.remote_mounts.cache_entry(
            entry, lambda data: self._upload_chunks(
                data, rule.collection, replication, rule.ttl))
        return Response({"cached": entry.full_path,
                         "chunks": len(entry.chunks)})

    def _api_remote_uncache(self, req: Request) -> Response:
        entry, err = self._remote_entry_or_error(req)
        if err:
            return err
        self.remote_mounts.uncache_entry(entry)
        return Response({"uncached": entry.full_path})

    def _api_remote_writeback(self, req: Request) -> Response:
        entry, err = self._remote_entry_or_error(req)
        if err:
            return err
        data = self._read_entry_bytes(entry)
        self.remote_mounts.write_back(entry, data)
        return Response({"synced": entry.full_path, "size": len(data)})

    def _api_remote_rm(self, req: Request) -> Response:
        self.remote_mounts.delete_remote(req.json()["path"])
        return Response({})

    def _api_chunk_blob(self, req: Request) -> Response:
        """Plaintext bytes of one chunk by fid — lets admin tools
        (volume.fsck) expand manifest chunks without reimplementing the
        decrypt/cache path."""
        from seaweedfs_tpu.filer.entry import FileChunk
        fid = req.match.group(1)
        key = bytes.fromhex(req.query.get("cipher_key", ""))
        try:
            blob = self._read_chunk(FileChunk(fid=fid, offset=0, size=0,
                                              cipher_key=key))
        except (ConnectionError, HttpError) as e:
            return Response({"error": str(e)}, status=502)
        return Response(blob, content_type="application/octet-stream")

    def _api_meta_events(self, req: Request) -> Response:
        since = int(req.query.get("since_ns", 0))
        prefix = req.query.get("prefix", "/")
        wait = float(req.query.get("wait", 0))
        # a sync direction excludes events its PEER direction wrote
        # (reference filer.sync signature exclusion — without it, a
        # bidirectional pair echoes every write forever)
        exclude = int(req.query.get("exclude_signature", 0))
        if req.query.get("aggregated") == "true":
            # reference SubscribeMetadata (cluster-wide) vs
            # SubscribeLocalMetadata (this filer only)
            log = getattr(self, "meta_aggregator", None)
            if log is None:
                return Response({"error": "aggregator not running"},
                                status=503)
            if wait > 0:
                log.log.wait_for_events(since, timeout=min(wait, 30))
            # snapshot BEFORE reading (same ordering as the gRPC
            # subscribe path): an event appended between read and
            # snapshot would be jumped by the cursor and lost
            latest = log.log.latest_tsns()
            events = log.log.read_since(since, prefix,
                                        exclude_signature=exclude)
            cursor = (events[-1]["tsns"] if events
                      else max(since, latest))
            return Response({"events": events, "cursor": cursor})
        if wait > 0:
            self.filer.meta_log.wait_for_events(since, timeout=min(wait, 30))
        # cursor: where the NEXT poll should resume. With results, the
        # last returned event (more may wait beyond the limit); with
        # none, the whole scanned range was excluded/non-matching, so
        # skip past it instead of re-scanning it every poll. The
        # latest-snapshot happens BEFORE the read so a concurrent
        # append can never land inside the skipped range.
        latest = self.filer.meta_log.latest_tsns()
        events = self.filer.meta_log.read_since(
            since, prefix, exclude_signature=exclude)
        cursor = (events[-1].tsns if events else max(since, latest))
        return Response({"events": [e.to_dict() for e in events],
                         "cursor": cursor})
