"""gRPC plane for the volume server (reference weed/pb/volume_server.proto).

Serves the admin RPC surface — allocation, vacuum, copy, tiering, the nine
EC RPCs, streaming CopyFile/VolumeEcShardRead, and BatchDelete — over
grpc generic method handlers (same pattern as server/master_grpc.py). The
unary RPCs dispatch in-process to the SAME handler bodies the HTTP admin
plane uses (via LocalRequest), so both wires share one implementation;
streams read files/shards in chunks directly.

Runs next to the HTTP plane: the public data path (GET/POST /fid) stays
HTTP like the reference, the control plane can speak either.
"""

from __future__ import annotations

import json
import os
from concurrent import futures
from typing import Iterator

import grpc

from seaweedfs_tpu.pb import volume_server_pb2 as pb
from seaweedfs_tpu.storage.file_id import FileId
from seaweedfs_tpu.storage.volume import DeletedError, NotFoundError
from seaweedfs_tpu.utils.httpd import LocalRequest

SERVICE = "volume_server_pb.VolumeServer"
STREAM_CHUNK = 256 * 1024


class _RpcError(Exception):
    def __init__(self, code: grpc.StatusCode, msg: str):
        super().__init__(msg)
        self.code = code
        self.msg = msg


def _check(resp) -> dict:
    """Unwrap a handler Response; map HTTP-ish errors to grpc codes."""
    body = json.loads(resp.body) if resp.body else {}
    if resp.status >= 400:
        code = (grpc.StatusCode.NOT_FOUND if resp.status == 404
                else grpc.StatusCode.INVALID_ARGUMENT if resp.status == 400
                else grpc.StatusCode.INTERNAL)
        raise _RpcError(code, body.get("error", f"status {resp.status}"))
    return body


def _guard(fn):
    def wrapped(self, request, context):
        try:
            return fn(self, request, context)
        except _RpcError as e:
            context.abort(e.code, e.msg)
        except FileNotFoundError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        except Exception as e:  # surface the message, not a hung stream
            context.abort(grpc.StatusCode.INTERNAL, f"{type(e).__name__}: {e}")
    return wrapped


class VolumeGrpc:
    def __init__(self, vs):
        self.vs = vs

    # ---- unary RPCs via the shared handler bodies ----
    @_guard
    def allocate_volume(self, request, context):
        _check(self.vs._admin_allocate_volume(LocalRequest({
            "volume_id": request.volume_id,
            "collection": request.collection,
            "replication": request.replication or "000",
            "ttl": request.ttl})))
        return pb.AllocateVolumeResponse()

    @_guard
    def volume_delete(self, request, context):
        body = _check(self.vs._admin_delete_volume(
            LocalRequest({"volume_id": request.volume_id})))
        return pb.VolumeDeleteResponse(deleted=bool(body.get("deleted")))

    @_guard
    def volume_mark_readonly(self, request, context):
        _check(self.vs._admin_mark_readonly(LocalRequest(
            {"volume_id": request.volume_id,
             "read_only": request.read_only})))
        return pb.VolumeMarkReadonlyResponse()

    @_guard
    def vacuum_volume_check(self, request, context):
        body = _check(self.vs._admin_vacuum(LocalRequest(
            {"volume_id": request.volume_id, "check_only": True})))
        return pb.VacuumVolumeCheckResponse(
            garbage_ratio=body.get("garbage_ratio", 0.0))

    @_guard
    def vacuum_volume_compact(self, request, context):
        body = _check(self.vs._admin_vacuum(LocalRequest(
            {"volume_id": request.volume_id})))
        return pb.VacuumVolumeCompactResponse(
            garbage_ratio=body.get("garbage_ratio", 0.0),
            compacted=bool(body.get("compacted")))

    @_guard
    def volume_sync(self, request, context):
        _check(self.vs._admin_sync(LocalRequest(
            {"volume_id": request.volume_id})))
        return pb.VolumeSyncResponse()

    @_guard
    def volume_copy(self, request, context):
        _check(self.vs._admin_copy_volume(LocalRequest(
            {"volume_id": request.volume_id,
             "source_data_node": request.source_data_node,
             "collection": request.collection})))
        return pb.VolumeCopyResponse()

    @_guard
    def volume_tier_to_remote(self, request, context):
        body = _check(self.vs._admin_tier_upload(LocalRequest(
            {"volume_id": request.volume_id,
             "endpoint": request.destination_backend_name,
             "bucket": request.bucket,
             "keep_local": request.keep_local_dat_file})))
        return pb.VolumeTierMoveDatToRemoteResponse(
            remote_key=str(body.get("remote", "")))

    @_guard
    def volume_tier_from_remote(self, request, context):
        _check(self.vs._admin_tier_download(LocalRequest(
            {"volume_id": request.volume_id})))
        return pb.VolumeTierMoveDatFromRemoteResponse()

    @_guard
    def volume_digest(self, request, context):
        body = _check(self.vs._admin_volume_digest(LocalRequest(
            query={"volumeId": str(request.volume_id)}, method="GET")))
        resp = pb.VolumeDigestResponse(file_count=body["file_count"],
                                       digest=body["digest"])
        for key, size in body.get("keys", []):
            resp.keys.add(key=key, size=size)
        return resp

    @_guard
    def read_needle_blob(self, request, context):
        v = self.vs.store.find_volume(request.volume_id)
        if v is None:
            raise _RpcError(grpc.StatusCode.NOT_FOUND, "volume not found")
        blob, size = v.read_needle_blob(request.needle_id)
        return pb.ReadNeedleBlobResponse(needle_blob=blob, size=size)

    @_guard
    def write_needle_blob(self, request, context):
        _check(self.vs._admin_write_needle_blob(LocalRequest(
            {"volume_id": request.volume_id, "key": request.needle_id,
             "size": request.size,
             "blob": request.needle_blob.hex()})))
        return pb.WriteNeedleBlobResponse()

    @_guard
    def batch_delete(self, request, context):
        """Reference volume_grpc_batch_delete.go: local deletes only (no
        replica fan-out — the caller addresses each replica)."""
        resp = pb.BatchDeleteResponse()
        for fid in request.file_ids:
            r = resp.results.add(file_id=fid)
            try:
                f = FileId.parse(fid)
            except (ValueError, KeyError):
                r.status, r.error = 400, "malformed file id"
                continue
            try:
                cookie = None if request.skip_cookie_check else f.cookie
                size = self.vs.store.delete_volume_needle(
                    f.volume_id, f.key, cookie)
                r.status, r.size = 202, size
            except (NotFoundError, DeletedError) as e:
                r.status, r.error = 404, str(e) or "not found"
            except PermissionError as e:
                r.status, r.error = 403, str(e)
            except Exception as e:
                r.status, r.error = 500, f"{type(e).__name__}: {e}"
        return resp

    @_guard
    def volume_server_status(self, request, context):
        resp = pb.VolumeServerStatusResponse(version="seaweedfs-tpu")
        for loc in self.vs.store.locations:
            for v in loc.volumes.values():
                resp.volumes.add(id=v.id, collection=v.collection,
                                 file_count=v.nm.file_count,
                                 size=v.content_size(),
                                 read_only=v.read_only)
        return resp

    # ---- EC unary RPCs ----
    @_guard
    def ec_generate(self, request, context):
        body = _check(self.vs._ec_generate(LocalRequest(
            {"volume_id": request.volume_id,
             "collection": request.collection})))
        return pb.VolumeEcShardsGenerateResponse(base=body.get("base", ""))

    @_guard
    def ec_rebuild(self, request, context):
        body = _check(self.vs._ec_rebuild(LocalRequest(
            {"volume_id": request.volume_id,
             "collection": request.collection})))
        return pb.VolumeEcShardsRebuildResponse(
            rebuilt_shard_ids=body.get("rebuilt_shard_ids", []))

    @_guard
    def ec_copy(self, request, context):
        _check(self.vs._ec_copy(LocalRequest(
            {"volume_id": request.volume_id,
             "collection": request.collection,
             "shard_ids": list(request.shard_ids),
             "copy_ecx_file": request.copy_ecx_file,
             "source_data_node": request.source_data_node})))
        return pb.VolumeEcShardsCopyResponse()

    @_guard
    def ec_delete(self, request, context):
        _check(self.vs._ec_delete_shards(LocalRequest(
            {"volume_id": request.volume_id,
             "collection": request.collection,
             "shard_ids": list(request.shard_ids)})))
        return pb.VolumeEcShardsDeleteResponse()

    @_guard
    def ec_mount(self, request, context):
        _check(self.vs._ec_mount(LocalRequest(
            {"volume_id": request.volume_id,
             "collection": request.collection,
             "shard_ids": list(request.shard_ids)})))
        return pb.VolumeEcShardsMountResponse()

    @_guard
    def ec_unmount(self, request, context):
        _check(self.vs._ec_unmount(LocalRequest(
            {"volume_id": request.volume_id,
             "shard_ids": list(request.shard_ids)})))
        return pb.VolumeEcShardsUnmountResponse()

    @_guard
    def ec_blob_delete(self, request, context):
        _check(self.vs._ec_blob_delete(LocalRequest(
            {"volume_id": request.volume_id,
             "collection": request.collection,
             "needle_id": request.file_key})))
        return pb.VolumeEcBlobDeleteResponse()

    @_guard
    def ec_to_volume(self, request, context):
        _check(self.vs._ec_to_volume(LocalRequest(
            {"volume_id": request.volume_id,
             "collection": request.collection})))
        return pb.VolumeEcShardsToVolumeResponse()

    # ---- streams ----
    @_guard
    def copy_file(self, request, context) -> Iterator[pb.CopyFileResponse]:
        """Streaming file pull (reference CopyFile): volume .dat/.idx or
        EC shard/index files."""
        if request.is_ec_volume:
            base = self.vs._ec_base_name(request.volume_id,
                                         request.collection)
            path = base + request.ext
        else:
            v = self.vs.store.find_volume(request.volume_id)
            if v is None:
                raise _RpcError(grpc.StatusCode.NOT_FOUND,
                                "volume not found")
            if request.ext not in (".dat", ".idx"):
                raise _RpcError(grpc.StatusCode.INVALID_ARGUMENT, "bad ext")
            v.sync()
            path = v.file_name() + request.ext
        if not os.path.exists(path):
            raise _RpcError(grpc.StatusCode.NOT_FOUND, path)
        with open(path, "rb") as f:
            while chunk := f.read(STREAM_CHUNK):
                yield pb.CopyFileResponse(file_content=chunk)

    @_guard
    def ec_shard_read(self, request, context
                      ) -> Iterator[pb.VolumeEcShardReadResponse]:
        ev = self.vs.store.find_ec_volume(request.volume_id)
        if ev is None or request.shard_id not in ev.shards:
            raise _RpcError(grpc.StatusCode.NOT_FOUND, "shard not found")
        if request.file_key and ev.is_deleted(request.file_key):
            yield pb.VolumeEcShardReadResponse(is_deleted=True)
            return
        shard = ev.shards[request.shard_id]
        off, remaining = request.offset, request.size
        while remaining > 0:
            n = min(STREAM_CHUNK, remaining)
            data = shard.read_at(off, n)
            if not data:
                break
            yield pb.VolumeEcShardReadResponse(data=data)
            off += len(data)
            remaining -= len(data)

    # ---- registration ----
    def handlers(self) -> grpc.GenericRpcHandler:
        def unary(fn, req_cls, resp_cls):
            return grpc.unary_unary_rpc_method_handler(
                fn, request_deserializer=req_cls.FromString,
                response_serializer=resp_cls.SerializeToString)

        def ustream(fn, req_cls, resp_cls):
            return grpc.unary_stream_rpc_method_handler(
                fn, request_deserializer=req_cls.FromString,
                response_serializer=resp_cls.SerializeToString)

        rpcs = {
            "AllocateVolume": unary(self.allocate_volume,
                                    pb.AllocateVolumeRequest,
                                    pb.AllocateVolumeResponse),
            "VolumeDelete": unary(self.volume_delete,
                                  pb.VolumeDeleteRequest,
                                  pb.VolumeDeleteResponse),
            "VolumeMarkReadonly": unary(self.volume_mark_readonly,
                                        pb.VolumeMarkReadonlyRequest,
                                        pb.VolumeMarkReadonlyResponse),
            "VacuumVolumeCheck": unary(self.vacuum_volume_check,
                                       pb.VacuumVolumeCheckRequest,
                                       pb.VacuumVolumeCheckResponse),
            "VacuumVolumeCompact": unary(self.vacuum_volume_compact,
                                         pb.VacuumVolumeCompactRequest,
                                         pb.VacuumVolumeCompactResponse),
            "VolumeSync": unary(self.volume_sync, pb.VolumeSyncRequest,
                                pb.VolumeSyncResponse),
            "VolumeCopy": unary(self.volume_copy, pb.VolumeCopyRequest,
                                pb.VolumeCopyResponse),
            "CopyFile": ustream(self.copy_file, pb.CopyFileRequest,
                                pb.CopyFileResponse),
            "VolumeTierMoveDatToRemote": unary(
                self.volume_tier_to_remote,
                pb.VolumeTierMoveDatToRemoteRequest,
                pb.VolumeTierMoveDatToRemoteResponse),
            "VolumeTierMoveDatFromRemote": unary(
                self.volume_tier_from_remote,
                pb.VolumeTierMoveDatFromRemoteRequest,
                pb.VolumeTierMoveDatFromRemoteResponse),
            "VolumeDigest": unary(self.volume_digest,
                                  pb.VolumeDigestRequest,
                                  pb.VolumeDigestResponse),
            "ReadNeedleBlob": unary(self.read_needle_blob,
                                    pb.ReadNeedleBlobRequest,
                                    pb.ReadNeedleBlobResponse),
            "WriteNeedleBlob": unary(self.write_needle_blob,
                                     pb.WriteNeedleBlobRequest,
                                     pb.WriteNeedleBlobResponse),
            "BatchDelete": unary(self.batch_delete, pb.BatchDeleteRequest,
                                 pb.BatchDeleteResponse),
            "VolumeServerStatus": unary(self.volume_server_status,
                                        pb.VolumeServerStatusRequest,
                                        pb.VolumeServerStatusResponse),
            "VolumeEcShardsGenerate": unary(
                self.ec_generate, pb.VolumeEcShardsGenerateRequest,
                pb.VolumeEcShardsGenerateResponse),
            "VolumeEcShardsRebuild": unary(
                self.ec_rebuild, pb.VolumeEcShardsRebuildRequest,
                pb.VolumeEcShardsRebuildResponse),
            "VolumeEcShardsCopy": unary(
                self.ec_copy, pb.VolumeEcShardsCopyRequest,
                pb.VolumeEcShardsCopyResponse),
            "VolumeEcShardsDelete": unary(
                self.ec_delete, pb.VolumeEcShardsDeleteRequest,
                pb.VolumeEcShardsDeleteResponse),
            "VolumeEcShardsMount": unary(
                self.ec_mount, pb.VolumeEcShardsMountRequest,
                pb.VolumeEcShardsMountResponse),
            "VolumeEcShardsUnmount": unary(
                self.ec_unmount, pb.VolumeEcShardsUnmountRequest,
                pb.VolumeEcShardsUnmountResponse),
            "VolumeEcShardRead": ustream(
                self.ec_shard_read, pb.VolumeEcShardReadRequest,
                pb.VolumeEcShardReadResponse),
            "VolumeEcBlobDelete": unary(
                self.ec_blob_delete, pb.VolumeEcBlobDeleteRequest,
                pb.VolumeEcBlobDeleteResponse),
            "VolumeEcShardsToVolume": unary(
                self.ec_to_volume, pb.VolumeEcShardsToVolumeRequest,
                pb.VolumeEcShardsToVolumeResponse),
        }
        return grpc.method_handlers_generic_handler(SERVICE, rpcs)


def start_volume_grpc(vs, host: str = "127.0.0.1",
                      port: int = 0, tls="auto") -> tuple[grpc.Server, int]:
    from seaweedfs_tpu.utils import tls as tlsmod
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=32))
    server.add_generic_rpc_handlers((VolumeGrpc(vs).handlers(),))
    cfg = tlsmod.load_tls_config("volume") if tls == "auto" else tls
    if cfg is not None:
        bound = server.add_secure_port(
            f"{host}:{port}", tlsmod.server_credentials(cfg))
    else:
        bound = server.add_insecure_port(f"{host}:{port}")
    server.start()
    return server, bound


class GrpcVolumeClient:
    """Typed client for the volume admin plane; also exposes call(path,
    body) with the HTTP-admin path names so the shell applier can use one
    transport-neutral call site."""

    def __init__(self, address: str, tls="auto"):
        from seaweedfs_tpu.utils.tls import make_channel
        self.channel = make_channel(address, role="client", tls=tls)

    def _unary(self, method: str, request, resp_cls,
               timeout: float = 300):
        fn = self.channel.unary_unary(
            f"/{SERVICE}/{method}",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=resp_cls.FromString)
        return fn(request, timeout=timeout)

    def copy_file(self, volume_id: int, ext: str, collection: str = "",
                  is_ec: bool = False) -> bytes:
        fn = self.channel.unary_stream(
            f"/{SERVICE}/CopyFile",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.CopyFileResponse.FromString)
        out = bytearray()
        for chunk in fn(pb.CopyFileRequest(volume_id=volume_id, ext=ext,
                                           collection=collection,
                                           is_ec_volume=is_ec),
                        timeout=600):
            out += chunk.file_content
        return bytes(out)

    def ec_shard_read(self, volume_id: int, shard_id: int, offset: int,
                      size: int, file_key: int = 0) -> tuple[bytes, bool]:
        fn = self.channel.unary_stream(
            f"/{SERVICE}/VolumeEcShardRead",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.VolumeEcShardReadResponse.FromString)
        out = bytearray()
        for chunk in fn(pb.VolumeEcShardReadRequest(
                volume_id=volume_id, shard_id=shard_id, offset=offset,
                size=size, file_key=file_key), timeout=120):
            if chunk.is_deleted:
                return b"", True
            out += chunk.data
        return bytes(out), False

    def batch_delete(self, file_ids: list[str],
                     skip_cookie_check: bool = False) -> pb.BatchDeleteResponse:
        return self._unary("BatchDelete",
                           pb.BatchDeleteRequest(
                               file_ids=file_ids,
                               skip_cookie_check=skip_cookie_check),
                           pb.BatchDeleteResponse)

    # HTTP-admin-path compatible dispatch used by the shell applier.
    # Returns a dict shaped like the HTTP JSON body.
    def call(self, path: str, body: dict, timeout: float = 300) -> dict:
        def un(method, request, resp_cls):
            return self._unary(method, request, resp_cls, timeout=timeout)
        return self._call_mapped(path, body or {}, un)

    def _call_mapped(self, path: str, b: dict, un) -> dict:
        if path == "/admin/allocate_volume":
            un("AllocateVolume", pb.AllocateVolumeRequest(
                volume_id=b["volume_id"],
                collection=b.get("collection", ""),
                replication=b.get("replication", "000"),
                ttl=b.get("ttl", "")), pb.AllocateVolumeResponse)
            return {}
        if path == "/admin/delete_volume":
            r = un("VolumeDelete", pb.VolumeDeleteRequest(
                volume_id=b["volume_id"]), pb.VolumeDeleteResponse)
            return {"deleted": r.deleted}
        if path == "/admin/mark_readonly":
            un("VolumeMarkReadonly", pb.VolumeMarkReadonlyRequest(
                volume_id=b["volume_id"],
                read_only=b.get("read_only", True)),
                pb.VolumeMarkReadonlyResponse)
            return {}
        if path == "/admin/vacuum":
            if b.get("check_only"):
                r = un("VacuumVolumeCheck",
                                pb.VacuumVolumeCheckRequest(
                                    volume_id=b["volume_id"]),
                                pb.VacuumVolumeCheckResponse)
                return {"garbage_ratio": r.garbage_ratio}
            r = un("VacuumVolumeCompact",
                            pb.VacuumVolumeCompactRequest(
                                volume_id=b["volume_id"]),
                            pb.VacuumVolumeCompactResponse)
            return {"garbage_ratio": r.garbage_ratio,
                    "compacted": r.compacted}
        if path == "/admin/sync":
            un("VolumeSync", pb.VolumeSyncRequest(
                volume_id=b.get("volume_id", 0)), pb.VolumeSyncResponse)
            return {}
        if path == "/admin/copy_volume":
            un("VolumeCopy", pb.VolumeCopyRequest(
                volume_id=b["volume_id"],
                source_data_node=b["source_data_node"],
                collection=b.get("collection", "")), pb.VolumeCopyResponse)
            return {}
        if path == "/admin/tier_upload":
            r = un("VolumeTierMoveDatToRemote",
                            pb.VolumeTierMoveDatToRemoteRequest(
                                volume_id=b["volume_id"],
                                destination_backend_name=b["endpoint"],
                                bucket=b["bucket"],
                                keep_local_dat_file=b.get("keep_local",
                                                          False)),
                            pb.VolumeTierMoveDatToRemoteResponse)
            return {"tiered": b["volume_id"], "remote": r.remote_key}
        if path == "/admin/tier_download":
            un("VolumeTierMoveDatFromRemote",
                        pb.VolumeTierMoveDatFromRemoteRequest(
                            volume_id=b["volume_id"]),
                        pb.VolumeTierMoveDatFromRemoteResponse)
            return {}
        if path == "/admin/write_needle_blob":
            un("WriteNeedleBlob", pb.WriteNeedleBlobRequest(
                volume_id=b["volume_id"], needle_id=b["key"],
                size=b["size"], needle_blob=bytes.fromhex(b["blob"])),
                pb.WriteNeedleBlobResponse)
            return {}
        if path == "/admin/ec/generate":
            r = un("VolumeEcShardsGenerate",
                            pb.VolumeEcShardsGenerateRequest(
                                volume_id=b["volume_id"],
                                collection=b.get("collection", "")),
                            pb.VolumeEcShardsGenerateResponse)
            return {"base": r.base}
        if path == "/admin/ec/rebuild":
            r = un("VolumeEcShardsRebuild",
                            pb.VolumeEcShardsRebuildRequest(
                                volume_id=b["volume_id"],
                                collection=b.get("collection", "")),
                            pb.VolumeEcShardsRebuildResponse)
            return {"rebuilt_shard_ids": list(r.rebuilt_shard_ids)}
        if path == "/admin/ec/copy":
            un("VolumeEcShardsCopy", pb.VolumeEcShardsCopyRequest(
                volume_id=b["volume_id"], collection=b.get("collection", ""),
                shard_ids=b.get("shard_ids", []),
                copy_ecx_file=b.get("copy_ecx_file", True),
                source_data_node=b["source_data_node"]),
                pb.VolumeEcShardsCopyResponse)
            return {}
        if path == "/admin/ec/delete_shards":
            un("VolumeEcShardsDelete",
                        pb.VolumeEcShardsDeleteRequest(
                            volume_id=b["volume_id"],
                            collection=b.get("collection", ""),
                            shard_ids=b.get("shard_ids", [])),
                        pb.VolumeEcShardsDeleteResponse)
            return {}
        if path == "/admin/ec/mount":
            un("VolumeEcShardsMount", pb.VolumeEcShardsMountRequest(
                volume_id=b["volume_id"], collection=b.get("collection", ""),
                shard_ids=b.get("shard_ids", [])),
                pb.VolumeEcShardsMountResponse)
            return {}
        if path == "/admin/ec/unmount":
            un("VolumeEcShardsUnmount",
                        pb.VolumeEcShardsUnmountRequest(
                            volume_id=b["volume_id"],
                            shard_ids=b.get("shard_ids", [])),
                        pb.VolumeEcShardsUnmountResponse)
            return {}
        if path == "/admin/ec/blob_delete":
            un("VolumeEcBlobDelete", pb.VolumeEcBlobDeleteRequest(
                volume_id=b["volume_id"], collection=b.get("collection", ""),
                file_key=b["needle_id"]), pb.VolumeEcBlobDeleteResponse)
            return {}
        if path == "/admin/ec/to_volume":
            un("VolumeEcShardsToVolume",
                        pb.VolumeEcShardsToVolumeRequest(
                            volume_id=b["volume_id"],
                            collection=b.get("collection", "")),
                        pb.VolumeEcShardsToVolumeResponse)
            return {}
        raise KeyError(f"no gRPC mapping for {path}")

    def close(self):
        self.channel.close()
