"""gRPC plane for the volume server (reference weed/pb/volume_server.proto).

Serves the admin RPC surface — allocation, vacuum, copy, tiering, the nine
EC RPCs, streaming CopyFile/VolumeEcShardRead, and BatchDelete — over
grpc generic method handlers (same pattern as server/master_grpc.py). The
unary RPCs dispatch in-process to the SAME handler bodies the HTTP admin
plane uses (via LocalRequest), so both wires share one implementation;
streams read files/shards in chunks directly.

Runs next to the HTTP plane: the public data path (GET/POST /fid) stays
HTTP like the reference, the control plane can speak either.
"""

from __future__ import annotations

import json
import os
from concurrent import futures
from typing import Iterator

import grpc

from seaweedfs_tpu.pb import volume_server_pb2 as pb
from seaweedfs_tpu.storage.file_id import FileId
from seaweedfs_tpu.storage.volume import DeletedError, NotFoundError
from seaweedfs_tpu.utils.httpd import LocalRequest

SERVICE = "weedtpu_volume_server_pb.VolumeServer"
STREAM_CHUNK = 256 * 1024


class _RpcError(Exception):
    def __init__(self, code: grpc.StatusCode, msg: str):
        super().__init__(msg)
        self.code = code
        self.msg = msg


def _check(resp) -> dict:
    """Unwrap a handler Response; map HTTP-ish errors to grpc codes."""
    body = json.loads(resp.body) if resp.body else {}
    if resp.status >= 400:
        code = (grpc.StatusCode.NOT_FOUND if resp.status == 404
                else grpc.StatusCode.INVALID_ARGUMENT if resp.status == 400
                else grpc.StatusCode.INTERNAL)
        raise _RpcError(code, body.get("error", f"status {resp.status}"))
    return body


def _guard(fn):
    def wrapped(self, request, context):
        try:
            return fn(self, request, context)
        except _RpcError as e:
            context.abort(e.code, e.msg)
        except FileNotFoundError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        except Exception as e:  # surface the message, not a hung stream
            context.abort(grpc.StatusCode.INTERNAL, f"{type(e).__name__}: {e}")
    return wrapped


class VolumeGrpc:
    def __init__(self, vs):
        self.vs = vs

    # ---- unary RPCs via the shared handler bodies ----
    @_guard
    def allocate_volume(self, request, context):
        _check(self.vs._admin_allocate_volume(LocalRequest({
            "volume_id": request.volume_id,
            "collection": request.collection,
            "replication": request.replication or "000",
            "ttl": request.ttl})))
        return pb.AllocateVolumeResponse()

    @_guard
    def volume_delete(self, request, context):
        body = _check(self.vs._admin_delete_volume(
            LocalRequest({"volume_id": request.volume_id})))
        return pb.VolumeDeleteResponse(deleted=bool(body.get("deleted")))

    @_guard
    def volume_mark_readonly(self, request, context):
        _check(self.vs._admin_mark_readonly(LocalRequest(
            {"volume_id": request.volume_id,
             "read_only": request.read_only})))
        return pb.VolumeMarkReadonlyResponse()

    @_guard
    def volume_mount(self, request, context):
        _check(self.vs._admin_mount_volume(
            LocalRequest({"volume_id": request.volume_id})))
        return pb.VolumeMountResponse()

    @_guard
    def volume_unmount(self, request, context):
        _check(self.vs._admin_unmount_volume(
            LocalRequest({"volume_id": request.volume_id})))
        return pb.VolumeUnmountResponse()

    @_guard
    def volume_configure(self, request, context):
        _check(self.vs._admin_configure_replication(
            LocalRequest({"volume_id": request.volume_id,
                          "replication": request.replication})))
        return pb.VolumeConfigureResponse()

    @_guard
    def vacuum_volume_check(self, request, context):
        body = _check(self.vs._admin_vacuum(LocalRequest(
            {"volume_id": request.volume_id, "check_only": True})))
        return pb.VacuumVolumeCheckResponse(
            garbage_ratio=body.get("garbage_ratio", 0.0))

    @_guard
    def vacuum_volume_compact(self, request, context):
        body = _check(self.vs._admin_vacuum(LocalRequest(
            {"volume_id": request.volume_id})))
        return pb.VacuumVolumeCompactResponse(
            garbage_ratio=body.get("garbage_ratio", 0.0),
            compacted=bool(body.get("compacted")))

    @_guard
    def volume_sync(self, request, context):
        _check(self.vs._admin_sync(LocalRequest(
            {"volume_id": request.volume_id})))
        return pb.VolumeSyncResponse()

    @_guard
    def volume_copy(self, request, context):
        _check(self.vs._admin_copy_volume(LocalRequest(
            {"volume_id": request.volume_id,
             "source_data_node": request.source_data_node,
             "collection": request.collection})))
        return pb.VolumeCopyResponse()

    @_guard
    def volume_tier_to_remote(self, request, context):
        body = _check(self.vs._admin_tier_upload(LocalRequest(
            {"volume_id": request.volume_id,
             "endpoint": request.destination_backend_name,
             "bucket": request.bucket,
             "keep_local": request.keep_local_dat_file})))
        return pb.VolumeTierMoveDatToRemoteResponse(
            remote_key=str(body.get("remote", "")))

    @_guard
    def volume_tier_from_remote(self, request, context):
        _check(self.vs._admin_tier_download(LocalRequest(
            {"volume_id": request.volume_id})))
        return pb.VolumeTierMoveDatFromRemoteResponse()

    @_guard
    def volume_digest(self, request, context):
        body = _check(self.vs._admin_volume_digest(LocalRequest(
            query={"volumeId": str(request.volume_id)}, method="GET")))
        resp = pb.VolumeDigestResponse(file_count=body["file_count"],
                                       digest=body["digest"])
        for key, size in body.get("keys", []):
            resp.keys.add(key=key, size=size)
        return resp

    @_guard
    def read_needle_blob(self, request, context):
        v = self.vs.store.find_volume(request.volume_id)
        if v is None:
            raise _RpcError(grpc.StatusCode.NOT_FOUND, "volume not found")
        blob, size = v.read_needle_blob(request.needle_id)
        return pb.ReadNeedleBlobResponse(needle_blob=blob, size=size)

    @_guard
    def write_needle_blob(self, request, context):
        _check(self.vs._admin_write_needle_blob(LocalRequest(
            {"volume_id": request.volume_id, "key": request.needle_id,
             "size": request.size,
             "blob": request.needle_blob.hex()})))
        return pb.WriteNeedleBlobResponse()

    @_guard
    def batch_delete(self, request, context):
        """Reference volume_grpc_batch_delete.go: local deletes only (no
        replica fan-out — the caller addresses each replica)."""
        resp = pb.BatchDeleteResponse()
        for fid in request.file_ids:
            r = resp.results.add(file_id=fid)
            try:
                f = FileId.parse(fid)
            except (ValueError, KeyError):
                r.status, r.error = 400, "malformed file id"
                continue
            try:
                cookie = None if request.skip_cookie_check else f.cookie
                size = self.vs.store.delete_volume_needle(
                    f.volume_id, f.key, cookie)
                r.status, r.size = 202, size
            except (NotFoundError, DeletedError) as e:
                r.status, r.error = 404, str(e) or "not found"
            except PermissionError as e:
                r.status, r.error = 403, str(e)
            except Exception as e:
                r.status, r.error = 500, f"{type(e).__name__}: {e}"
        return resp

    @_guard
    def volume_server_status(self, request, context):
        resp = pb.VolumeServerStatusResponse(version="seaweedfs-tpu")
        for loc in self.vs.store.locations:
            for v in loc.volumes.values():
                resp.volumes.add(id=v.id, collection=v.collection,
                                 file_count=v.nm.file_count,
                                 size=v.content_size(),
                                 read_only=v.read_only)
        return resp

    # ---- EC unary RPCs ----
    @_guard
    def ec_generate(self, request, context):
        # gRPC plane always takes the pipelined encoder (overlapped
        # I/O + compute; serial is reachable via the HTTP admin flag)
        body = _check(self.vs._ec_generate(LocalRequest(
            {"volume_id": request.volume_id,
             "collection": request.collection,
             "pipelined": True})))
        return pb.VolumeEcShardsGenerateResponse(base=body.get("base", ""))

    @_guard
    def ec_rebuild(self, request, context):
        body = _check(self.vs._ec_rebuild(LocalRequest(
            {"volume_id": request.volume_id,
             "collection": request.collection,
             "pipelined": True})))
        return pb.VolumeEcShardsRebuildResponse(
            rebuilt_shard_ids=body.get("rebuilt_shard_ids", []))

    @_guard
    def ec_copy(self, request, context):
        _check(self.vs._ec_copy(LocalRequest(
            {"volume_id": request.volume_id,
             "collection": request.collection,
             "shard_ids": list(request.shard_ids),
             "copy_ecx_file": request.copy_ecx_file,
             "source_data_node": request.source_data_node})))
        return pb.VolumeEcShardsCopyResponse()

    @_guard
    def ec_delete(self, request, context):
        _check(self.vs._ec_delete_shards(LocalRequest(
            {"volume_id": request.volume_id,
             "collection": request.collection,
             "shard_ids": list(request.shard_ids)})))
        return pb.VolumeEcShardsDeleteResponse()

    @_guard
    def ec_mount(self, request, context):
        _check(self.vs._ec_mount(LocalRequest(
            {"volume_id": request.volume_id,
             "collection": request.collection,
             "shard_ids": list(request.shard_ids)})))
        return pb.VolumeEcShardsMountResponse()

    @_guard
    def ec_unmount(self, request, context):
        _check(self.vs._ec_unmount(LocalRequest(
            {"volume_id": request.volume_id,
             "shard_ids": list(request.shard_ids)})))
        return pb.VolumeEcShardsUnmountResponse()

    @_guard
    def ec_blob_delete(self, request, context):
        _check(self.vs._ec_blob_delete(LocalRequest(
            {"volume_id": request.volume_id,
             "collection": request.collection,
             "needle_id": request.file_key})))
        return pb.VolumeEcBlobDeleteResponse()

    @_guard
    def ec_to_volume(self, request, context):
        _check(self.vs._ec_to_volume(LocalRequest(
            {"volume_id": request.volume_id,
             "collection": request.collection})))
        return pb.VolumeEcShardsToVolumeResponse()

    # ---- streams ----
    @_guard
    def copy_file(self, request, context) -> Iterator[pb.CopyFileResponse]:
        """Streaming file pull (reference CopyFile): volume .dat/.idx or
        EC shard/index files."""
        if request.is_ec_volume:
            base = self.vs._ec_base_name(request.volume_id,
                                         request.collection)
            path = base + request.ext
        else:
            v = self.vs.store.find_volume(request.volume_id)
            if v is None:
                raise _RpcError(grpc.StatusCode.NOT_FOUND,
                                "volume not found")
            if request.ext not in (".dat", ".idx"):
                raise _RpcError(grpc.StatusCode.INVALID_ARGUMENT, "bad ext")
            v.sync()
            path = v.file_name() + request.ext
        if not os.path.exists(path):
            raise _RpcError(grpc.StatusCode.NOT_FOUND, path)
        with open(path, "rb") as f:
            while chunk := f.read(STREAM_CHUNK):
                yield pb.CopyFileResponse(file_content=chunk)

    @_guard
    def ec_shard_read(self, request, context
                      ) -> Iterator[pb.VolumeEcShardReadResponse]:
        ev = self.vs.store.find_ec_volume(request.volume_id)
        if ev is None or request.shard_id not in ev.shards:
            raise _RpcError(grpc.StatusCode.NOT_FOUND, "shard not found")
        if request.file_key and ev.is_deleted(request.file_key):
            yield pb.VolumeEcShardReadResponse(is_deleted=True)
            return
        shard = ev.shards[request.shard_id]
        off, remaining = request.offset, request.size
        while remaining > 0:
            n = min(STREAM_CHUNK, remaining)
            data = shard.read_at(off, n)
            if not data:
                break
            yield pb.VolumeEcShardReadResponse(data=data)
            off += len(data)
            remaining -= len(data)

    # ---- replica catch-up (reference volume_server.proto:31,64;
    # volume_grpc_tail.go) ----
    def _records_since(self, volume_id: int, since_ns: int,
                       normalize_v3: bool = False):
        """Yield (needle, raw_record) for every record appended after
        since_ns, in log order. Deletion records are included — a
        catching-up replica must replay those too.

        The scan is header-only until a record qualifies: for v3 the
        append_at_ns rides at a fixed position (header + size + crc),
        so old records cost one 8-byte pread each instead of a full
        body read — a periodic tail poll is O(records), not O(bytes)
        (the reference seeks from a known offset, volume_grpc_tail.go;
        without one this is the next best).

        normalize_v3 re-serializes v1/v2 records as v3 so the receiving
        side can parse one wire version."""
        from seaweedfs_tpu.storage import types as t
        from seaweedfs_tpu.storage.needle import Needle
        from seaweedfs_tpu.storage.super_block import SuperBlock
        v = self.vs.store.find_volume(volume_id)
        if v is None:
            raise _RpcError(grpc.StatusCode.NOT_FOUND, "volume not found")
        v.sync()
        path = v.file_name() + ".dat"
        size_total = os.path.getsize(path)
        with open(path, "rb") as f:
            import struct
            sb = SuperBlock.parse(f.read(8 + 65536)[:8 + 65536])
            offset = (sb.block_size + t.NEEDLE_PADDING_SIZE - 1) \
                // t.NEEDLE_PADDING_SIZE * t.NEEDLE_PADDING_SIZE
            version = sb.version
            if version < 3 and since_ns > 0:
                # v1/v2 records carry no append timestamp; a cursor'd
                # tail CANNOT be answered — failing loudly beats
                # returning an empty stream the caller reads as
                # "in sync" (use a full VolumeCopy instead)
                raise _RpcError(
                    grpc.StatusCode.FAILED_PRECONDITION,
                    f"volume version {version} has no append_at_ns; "
                    "tail requires since_ns=0 or a full copy")
            fd = f.fileno()
            while offset + t.NEEDLE_HEADER_SIZE <= size_total:
                header = os.pread(fd, t.NEEDLE_HEADER_SIZE, offset)
                if len(header) < t.NEEDLE_HEADER_SIZE:
                    break
                hn = Needle.parse_header(header)
                if hn.size < 0:
                    break
                record_len = t.get_actual_size(hn.size, version)
                if offset + record_len > size_total:
                    break
                ts = 0
                if version == 3:
                    raw_ts = os.pread(
                        fd, t.TIMESTAMP_SIZE,
                        offset + t.NEEDLE_HEADER_SIZE + hn.size
                        + t.NEEDLE_CHECKSUM_SIZE)
                    if len(raw_ts) == t.TIMESTAMP_SIZE:
                        ts, = struct.unpack(">Q", raw_ts)
                if ts > since_ns or (version < 3 and since_ns == 0):
                    blob = os.pread(fd, record_len, offset)
                    try:
                        n = Needle.from_bytes(blob, hn.size, version,
                                              check_crc=False)
                    except Exception:
                        break
                    if normalize_v3 and version != 3:
                        blob = n.to_bytes(3)
                    yield n, blob
                offset += record_len

    @_guard
    def volume_incremental_copy(self, request, context
                                ) -> Iterator["pb.VolumeIncrementalCopyResponse"]:
        buf = bytearray()
        for _, raw in self._records_since(request.volume_id,
                                          request.since_ns):
            buf.extend(raw)
            while len(buf) >= STREAM_CHUNK:
                yield pb.VolumeIncrementalCopyResponse(
                    file_content=bytes(buf[:STREAM_CHUNK]))
                del buf[:STREAM_CHUNK]
        if buf:
            yield pb.VolumeIncrementalCopyResponse(file_content=bytes(buf))

    @_guard
    def volume_tail_sender(self, request, context
                           ) -> Iterator["pb.VolumeTailSenderResponse"]:
        from seaweedfs_tpu.storage import types as t
        for _, raw in self._records_since(request.volume_id,
                                          request.since_ns,
                                          normalize_v3=True):
            header = raw[:t.NEEDLE_HEADER_SIZE]
            body = raw[t.NEEDLE_HEADER_SIZE:]
            # large needles stream in body pieces; the header rides the
            # first message, is_last_chunk closes the record
            first = True
            pos = 0
            while True:
                piece = body[pos:pos + STREAM_CHUNK]
                pos += len(piece)
                last = pos >= len(body)
                yield pb.VolumeTailSenderResponse(
                    needle_header=header if first else b"",
                    needle_body=piece, is_last_chunk=last)
                first = False
                if last:
                    break

    @_guard
    def volume_tail_receiver(self, request, context):
        """Pull a tail FROM a peer and apply it locally — the replica
        catch-up entry point (reference volume_grpc_tail.go
        VolumeTailReceiver)."""
        v = self.vs.store.find_volume(request.volume_id)
        if v is None:
            raise _RpcError(grpc.StatusCode.NOT_FOUND, "volume not found")
        client = GrpcVolumeClient(request.source_volume_server)
        try:
            applied = 0
            for n in client.volume_tail_needles(request.volume_id,
                                                request.since_ns):
                if n.size == 0 and not n.data:
                    v.delete_needle(n.id)
                else:
                    v.write_needle(n)
                applied += 1
            return pb.VolumeTailReceiverResponse()
        finally:
            client.close()

    @_guard
    def read_volume_file_status(self, request, context):
        v = self.vs.store.find_volume(request.volume_id)
        if v is None:
            raise _RpcError(grpc.StatusCode.NOT_FOUND, "volume not found")
        v.sync()
        base = v.file_name()
        resp = pb.ReadVolumeFileStatusResponse(
            volume_id=request.volume_id,
            collection=v.collection,
            file_count=v.file_count(),
            compaction_revision=getattr(v.super_block,
                                        "compaction_revision", 0),
            last_append_at_ns=v.last_append_at_ns)
        for ext, ts_field, size_field in (
                (".idx", "idx_file_timestamp_seconds", "idx_file_size"),
                (".dat", "dat_file_timestamp_seconds", "dat_file_size")):
            try:
                st = os.stat(base + ext)
                setattr(resp, ts_field, int(st.st_mtime))
                setattr(resp, size_field, st.st_size)
            except OSError:
                pass
        return resp

    @_guard
    def volume_needle_status(self, request, context):
        try:
            n = self.vs.store.read_volume_needle(request.volume_id,
                                                 request.needle_id, None)
        except (NotFoundError, DeletedError) as e:
            raise _RpcError(grpc.StatusCode.NOT_FOUND, str(e))
        return pb.VolumeNeedleStatusResponse(
            needle_id=n.id, cookie=n.cookie, size=len(n.data),
            last_modified=n.last_modified, crc=n.checksum,
            ttl=n.ttl.hex() if n.ttl else "")

    def ping(self, request, context):
        import time as _time
        start = _time.time_ns()
        remote = start
        if request.target:
            from seaweedfs_tpu.utils.httpd import http_call
            try:
                http_call("GET", f"http://{request.target}/status",
                          timeout=5)
                remote = _time.time_ns()
            except Exception as e:
                context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
        return pb.PingResponse(start_time_ns=start,
                               remote_time_ns=remote,
                               stop_time_ns=_time.time_ns())

    @_guard
    def query(self, request, context) -> Iterator["pb.QueriedStripe"]:
        """Server-side JSON scan over needles (reference Query rpc +
        weed/query/json): projections/filter run where the data lives,
        only matching rows cross the wire."""
        from seaweedfs_tpu.query.json_query import (Predicate,
                                                    query_json_lines)
        preds = []
        if request.HasField("filter") and request.filter.field:
            val = request.filter.value
            for cast in (int, float):
                try:
                    val = cast(request.filter.value)
                    break
                except ValueError:
                    continue
            preds = [Predicate(request.filter.field,
                               request.filter.operand or "=", val)]
        selections = list(request.selections)
        for fid in request.from_file_ids:
            f = FileId.parse(fid)
            try:
                n = self.vs.store.read_volume_needle(f.volume_id, f.key,
                                                     f.cookie)
            except (NotFoundError, DeletedError):
                continue
            out = []
            for doc in query_json_lines(n.data, selections or None, preds):
                out.append(json.dumps(doc))
            if out:
                yield pb.QueriedStripe(
                    records=("\n".join(out) + "\n").encode())

    # ---- integrity scrub (JSON codec: these RPCs postdate the vendored
    # pb modules and the container has no protoc to regenerate them) ----
    @_guard
    def volume_scrub(self, request, context):
        return _check(self.vs._admin_scrub(LocalRequest(request or {})))

    @_guard
    def scrub_status(self, request, context):
        return _check(self.vs._admin_scrub_status(
            LocalRequest(method="GET", path="/admin/scrub/status")))

    # ---- registration ----
    def handlers(self) -> grpc.GenericRpcHandler:
        def unary(fn, req_cls, resp_cls):
            return grpc.unary_unary_rpc_method_handler(
                fn, request_deserializer=req_cls.FromString,
                response_serializer=resp_cls.SerializeToString)

        def ustream(fn, req_cls, resp_cls):
            return grpc.unary_stream_rpc_method_handler(
                fn, request_deserializer=req_cls.FromString,
                response_serializer=resp_cls.SerializeToString)

        def junary(fn):
            # JSON-bytes codec for RPCs without vendored pb messages
            return grpc.unary_unary_rpc_method_handler(
                fn,
                request_deserializer=lambda raw:
                    json.loads(raw.decode()) if raw else {},
                response_serializer=lambda d: json.dumps(d).encode())

        rpcs = {
            "AllocateVolume": unary(self.allocate_volume,
                                    pb.AllocateVolumeRequest,
                                    pb.AllocateVolumeResponse),
            "VolumeDelete": unary(self.volume_delete,
                                  pb.VolumeDeleteRequest,
                                  pb.VolumeDeleteResponse),
            "VolumeMarkReadonly": unary(self.volume_mark_readonly,
                                        pb.VolumeMarkReadonlyRequest,
                                        pb.VolumeMarkReadonlyResponse),
            "VolumeMount": unary(self.volume_mount,
                                 pb.VolumeMountRequest,
                                 pb.VolumeMountResponse),
            "VolumeUnmount": unary(self.volume_unmount,
                                   pb.VolumeUnmountRequest,
                                   pb.VolumeUnmountResponse),
            "VolumeConfigure": unary(self.volume_configure,
                                     pb.VolumeConfigureRequest,
                                     pb.VolumeConfigureResponse),
            "VacuumVolumeCheck": unary(self.vacuum_volume_check,
                                       pb.VacuumVolumeCheckRequest,
                                       pb.VacuumVolumeCheckResponse),
            "VacuumVolumeCompact": unary(self.vacuum_volume_compact,
                                         pb.VacuumVolumeCompactRequest,
                                         pb.VacuumVolumeCompactResponse),
            "VolumeSync": unary(self.volume_sync, pb.VolumeSyncRequest,
                                pb.VolumeSyncResponse),
            "VolumeCopy": unary(self.volume_copy, pb.VolumeCopyRequest,
                                pb.VolumeCopyResponse),
            "CopyFile": ustream(self.copy_file, pb.CopyFileRequest,
                                pb.CopyFileResponse),
            "VolumeTierMoveDatToRemote": unary(
                self.volume_tier_to_remote,
                pb.VolumeTierMoveDatToRemoteRequest,
                pb.VolumeTierMoveDatToRemoteResponse),
            "VolumeTierMoveDatFromRemote": unary(
                self.volume_tier_from_remote,
                pb.VolumeTierMoveDatFromRemoteRequest,
                pb.VolumeTierMoveDatFromRemoteResponse),
            "VolumeDigest": unary(self.volume_digest,
                                  pb.VolumeDigestRequest,
                                  pb.VolumeDigestResponse),
            "ReadNeedleBlob": unary(self.read_needle_blob,
                                    pb.ReadNeedleBlobRequest,
                                    pb.ReadNeedleBlobResponse),
            "WriteNeedleBlob": unary(self.write_needle_blob,
                                     pb.WriteNeedleBlobRequest,
                                     pb.WriteNeedleBlobResponse),
            "BatchDelete": unary(self.batch_delete, pb.BatchDeleteRequest,
                                 pb.BatchDeleteResponse),
            "VolumeServerStatus": unary(self.volume_server_status,
                                        pb.VolumeServerStatusRequest,
                                        pb.VolumeServerStatusResponse),
            "VolumeEcShardsGenerate": unary(
                self.ec_generate, pb.VolumeEcShardsGenerateRequest,
                pb.VolumeEcShardsGenerateResponse),
            "VolumeEcShardsRebuild": unary(
                self.ec_rebuild, pb.VolumeEcShardsRebuildRequest,
                pb.VolumeEcShardsRebuildResponse),
            "VolumeEcShardsCopy": unary(
                self.ec_copy, pb.VolumeEcShardsCopyRequest,
                pb.VolumeEcShardsCopyResponse),
            "VolumeEcShardsDelete": unary(
                self.ec_delete, pb.VolumeEcShardsDeleteRequest,
                pb.VolumeEcShardsDeleteResponse),
            "VolumeEcShardsMount": unary(
                self.ec_mount, pb.VolumeEcShardsMountRequest,
                pb.VolumeEcShardsMountResponse),
            "VolumeEcShardsUnmount": unary(
                self.ec_unmount, pb.VolumeEcShardsUnmountRequest,
                pb.VolumeEcShardsUnmountResponse),
            "VolumeEcShardRead": ustream(
                self.ec_shard_read, pb.VolumeEcShardReadRequest,
                pb.VolumeEcShardReadResponse),
            "VolumeEcBlobDelete": unary(
                self.ec_blob_delete, pb.VolumeEcBlobDeleteRequest,
                pb.VolumeEcBlobDeleteResponse),
            "VolumeEcShardsToVolume": unary(
                self.ec_to_volume, pb.VolumeEcShardsToVolumeRequest,
                pb.VolumeEcShardsToVolumeResponse),
            "VolumeIncrementalCopy": ustream(
                self.volume_incremental_copy,
                pb.VolumeIncrementalCopyRequest,
                pb.VolumeIncrementalCopyResponse),
            "VolumeTailSender": ustream(
                self.volume_tail_sender, pb.VolumeTailSenderRequest,
                pb.VolumeTailSenderResponse),
            "VolumeTailReceiver": unary(
                self.volume_tail_receiver, pb.VolumeTailReceiverRequest,
                pb.VolumeTailReceiverResponse),
            "ReadVolumeFileStatus": unary(
                self.read_volume_file_status,
                pb.ReadVolumeFileStatusRequest,
                pb.ReadVolumeFileStatusResponse),
            "VolumeNeedleStatus": unary(
                self.volume_needle_status, pb.VolumeNeedleStatusRequest,
                pb.VolumeNeedleStatusResponse),
            "Ping": unary(self.ping, pb.PingRequest, pb.PingResponse),
            "Query": ustream(self.query, pb.QueryRequest,
                             pb.QueriedStripe),
            "VolumeScrub": junary(self.volume_scrub),
            "ScrubStatus": junary(self.scrub_status),
        }
        return grpc.method_handlers_generic_handler(SERVICE, rpcs)


def start_volume_grpc(vs, host: str = "127.0.0.1",
                      port: int = 0, tls="auto") -> tuple[grpc.Server, int]:
    from seaweedfs_tpu.utils import tls as tlsmod
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=32))
    server.add_generic_rpc_handlers((VolumeGrpc(vs).handlers(),))
    cfg = tlsmod.load_tls_config("volume") if tls == "auto" else tls
    if cfg is not None:
        bound = server.add_secure_port(
            f"{host}:{port}", tlsmod.server_credentials(cfg))
    else:
        bound = server.add_insecure_port(f"{host}:{port}")
    server.start()
    return server, bound


class GrpcVolumeClient:
    """Typed client for the volume admin plane; also exposes call(path,
    body) with the HTTP-admin path names so the shell applier can use one
    transport-neutral call site."""

    def __init__(self, address: str, tls="auto"):
        from seaweedfs_tpu.utils.tls import make_channel
        self.channel = make_channel(address, role="client", tls=tls)

    def _unary(self, method: str, request, resp_cls,
               timeout: float = 300):
        fn = self.channel.unary_unary(
            f"/{SERVICE}/{method}",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=resp_cls.FromString)
        return fn(request, timeout=timeout)

    def copy_file(self, volume_id: int, ext: str, collection: str = "",
                  is_ec: bool = False) -> bytes:
        fn = self.channel.unary_stream(
            f"/{SERVICE}/CopyFile",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.CopyFileResponse.FromString)
        out = bytearray()
        for chunk in fn(pb.CopyFileRequest(volume_id=volume_id, ext=ext,
                                           collection=collection,
                                           is_ec_volume=is_ec),
                        timeout=600):
            out += chunk.file_content
        return bytes(out)

    def ec_shard_read(self, volume_id: int, shard_id: int, offset: int,
                      size: int, file_key: int = 0) -> tuple[bytes, bool]:
        fn = self.channel.unary_stream(
            f"/{SERVICE}/VolumeEcShardRead",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.VolumeEcShardReadResponse.FromString)
        out = bytearray()
        for chunk in fn(pb.VolumeEcShardReadRequest(
                volume_id=volume_id, shard_id=shard_id, offset=offset,
                size=size, file_key=file_key), timeout=120):
            if chunk.is_deleted:
                return b"", True
            out += chunk.data
        return bytes(out), False

    def batch_delete(self, file_ids: list[str],
                     skip_cookie_check: bool = False) -> pb.BatchDeleteResponse:
        return self._unary("BatchDelete",
                           pb.BatchDeleteRequest(
                               file_ids=file_ids,
                               skip_cookie_check=skip_cookie_check),
                           pb.BatchDeleteResponse)

    # ---- replica catch-up ----
    def volume_tail_needles(self, volume_id: int, since_ns: int = 0):
        """Iterate needles a peer appended after since_ns (reassembled
        from the VolumeTailSender stream)."""
        from seaweedfs_tpu.storage.needle import Needle
        fn = self.channel.unary_stream(
            f"/{SERVICE}/VolumeTailSender",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.VolumeTailSenderResponse.FromString)
        header = b""
        body = bytearray()
        for msg in fn(pb.VolumeTailSenderRequest(
                volume_id=volume_id, since_ns=since_ns), timeout=600):
            if msg.needle_header:
                header, body = bytes(msg.needle_header), bytearray()
            body += msg.needle_body
            if msg.is_last_chunk:
                raw = header + bytes(body)
                n = Needle.parse_header(header)
                yield Needle.from_bytes(raw, n.size, 3, check_crc=False)
                header, body = b"", bytearray()

    def volume_incremental_copy(self, volume_id: int,
                                since_ns: int = 0) -> bytes:
        """Raw appended record bytes since a timestamp (reference
        VolumeIncrementalCopy: the caller appends them to its .dat)."""
        fn = self.channel.unary_stream(
            f"/{SERVICE}/VolumeIncrementalCopy",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.VolumeIncrementalCopyResponse.FromString)
        out = bytearray()
        for msg in fn(pb.VolumeIncrementalCopyRequest(
                volume_id=volume_id, since_ns=since_ns), timeout=600):
            out += msg.file_content
        return bytes(out)

    def volume_tail_receiver(self, volume_id: int, since_ns: int,
                             source: str) -> None:
        self._unary("VolumeTailReceiver", pb.VolumeTailReceiverRequest(
            volume_id=volume_id, since_ns=since_ns,
            source_volume_server=source), pb.VolumeTailReceiverResponse)

    def read_volume_file_status(self, volume_id: int
                                ) -> pb.ReadVolumeFileStatusResponse:
        return self._unary("ReadVolumeFileStatus",
                           pb.ReadVolumeFileStatusRequest(
                               volume_id=volume_id),
                           pb.ReadVolumeFileStatusResponse)

    def volume_needle_status(self, volume_id: int, needle_id: int
                             ) -> pb.VolumeNeedleStatusResponse:
        return self._unary("VolumeNeedleStatus",
                           pb.VolumeNeedleStatusRequest(
                               volume_id=volume_id, needle_id=needle_id),
                           pb.VolumeNeedleStatusResponse)

    def ping(self, target: str = "", target_type: str = ""
             ) -> pb.PingResponse:
        return self._unary("Ping", pb.PingRequest(
            target=target, target_type=target_type), pb.PingResponse,
            timeout=10)

    def query(self, file_ids: list[str], selections: list[str] = (),
              filter_field: str = "", filter_op: str = "=",
              filter_value: str = "") -> bytes:
        fn = self.channel.unary_stream(
            f"/{SERVICE}/Query",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.QueriedStripe.FromString)
        req = pb.QueryRequest(selections=list(selections),
                              from_file_ids=list(file_ids))
        if filter_field:
            req.filter.field = filter_field
            req.filter.operand = filter_op
            req.filter.value = filter_value
        out = bytearray()
        for stripe in fn(req, timeout=300):
            out += stripe.records
        return bytes(out)

    # HTTP-admin-path compatible dispatch used by the shell applier.
    # Returns a dict shaped like the HTTP JSON body.
    def call(self, path: str, body: dict, timeout: float = 300) -> dict:
        def un(method, request, resp_cls):
            return self._unary(method, request, resp_cls, timeout=timeout)
        return self._call_mapped(path, body or {}, un)

    def _call_mapped(self, path: str, b: dict, un) -> dict:
        if path == "/admin/allocate_volume":
            un("AllocateVolume", pb.AllocateVolumeRequest(
                volume_id=b["volume_id"],
                collection=b.get("collection", ""),
                replication=b.get("replication", "000"),
                ttl=b.get("ttl", "")), pb.AllocateVolumeResponse)
            return {}
        if path == "/admin/delete_volume":
            r = un("VolumeDelete", pb.VolumeDeleteRequest(
                volume_id=b["volume_id"]), pb.VolumeDeleteResponse)
            return {"deleted": r.deleted}
        if path == "/admin/mark_readonly":
            un("VolumeMarkReadonly", pb.VolumeMarkReadonlyRequest(
                volume_id=b["volume_id"],
                read_only=b.get("read_only", True)),
                pb.VolumeMarkReadonlyResponse)
            return {}
        if path == "/admin/vacuum":
            if b.get("check_only"):
                r = un("VacuumVolumeCheck",
                                pb.VacuumVolumeCheckRequest(
                                    volume_id=b["volume_id"]),
                                pb.VacuumVolumeCheckResponse)
                return {"garbage_ratio": r.garbage_ratio}
            r = un("VacuumVolumeCompact",
                            pb.VacuumVolumeCompactRequest(
                                volume_id=b["volume_id"]),
                            pb.VacuumVolumeCompactResponse)
            return {"garbage_ratio": r.garbage_ratio,
                    "compacted": r.compacted}
        if path == "/admin/sync":
            un("VolumeSync", pb.VolumeSyncRequest(
                volume_id=b.get("volume_id", 0)), pb.VolumeSyncResponse)
            return {}
        if path == "/admin/copy_volume":
            un("VolumeCopy", pb.VolumeCopyRequest(
                volume_id=b["volume_id"],
                source_data_node=b["source_data_node"],
                collection=b.get("collection", "")), pb.VolumeCopyResponse)
            return {}
        if path == "/admin/tier_upload":
            r = un("VolumeTierMoveDatToRemote",
                            pb.VolumeTierMoveDatToRemoteRequest(
                                volume_id=b["volume_id"],
                                destination_backend_name=b["endpoint"],
                                bucket=b["bucket"],
                                keep_local_dat_file=b.get("keep_local",
                                                          False)),
                            pb.VolumeTierMoveDatToRemoteResponse)
            return {"tiered": b["volume_id"], "remote": r.remote_key}
        if path == "/admin/tier_download":
            un("VolumeTierMoveDatFromRemote",
                        pb.VolumeTierMoveDatFromRemoteRequest(
                            volume_id=b["volume_id"]),
                        pb.VolumeTierMoveDatFromRemoteResponse)
            return {}
        if path == "/admin/write_needle_blob":
            un("WriteNeedleBlob", pb.WriteNeedleBlobRequest(
                volume_id=b["volume_id"], needle_id=b["key"],
                size=b["size"], needle_blob=bytes.fromhex(b["blob"])),
                pb.WriteNeedleBlobResponse)
            return {}
        if path == "/admin/mount_volume":
            un("VolumeMount",
               pb.VolumeMountRequest(volume_id=b["volume_id"]),
               pb.VolumeMountResponse)
            return {"mounted": True}
        if path == "/admin/unmount_volume":
            un("VolumeUnmount",
               pb.VolumeUnmountRequest(volume_id=b["volume_id"]),
               pb.VolumeUnmountResponse)
            return {"unmounted": True}
        if path == "/admin/configure_replication":
            un("VolumeConfigure",
               pb.VolumeConfigureRequest(volume_id=b["volume_id"],
                                         replication=b["replication"]),
               pb.VolumeConfigureResponse)
            return {"replication": b["replication"]}
        if path == "/admin/batch_delete":
            r = un("BatchDelete", pb.BatchDeleteRequest(
                file_ids=b.get("file_ids", []),
                skip_cookie_check=b.get("skip_cookie_check", False)),
                pb.BatchDeleteResponse)
            return {"results": [
                {"file_id": x.file_id, "status": x.status,
                 "error": x.error, "size": x.size} for x in r.results]}
        if path == "/admin/ec/generate":
            r = un("VolumeEcShardsGenerate",
                            pb.VolumeEcShardsGenerateRequest(
                                volume_id=b["volume_id"],
                                collection=b.get("collection", "")),
                            pb.VolumeEcShardsGenerateResponse)
            return {"base": r.base}
        if path == "/admin/ec/rebuild":
            r = un("VolumeEcShardsRebuild",
                            pb.VolumeEcShardsRebuildRequest(
                                volume_id=b["volume_id"],
                                collection=b.get("collection", "")),
                            pb.VolumeEcShardsRebuildResponse)
            return {"rebuilt_shard_ids": list(r.rebuilt_shard_ids)}
        if path == "/admin/ec/copy":
            un("VolumeEcShardsCopy", pb.VolumeEcShardsCopyRequest(
                volume_id=b["volume_id"], collection=b.get("collection", ""),
                shard_ids=b.get("shard_ids", []),
                copy_ecx_file=b.get("copy_ecx_file", True),
                source_data_node=b["source_data_node"]),
                pb.VolumeEcShardsCopyResponse)
            return {}
        if path == "/admin/ec/delete_shards":
            un("VolumeEcShardsDelete",
                        pb.VolumeEcShardsDeleteRequest(
                            volume_id=b["volume_id"],
                            collection=b.get("collection", ""),
                            shard_ids=b.get("shard_ids", [])),
                        pb.VolumeEcShardsDeleteResponse)
            return {}
        if path == "/admin/ec/mount":
            un("VolumeEcShardsMount", pb.VolumeEcShardsMountRequest(
                volume_id=b["volume_id"], collection=b.get("collection", ""),
                shard_ids=b.get("shard_ids", [])),
                pb.VolumeEcShardsMountResponse)
            return {}
        if path == "/admin/ec/unmount":
            un("VolumeEcShardsUnmount",
                        pb.VolumeEcShardsUnmountRequest(
                            volume_id=b["volume_id"],
                            shard_ids=b.get("shard_ids", [])),
                        pb.VolumeEcShardsUnmountResponse)
            return {}
        if path == "/admin/ec/blob_delete":
            un("VolumeEcBlobDelete", pb.VolumeEcBlobDeleteRequest(
                volume_id=b["volume_id"], collection=b.get("collection", ""),
                file_key=b["needle_id"]), pb.VolumeEcBlobDeleteResponse)
            return {}
        if path == "/admin/ec/to_volume":
            un("VolumeEcShardsToVolume",
                        pb.VolumeEcShardsToVolumeRequest(
                            volume_id=b["volume_id"],
                            collection=b.get("collection", "")),
                        pb.VolumeEcShardsToVolumeResponse)
            return {}
        if path == "/admin/scrub":
            return self._json_unary("VolumeScrub", b)
        if path == "/admin/scrub/status":
            return self._json_unary("ScrubStatus", b)
        raise KeyError(f"no gRPC mapping for {path}")

    def _json_unary(self, method: str, body: dict,
                    timeout: float = 300) -> dict:
        fn = self.channel.unary_unary(
            f"/{SERVICE}/{method}",
            request_serializer=lambda d: json.dumps(d or {}).encode(),
            response_deserializer=lambda raw:
                json.loads(raw.decode()) if raw else {})
        return fn(body or {}, timeout=timeout)

    def close(self):
        self.channel.close()
