"""S3-select-style JSON scan (reference weed/query/json/query_json.go —
experimental there, functional here): run projections + predicates over
JSON-lines data stored in the object store."""

from __future__ import annotations

import json
import operator
from typing import Any, Callable, Iterator, Optional

_OPS = {
    "=": operator.eq, "==": operator.eq, "!=": operator.ne,
    ">": operator.gt, ">=": operator.ge, "<": operator.lt,
    "<=": operator.le,
}


def _get_path(doc: dict, path: str) -> Any:
    cur: Any = doc
    for part in path.split("."):
        if isinstance(cur, dict) and part in cur:
            cur = cur[part]
        else:
            return None
    return cur


class Predicate:
    def __init__(self, path: str, op: str, value: Any):
        self.path = path
        self.op = _OPS[op]
        self.value = value

    def __call__(self, doc: dict) -> bool:
        got = _get_path(doc, self.path)
        if got is None:
            return False
        try:
            return self.op(got, self.value)
        except TypeError:
            return False


def query_json_lines(data: bytes | str,
                     select: Optional[list[str]] = None,
                     where: Optional[list[Predicate]] = None,
                     limit: Optional[int] = None) -> Iterator[dict]:
    """Scan JSONL content: keep docs matching every predicate, project the
    selected dotted paths ('*' or None keeps the whole doc)."""
    if isinstance(data, bytes):
        data = data.decode()
    out_count = 0
    for line in data.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            doc = json.loads(line)
        except json.JSONDecodeError:
            continue
        if where and not all(p(doc) for p in where):
            continue
        if select and select != ["*"]:
            doc = {path: _get_path(doc, path) for path in select}
        yield doc
        out_count += 1
        if limit is not None and out_count >= limit:
            return


def parse_where(clause: str) -> list[Predicate]:
    """Parse 'a.b >= 3 AND name = "x"' into predicates."""
    preds = []
    for part in clause.split(" AND "):
        part = part.strip()
        if not part:
            continue
        for op in ("<=", ">=", "!=", "==", "=", "<", ">"):
            if op in part:
                path, _, raw = part.partition(op)
                raw = raw.strip()
                try:
                    value = json.loads(raw)
                except json.JSONDecodeError:
                    value = raw.strip('"\'')
                preds.append(Predicate(path.strip(), op, value))
                break
        else:
            raise ValueError(f"cannot parse predicate {part!r}")
    return preds
