"""Scripted incident library + machine-checked invariants.

Ten incidents, each a pure function of (seed, n_actors):

  az_loss          grey-failure prelude (scripted latency band on every
                   link), then correlated crash of one whole AZ; the
                   fleet must lose zero acked writes, re-replicate
                   within the pacing budget, keep interactive p99
                   bounded and no tenant starved.
  rolling_restart  drain -> restart every node, one AZ at a time in
                   small groups; the drain path must make this
                   invisible: ZERO failed client requests and ZERO
                   repair enqueues for drained nodes, breakers all
                   re-closed at the end.
  herd_repair      simultaneous crash of a spread of nodes (repair
                   storm) plus a scripted 5xx flap on one survivor;
                   pacing must hold (active streams never exceed the
                   budget), convergence within budget, p99 bounded,
                   breakers recovered.
  tenant_flood     one tenant floods background scans at ~4x the total
                   polite load; the governor must keep interactive p99
                   bounded, shed the flood (not the polite tenants),
                   and still leave the flooder its background slot.
  partition_heal_mid_repair
                   a spread of nodes is partitioned (blackholed, not
                   crashed) long enough to trigger a repair wave, then
                   heals while the wave drains; the master must rejoin
                   the victims, settle the half-finished wave, lose no
                   acked write, and re-close every breaker — the sim
                   rehearsal of the hinted-handoff divergence drill.
  master_failover_mid_write
                   the Raft leader dies mid-write-flood for a 6s
                   election window; the fid-range assign leases must
                   carry every write (ZERO failed client requests,
                   lease mints observed during the dark window), the
                   new leader takes over with a bumped term, and the
                   outage alone must trigger no repairs and declare
                   no node dead.
  master_failover_mid_repair
                   the leader dies while a crash-triggered repair wave
                   is mid-flight; the dead leader's streams abort, the
                   new leader re-derives the wave from its own scan and
                   finishes it — no vid rebuilt twice, no repair entry
                   lost, zero acked-write loss, convergence within the
                   budget stretched only by the election + re-detect.
  hot_shard_migration
                   one namespace directory melts its owning filer shard
                   (80% of all ops); the REAL RebalancePlanner must
                   detect the imbalance from announce-shaped telemetry,
                   emit exactly one converged move plan, and flip a
                   REAL ShardRing via the override table mid-traffic —
                   rolling_restart shape: ZERO failed client requests,
                   the hot shard's routed share collapses after the
                   flip, and the cooldown/min-share gates prevent
                   ping-pong (no second flip).
  diurnal_sweep    a virtual day for the tiering autopilot: one sealed
                   working set carries the day traffic, goes dark for
                   the "night" phase, then re-heats at dawn.  The REAL
                   TieringPlanner must walk it down the full ladder
                   (hot -> EC -> cloud) from heartbeat-shaped
                   cumulative read counters, pause outright through a
                   scripted telemetry-silence window, promote the set
                   back (cloud -> EC -> hot) when it re-heats, and
                   never touch the steady-warm set or any still-
                   writable volume — with ZERO failed client requests
                   and no ping-pong (no demotion after a promotion).
  ec_single_shard_loss
                   ONE shard holder dies under live traffic — the LRC
                   repair drill.  Hybrid incident: the sim cluster must
                   repair the lost holder with ZERO failed client ops
                   (degraded reads fail over, never fail), while the
                   code-level checks drive the real LrcCoder over every
                   single-shard erasure — group shards must plan
                   group-LOCAL repairs and the read cost must stay
                   <= 0.6x the RS(10,4) baseline of k=10 columns.

``run_incident`` returns a JSON-able report: per-invariant verdicts,
client/repair metrics, the event-log hash (bit-reproducibility), and
the sizing actually used.  Used by tests/test_macro_sim.py (16-actor
tier-1 smoke, 100-actor slow matrix) and tools/macro_sim.py.
"""

from __future__ import annotations

from seaweedfs_tpu.qos.classes import INTERACTIVE
from seaweedfs_tpu.sim.faults import parse_schedule
from seaweedfs_tpu.sim.harness import SimCluster, percentile
from seaweedfs_tpu.sim.workload import TenantSpec, ZipfWorkload, \
    default_tenants
from seaweedfs_tpu.stats.slo import FAST_BURN

# interactive p99 ceiling (virtual seconds) for every incident: service
# time is ~4ms, so 250ms allows one failover + backoff but not collapse
P99_BOUND_S = 0.25
# partition incidents pay one full wedged-peer read timeout (0.6s in
# FilerActor) + failover service time on the unlucky tail read; crash
# incidents never do (a dead socket refuses instantly)
P99_PARTITION_BOUND_S = 0.75
TENANT_MIN_OK_RATIO = 0.85


def _check(name: str, ok: bool, detail: str) -> dict:
    return {"name": name, "ok": bool(ok), "detail": detail}


def _common_invariants(cluster: SimCluster, checks: list) -> None:
    lost = cluster.lost_acked_writes()
    checks.append(_check(
        "zero_acked_write_loss", not lost,
        f"{len(lost)} acked writes unreadable" if lost
        else f"{len(cluster.metrics.acked)} acked writes all readable"))
    p99 = percentile(cluster.metrics.lat[INTERACTIVE], 0.99)
    checks.append(_check(
        "interactive_p99_bounded", p99 <= P99_BOUND_S,
        f"p99={p99 * 1000:.1f}ms bound={P99_BOUND_S * 1000:.0f}ms"))


def _tenant_invariant(cluster: SimCluster, checks: list,
                      exclude=()) -> None:
    worst_name, worst = "", 1.0
    for t, (ok, fail) in sorted(cluster.metrics.tenants.items()):
        if t in exclude or ok + fail == 0:
            continue
        ratio = ok / (ok + fail)
        if ratio < worst:
            worst_name, worst = t, ratio
    checks.append(_check(
        "no_tenant_starvation", worst >= TENANT_MIN_OK_RATIO,
        f"worst tenant {worst_name or 'n/a'} ok-ratio {worst:.3f} "
        f"(floor {TENANT_MIN_OK_RATIO})"))


def _slo_invariants(cluster: SimCluster, checks: list,
                    expect_cls: str) -> None:
    """The burn-rate judge must page during the incident (fast-burn on
    the class the script degrades) and stand down once healed."""
    fired = [(t, cls) for t, cls, _old, new in cluster.slo.timeline()
             if new == FAST_BURN and cls == expect_cls]
    checks.append(_check(
        "slo_fast_burn_fired", bool(fired),
        f"{expect_cls} fast-burn paged at t={fired[0][0]:.1f}s"
        if fired else f"no fast-burn transition for {expect_cls} "
                      f"(timeline: {cluster.slo.timeline()[:6]})"))
    firing = cluster.slo.firing()
    checks.append(_check(
        "slo_resolved_after_heal", not firing,
        f"still firing at end: {firing}" if firing
        else "all classes back to ok"))


def _breaker_invariant(cluster: SimCluster, checks: list) -> None:
    bad = cluster.open_breakers()
    checks.append(_check(
        "breakers_reclosed", not bad,
        f"open against live nodes: {bad[:4]}" if bad
        else "all filer breakers closed against live nodes"))


def _convergence_invariant(cluster: SimCluster, checks: list,
                           t_fault: float, n_repairs_expected: int) -> None:
    m = cluster.master
    copy_s = (cluster.volumes[0].base_volume_bytes
              / m.repair_stream_bw + 0.1)
    # detection (5 pulses + tick quantization) + continuous scan grace
    # + paced copy waves with 3.5x slack for backoff/stagger + settle
    budget = (12.0 + m.repair_grace_s
              + 3.5 * n_repairs_expected * copy_s / m.max_repair_streams
              + 15.0)
    took = (m.converged_at - t_fault) if m.converged_at else None
    checks.append(_check(
        "repair_converged_in_budget",
        took is not None and took <= budget,
        f"converged in {took:.1f}s (budget {budget:.1f}s, "
        f"{m.repairs_done} repairs)" if took is not None
        else f"NOT converged (queue={len(m._queue)} "
             f"active={len(m._active)} degraded={len(cluster.degraded_vids())})"))
    checks.append(_check(
        "repair_pacing_held",
        m.repair_active_max <= m.max_repair_streams,
        f"max active streams {m.repair_active_max} "
        f"<= budget {m.max_repair_streams}"))


def _settle(cluster: SimCluster, workload: ZipfWorkload, t0: float,
            duration: float) -> None:
    """Light post-incident traffic so half-open probes have something
    to ride on (breakers only transition on real calls)."""
    rate = max(8.0, 0.5 * len(cluster.volumes))
    ops = ZipfWorkload([TenantSpec("settle", rate)],
                       seed=cluster.kernel.seed + 7,
                       keyspace=workload.keyspace).generate(duration)
    for op in ops:
        op.t += t0
    cluster.load(ops)


# ---------------------------------------------------------------- incidents

def _az_loss(cluster: SimCluster, n_actors: int, rate: float) -> list:
    duration, t_fault = 40.0, 12.0
    schedule = [{"link": "*->*", "fault": "latency", "start": 8.0,
                 "duration": 4.0, "latency_ms": 60}]
    cluster.faults.events[:] = parse_schedule(schedule)
    wl = ZipfWorkload(default_tenants(4, rate), seed=cluster.kernel.seed)
    cluster.load(wl.generate(duration))
    cluster.at(t_fault, cluster.crash_az, 0)
    cluster.run(duration)
    n_lost = len(cluster.az_nodes(0))
    degraded = sum(1 for vid, holders in cluster.master.layout.items()
                   if any(cluster.actor(h).crashed for h in holders))
    _settle(cluster, wl, duration, 30.0)
    cluster.run_until_converged(duration + 90.0)
    cluster.run(cluster.kernel.now + 8.0)  # let probes settle
    checks: list = []
    _common_invariants(cluster, checks)
    _tenant_invariant(cluster, checks)
    _convergence_invariant(cluster, checks, t_fault, degraded)
    checks.append(_check(
        "az_dead_detected", len(cluster.master.dead) == n_lost,
        f"{len(cluster.master.dead)}/{n_lost} lost nodes declared dead"))
    # the grey-failure band (60ms on every link) pushes interactive ops
    # past their 50ms sim target, so the fast window must page — and
    # the healed, converged fleet must resolve it
    _slo_invariants(cluster, checks, INTERACTIVE)
    return checks


def _rolling_restart(cluster: SimCluster, n_actors: int,
                     rate: float) -> list:
    wl = ZipfWorkload(default_tenants(4, rate), seed=cluster.kernel.seed)

    def roll():
        yield 6.0  # warmup traffic first
        for az in range(cluster.n_az):
            nodes = cluster.az_nodes(az)
            group = max(1, len(nodes) // 4)
            for i in range(0, len(nodes), group):
                batch = nodes[i:i + group]
                drains = [cluster.kernel.spawn(
                    cluster.actor(n).drain()) for n in batch]
                yield drains
                yield 3.0  # process down: restart delay
                for n in batch:
                    cluster.restore(n)
                yield 2.0  # re-register + settle before next batch

    driver = cluster.kernel.spawn(roll())
    # traffic must cover the whole wave: 4 az * 4 groups * ~6s
    duration = 6.0 + cluster.n_az * 4 * 6.5 + 10.0
    cluster.load(wl.generate(duration))
    cluster.run(duration)
    if not driver.done:  # pragma: no cover - sizing guard
        raise RuntimeError("rolling restart did not finish in window")
    _settle(cluster, wl, duration, 10.0)
    cluster.run(duration + 12.0)  # 2x breaker open_for + probe traffic
    checks: list = []
    _common_invariants(cluster, checks)
    checks.append(_check(
        "zero_failed_client_requests", cluster.metrics.fail_total == 0,
        f"{cluster.metrics.fail_total} failed ops "
        f"(samples: {cluster.metrics.fail_samples[:3]})"
        if cluster.metrics.fail_total else
        f"all {cluster.metrics.ops_total()} ops succeeded"))
    enq = cluster.master.repair_enqueued_for
    checks.append(_check(
        "zero_repairs_for_drained_nodes", not enq,
        f"repairs enqueued for {dict(enq)}" if enq
        else "repair queue never saw a drained node"))
    _breaker_invariant(cluster, checks)
    _tenant_invariant(cluster, checks)
    return checks


def _herd_repair(cluster: SimCluster, n_actors: int, rate: float) -> list:
    duration, t_fault = 40.0, 10.0
    victims = [f"vol-{i}" for i in range(0, n_actors, 7)]
    flapper = f"vol-{3 % n_actors}"
    schedule = [{"link": f"*->{flapper}", "fault": "http_error",
                 "start": 14.0, "duration": 5.0, "status": 503}]
    cluster.faults.events[:] = parse_schedule(schedule)
    wl = ZipfWorkload(default_tenants(4, rate), seed=cluster.kernel.seed)
    cluster.load(wl.generate(duration))

    def herd():
        yield t_fault
        cluster.kernel.note("incident", "herd_crash", str(len(victims)))
        for v in victims:
            cluster.crash(v)

    cluster.kernel.spawn(herd())
    cluster.run(duration)
    degraded = sum(1 for vid, holders in cluster.master.layout.items()
                   if any(cluster.actor(h).crashed for h in holders))
    _settle(cluster, wl, duration, 30.0)
    cluster.run_until_converged(duration + 120.0)
    cluster.run(cluster.kernel.now + 8.0)
    checks: list = []
    _common_invariants(cluster, checks)
    _convergence_invariant(cluster, checks, t_fault, degraded)
    _breaker_invariant(cluster, checks)
    _tenant_invariant(cluster, checks)
    return checks


def _tenant_flood(cluster: SimCluster, n_actors: int, rate: float) -> list:
    duration = 40.0
    # 30x: enough queueing collateral to push interactive past its
    # latency target at cliff rate (fast-burn pages) even at the
    # 16-actor smoke scale, while the governor still sheds the flood
    tenants = default_tenants(4, rate, flood_tenant="flooder",
                              flood_rate=30.0 * rate)
    wl = ZipfWorkload(tenants, seed=cluster.kernel.seed)
    cluster.load(wl.generate(duration))
    cluster.run(duration + 5.0)
    # heal = the flood simply stops; polite settle traffic carries the
    # burn windows back down so the alert must resolve
    _settle(cluster, wl, duration + 5.0, 15.0)
    cluster.run(duration + 25.0)
    checks: list = []
    _common_invariants(cluster, checks)
    _tenant_invariant(cluster, checks, exclude=("flooder", "settle"))
    fl_ok, _fl_fail = cluster.metrics.tenants.get("flooder", (0, 0))
    fl_shed = cluster.metrics.sheds.get("flooder", 0)
    polite_shed = sum(n for t, n in cluster.metrics.sheds.items()
                      if t != "flooder")
    checks.append(_check(
        "flood_was_shed", fl_shed > 0 and fl_shed >= 10 * max(1, polite_shed),
        f"flooder shed {fl_shed}x vs polite tenants {polite_shed}x"))
    checks.append(_check(
        "flood_not_fully_starved", fl_ok > 0,
        f"flooder still completed {fl_ok} background ops"))
    # the judged class is interactive: the governor sheds the flood
    # (background mostly retries to completion), but the queueing
    # collateral pushes interactive ops past their latency target at
    # cliff rate — exactly the page an operator wants from a flood
    _slo_invariants(cluster, checks, INTERACTIVE)
    return checks


def _partition_heal_mid_repair(cluster: SimCluster, n_actors: int,
                               rate: float) -> list:
    """Network partition (not a crash): a spread of nodes goes dark on
    the wire long enough for the master to declare them dead and start
    a repair wave, then the partition heals while the wave is still
    draining. The victims' heartbeats resume, the master must rejoin
    them (dead set emptied), the half-finished wave must settle without
    wedging (queue and active drain, degraded set clears), no acked
    write may be lost, and breakers against the healed nodes must
    re-close. This is the sim rehearsal of the hinted-handoff drill:
    writes during the partition succeed on the surviving quorum, and
    heal-time repair closes the divergence window."""
    # part_len is tuned so the heal lands while the wave is still
    # draining: dead declared ~t_part+10, scan grace 5s, so the wave
    # starts ~t_part+15 and the heal at t_part+18 catches it mid-queue.
    # That matters beyond fidelity to the name — a completed repair
    # removes the dead holder from the layout, so a partition long
    # enough to re-home EVERY victim volume leaves the healed nodes
    # holding nothing, and no traffic (hence no breaker probe) ever
    # dials them again
    duration, t_part, part_len = 45.0, 8.0, 18.0
    victims = [f"vol-{i}" for i in range(0, n_actors, 9)]
    schedule = []
    for v in victims:
        # both directions: outbound kills the victim's heartbeats,
        # inbound kills client and repair traffic to it
        schedule.append({"link": f"{v}->*", "fault": "blackhole",
                         "start": t_part, "duration": part_len})
        schedule.append({"link": f"*->{v}", "fault": "blackhole",
                         "start": t_part, "duration": part_len})
    cluster.faults.events[:] = parse_schedule(schedule)
    wl = ZipfWorkload(default_tenants(4, rate), seed=cluster.kernel.seed)
    cluster.load(wl.generate(duration))
    # run exactly to the heal instant and snapshot the repair plane:
    # the wave must already be engaged when the partition lifts
    cluster.run(t_part + part_len)
    m = cluster.master
    wave_at_heal = (len(m._queue), len(m._active), m.repairs_done)
    dead_at_heal = sorted(m.dead)
    cluster.run(duration)
    _settle(cluster, wl, duration, 30.0)
    cluster.run_until_converged(duration + 90.0)
    # consume the whole settle window: the wave often finishes BEFORE
    # the heal (converged almost immediately), and breaker probes only
    # ride real traffic
    cluster.run(max(cluster.kernel.now + 8.0, duration + 32.0))
    checks: list = []
    lost = cluster.lost_acked_writes()
    checks.append(_check(
        "zero_acked_write_loss", not lost,
        f"{len(lost)} acked writes unreadable" if lost
        else f"{len(cluster.metrics.acked)} acked writes all readable"))
    # a blackholed peer (unlike a crashed one) answers nothing: the
    # first read to touch it pays its full timeout before failing over,
    # so the honest p99 ceiling is one wedged-peer timeout + failover,
    # not the steady-state bound — collapse would still blow through it
    p99 = percentile(cluster.metrics.lat[INTERACTIVE], 0.99)
    checks.append(_check(
        "interactive_p99_bounded", p99 <= P99_PARTITION_BOUND_S,
        f"p99={p99 * 1000:.1f}ms "
        f"bound={P99_PARTITION_BOUND_S * 1000:.0f}ms"))
    _tenant_invariant(cluster, checks)
    checks.append(_check(
        "partition_detected", bool(dead_at_heal),
        f"{len(dead_at_heal)}/{len(victims)} victims declared dead "
        f"during the partition" if dead_at_heal
        else "master never declared a victim dead"))
    checks.append(_check(
        "repair_wave_engaged_before_heal", any(wave_at_heal),
        f"at heal: queued={wave_at_heal[0]} active={wave_at_heal[1]} "
        f"done={wave_at_heal[2]}"))
    still_dead = [v for v in victims if v in m.dead]
    checks.append(_check(
        "victims_rejoined_after_heal", not still_dead,
        f"still dead: {still_dead}" if still_dead
        else f"all {len(victims)} victims heartbeating again"))
    checks.append(_check(
        "repair_wave_settled", not m._queue and not m._active
        and not cluster.degraded_vids(),
        f"queue={len(m._queue)} active={len(m._active)} "
        f"degraded={len(cluster.degraded_vids())}"))
    _breaker_invariant(cluster, checks)
    return checks


def _ec_single_shard_loss(cluster: SimCluster, n_actors: int,
                          rate: float) -> list:
    """Single-shard-loss repair drill, the LRC headline case.  The
    macro sim models whole volume holders (not individual EC shard
    files), so the incident is a hybrid: the cluster loses ONE holder
    under live traffic — the single-shard-loss analogue — and must
    repair it with zero failed client operations, degraded reads
    failing over rather than failing.  The code-level invariants then
    run the REAL LrcCoder over every single-shard erasure pattern: the
    planner must choose the group-local strategy for every shard that
    lives in a local group (data 0-9 + local parities 10-11), the mean
    plan read cost across all 14 losses must stay <= 0.6x the RS(10,4)
    baseline of k=10 columns, and a plan-driven rebuild must be
    bit-identical to the lost shard."""
    import numpy as np

    from seaweedfs_tpu.ops.lrc import LrcCoder

    duration, t_fault = 35.0, 10.0
    wl = ZipfWorkload(default_tenants(4, rate), seed=cluster.kernel.seed)
    cluster.load(wl.generate(duration))
    victim = f"vol-{5 % n_actors}"
    cluster.at(t_fault, cluster.crash, victim)
    cluster.run(duration)
    degraded = sum(1 for vid, holders in cluster.master.layout.items()
                   if any(cluster.actor(h).crashed for h in holders))
    _settle(cluster, wl, duration, 30.0)
    cluster.run_until_converged(duration + 90.0)
    cluster.run(cluster.kernel.now + 8.0)
    checks: list = []
    _common_invariants(cluster, checks)
    checks.append(_check(
        "zero_failed_degraded_reads", cluster.metrics.fail_total == 0,
        f"{cluster.metrics.fail_total} failed ops mid-repair "
        f"(samples: {cluster.metrics.fail_samples[:3]})"
        if cluster.metrics.fail_total else
        f"all {cluster.metrics.ops_total()} ops succeeded while "
        f"{victim} was down"))
    _tenant_invariant(cluster, checks)
    _convergence_invariant(cluster, checks, t_fault, degraded)
    _breaker_invariant(cluster, checks)

    # ---- code-level repair-plan invariants (real LrcCoder) ----
    coder = LrcCoder()
    spec = coder.scheme
    total, k = spec.total_shards, spec.data_shards
    group_sids: set = set()
    for g in range(spec.local_groups):
        group_sids.update(spec.group_members(g))
    strategies, reads = {}, []
    for sid in range(total):
        st = coder.repair_strategy(
            [s for s in range(total) if s != sid], [sid])
        strategies[sid] = st["strategy"]
        reads.append(st["reads"])
    bad = [s for s in sorted(group_sids) if strategies[s] != "local"]
    checks.append(_check(
        "lrc_local_strategy_for_group_shards", not bad,
        f"group shards planned globally: {bad}" if bad else
        f"all {len(group_sids)} group shards plan group-local repairs "
        f"({spec.group_size} reads each)"))
    mean_reads = sum(reads) / len(reads)
    ratio = mean_reads / k
    checks.append(_check(
        "lrc_read_cost_vs_rs", ratio <= 0.6,
        f"mean plan reads {mean_reads:.2f} cols vs RS baseline {k} "
        f"-> ratio {ratio:.3f} (ceiling 0.6)"))
    rng = np.random.default_rng(cluster.kernel.seed)
    data = rng.integers(0, 256, size=(k, 512), dtype=np.uint8)
    shards = coder.encode([data[i].tobytes() for i in range(k)])
    sid = 5 % total
    src, mat = coder.plan_rebuild(
        [s for s in range(total) if s != sid], [sid])
    rec = coder.reconstruct_rows(
        np.stack([np.frombuffer(shards[s], dtype=np.uint8)
                  for s in src]), mat)
    checks.append(_check(
        "lrc_repair_bit_identical",
        rec[0].tobytes() == bytes(shards[sid]),
        f"shard {sid} rebuilt bit-identically from "
        f"{len(src)} group columns"))
    return checks


def _master_failover_mid_write(cluster: SimCluster, n_actors: int,
                               rate: float) -> list:
    """The headline lease drill: the Raft leader dies under a full
    write flood. Holders hold epoch-stamped fid-range leases renewed
    every heartbeat (TTL 15x the pulse), so local minting rides out
    any election window shorter than the TTL — the dark master must
    cost ZERO failed client requests. Reads survive on follower-served
    lookups; the new leader's bumped term proves the failover actually
    happened rather than the window being too gentle to notice."""
    duration, t_fail, outage = 40.0, 12.0, 6.0
    wl = ZipfWorkload(default_tenants(4, rate), seed=cluster.kernel.seed)
    cluster.load(wl.generate(duration))
    cluster.run(t_fail)
    mints_before = cluster.metrics.lease_mints
    cluster.fail_master_leader(outage)
    cluster.run(t_fail + outage)
    mints_during = cluster.metrics.lease_mints - mints_before
    cluster.run(duration)
    _settle(cluster, wl, duration, 10.0)
    cluster.run(duration + 12.0)
    m = cluster.master
    checks: list = []
    _common_invariants(cluster, checks)
    checks.append(_check(
        "zero_failed_client_requests", cluster.metrics.fail_total == 0,
        f"{cluster.metrics.fail_total} failed ops "
        f"(samples: {cluster.metrics.fail_samples[:3]})"
        if cluster.metrics.fail_total else
        f"all {cluster.metrics.ops_total()} ops succeeded across the "
        f"{outage:.0f}s election window"))
    checks.append(_check(
        "writes_minted_during_outage", mints_during > 0,
        f"{mints_during} fids minted from leases while the "
        f"leader was dark"))
    checks.append(_check(
        "leader_took_over", m.term == 2,
        f"term={m.term} (takeover {'happened' if m.term == 2 else 'MISSING'})"))
    checks.append(_check(
        "no_spurious_repairs", m.repairs_done == 0 and not m.dead,
        f"repairs={m.repairs_done} dead={sorted(m.dead)}"
        if m.repairs_done or m.dead else
        "election window triggered no repair and declared nobody dead"))
    _tenant_invariant(cluster, checks)
    _breaker_invariant(cluster, checks)
    return checks


def _master_failover_mid_repair(cluster: SimCluster, n_actors: int,
                                rate: float) -> list:
    """Cascading failover: a herd crash puts a repair wave in flight,
    then the leader coordinating that wave dies. The dead leader's
    streams abort at their next yield (they belong to the old
    incarnation); the new leader starts with an empty queue and must
    re-derive the remaining work from its own degraded scan — repairs
    already committed to the replicated layout are not redone (no vid
    rebuilt twice), repairs not yet committed are not forgotten (the
    fleet still converges)."""
    duration, t_crash, t_leader, outage = 45.0, 10.0, 27.0, 6.0
    victims = [f"vol-{i}" for i in range(0, n_actors, 7)]
    wl = ZipfWorkload(default_tenants(4, rate), seed=cluster.kernel.seed)
    cluster.load(wl.generate(duration))

    def herd():
        yield t_crash
        cluster.kernel.note("incident", "herd_crash", str(len(victims)))
        for v in victims:
            cluster.crash(v)

    cluster.kernel.spawn(herd())
    # run exactly to the leader failure and snapshot the repair plane:
    # the wave must already be engaged when the leader dies, or the
    # incident degenerates into plain herd_repair
    cluster.run(t_leader)
    m = cluster.master
    wave_at_fail = (len(m._queue), len(m._active), m.repairs_done)
    cluster.fail_master_leader(outage)
    cluster.run(duration)
    degraded = sum(1 for vid, holders in cluster.master.layout.items()
                   if any(cluster.actor(h).crashed for h in holders))
    _settle(cluster, wl, duration, 30.0)
    cluster.run_until_converged(duration + 120.0)
    cluster.run(cluster.kernel.now + 8.0)
    checks: list = []
    _common_invariants(cluster, checks)
    checks.append(_check(
        "repair_wave_engaged_before_failover", any(wave_at_fail),
        f"at leader death: queued={wave_at_fail[0]} "
        f"active={wave_at_fail[1]} done={wave_at_fail[2]}"))
    checks.append(_check(
        "leader_took_over", m.term == 2,
        f"term={m.term}"))
    dup = {v: n for v, n in m.repair_log.items() if n > 1}
    checks.append(_check(
        "no_duplicate_rebuilds", not dup,
        f"vids rebuilt more than once: {dup}" if dup else
        f"{len(m.repair_log)} vids rebuilt exactly once across terms"))
    checks.append(_check(
        "repair_wave_settled", not m._queue and not m._active
        and not cluster.degraded_vids(),
        f"queue={len(m._queue)} active={len(m._active)} "
        f"degraded={len(cluster.degraded_vids())}"))
    # standard pacing budget from the crash instant, stretched by the
    # election window plus one liveness re-detection cycle (takeover
    # resets every node's clock, so the dead are re-declared ~10s +
    # scan grace later)
    copy_s = (cluster.volumes[0].base_volume_bytes
              / m.repair_stream_bw + 0.1)
    budget = (12.0 + m.repair_grace_s
              + 3.5 * degraded * copy_s / m.max_repair_streams
              + 15.0 + outage + 12.0 + m.repair_grace_s)
    took = (m.converged_at - t_crash) if m.converged_at else None
    checks.append(_check(
        "repair_converged_in_budget",
        took is not None and took <= budget,
        f"converged in {took:.1f}s (budget {budget:.1f}s, "
        f"{m.repairs_done} repairs across terms)" if took is not None
        else f"NOT converged (queue={len(m._queue)} "
             f"active={len(m._active)} "
             f"degraded={len(cluster.degraded_vids())})"))
    checks.append(_check(
        "repair_pacing_held",
        m.repair_active_max <= m.max_repair_streams,
        f"max active streams {m.repair_active_max} "
        f"<= budget {m.max_repair_streams}"))
    _breaker_invariant(cluster, checks)
    _tenant_invariant(cluster, checks)
    return checks


def _hot_shard_migration(cluster: SimCluster, n_actors: int,
                         rate: float) -> list:
    """Temperature-driven directory migration, closed loop.  The sim's
    filers are client-side drivers (no namespace service plane), so the
    namespace layer is modeled HERE with the real production pieces:
    ops route to the filer owning their directory per a real ShardRing,
    per-shard counters feed a real RebalancePlanner at announce
    cadence, and a modeled mover (copy delay, then commit) flips the
    ring with a real ``with_overrides`` epoch bump.  One directory
    carries 80% of the load, melting its hash-owner; the planner must
    move it to the coolest shard with zero failed client ops
    (rolling_restart shape) and then STOP — the cooldown and min-share
    gates must prevent the destination (now hottest by construction)
    from shedding crumbs forever."""
    from seaweedfs_tpu.filer.rebalance import RebalancePlanner
    from seaweedfs_tpu.filer.shard_ring import ShardRing

    duration = 40.0
    hot_dir = "/zipf/hot"
    names = [f.name for f in cluster.filers]
    by_name = {f.name: f for f in cluster.filers}
    ring = [ShardRing(names)]  # one-slot holder: the flip swaps it
    hot_owner = ring[0].owner(hot_dir)
    planner = RebalancePlanner(window_s=8.0, threshold=1.5,
                               min_rate=2.0, cooldown_s=60.0)
    ops_cum = {n: 0 for n in names}
    dirs_cum: dict = {n: {} for n in names}
    routed = {"pre": {n: 0 for n in names},
              "post": {n: 0 for n in names}}
    flips: list = []

    def dir_of(op) -> str:
        # 80% of ops hammer one directory; the rest spread over 97
        # buckets so every shard has a pulse (the planner refuses to
        # plan over members it has no rate for)
        if op.key % 10 < 8:
            return hot_dir
        return "/zipf/b%03d" % (op.key % 97)

    def dispatch(op) -> None:
        owner = ring[0].owner(dir_of(op))
        ops_cum[owner] += 1
        dc = dirs_cum[owner]
        d = dir_of(op)
        dc[d] = dc.get(d, 0) + 1
        routed["post" if flips else "pre"][owner] += 1
        cluster._start_op(by_name[owner], op)

    wl = ZipfWorkload(default_tenants(4, rate), seed=cluster.kernel.seed)
    for op in wl.generate(duration):
        cluster.kernel.schedule(op.t, dispatch, op)

    def control_loop():
        # the master's announce-ingest cadence: every 2s each shard
        # reports cumulative ops + top directories, then the planner
        # gets one shot at the current ring
        while cluster.kernel.now < duration:
            yield 2.0
            now = cluster.kernel.now
            for n in names:
                top = sorted(dirs_cum[n].items(),
                             key=lambda kv: (-kv[1], kv[0]))[:8]
                planner.observe(
                    n, {"ops": ops_cum[n],
                        "dirs": [{"key": d, "count": c}
                                 for d, c in top]}, now=now)
            plan = planner.plan(ring[0], now=now)
            if plan is None:
                continue

            def mover(moves=plan["moves"]):
                yield 1.5  # modeled copy + delta drain before commit
                new = ring[0].with_overrides(
                    {m["dir"]: m["to"] for m in moves})
                assert new.epoch > ring[0].epoch
                ring[0] = new
                for m in moves:
                    planner.note_committed(m["dir"],
                                           now=cluster.kernel.now)
                flips.append((cluster.kernel.now, list(moves)))
                cluster.kernel.note("incident", "ring_flip",
                                    f"epoch={new.epoch}")

            cluster.kernel.spawn(mover())

    cluster.kernel.spawn(control_loop())
    cluster.run(duration)
    _settle(cluster, wl, duration, 10.0)
    cluster.run(duration + 12.0)
    checks: list = []
    _common_invariants(cluster, checks)
    checks.append(_check(
        "zero_failed_client_requests", cluster.metrics.fail_total == 0,
        f"{cluster.metrics.fail_total} failed ops "
        f"(samples: {cluster.metrics.fail_samples[:3]})"
        if cluster.metrics.fail_total else
        f"all {cluster.metrics.ops_total()} ops succeeded across "
        f"{len(flips)} ring flip(s)"))
    moved = flips and any(m["dir"] == hot_dir
                          for _, mv in flips for m in mv)
    checks.append(_check(
        "planner_moved_hot_directory",
        bool(moved) and ring[0].overrides.get(hot_dir) not in (
            None, hot_owner),
        f"hot dir {hot_dir}: {hot_owner} -> "
        f"{ring[0].overrides.get(hot_dir)} at "
        f"t={flips[0][0]:.1f}s (ring epoch {ring[0].epoch})"
        if flips else "planner never flipped the ring"))
    pre_n, post_n = sum(routed["pre"].values()), sum(routed["post"].values())
    pre_share = routed["pre"][hot_owner] / pre_n if pre_n else 0.0
    post_share = routed["post"][hot_owner] / post_n if post_n else 1.0
    checks.append(_check(
        "hot_shard_share_collapsed",
        pre_share >= 0.5 and post_share <= 0.35,
        f"{hot_owner} routed share {pre_share:.2f} pre-flip -> "
        f"{post_share:.2f} post-flip "
        f"({pre_n} pre / {post_n} post ops)"))
    # under zipf a couple of second-tier directories are individually
    # warm, so follow-up spread moves are legitimate — thrash is a
    # directory moving TWICE (ping-pong) or the planner never settling
    moved_dirs = [m["dir"] for _, mv in flips for m in mv]
    checks.append(_check(
        "no_ping_pong",
        len(set(moved_dirs)) == len(moved_dirs) and len(flips) <= 3,
        f"{len(flips)} flips, moved {moved_dirs} "
        f"(each dir at most once, <=3 plans)"))
    _tenant_invariant(cluster, checks)
    _breaker_invariant(cluster, checks)
    return checks


def _diurnal_sweep(cluster: SimCluster, n_actors: int,
                   rate: float) -> list:
    """A virtual day for the tiering autopilot, closed loop.  The sim's
    volume actors have no rung state, so the storage tier is modeled
    HERE around the real production planner: per-vid cumulative read
    counters (heartbeat telemetry shape) feed a real ``TieringPlanner``
    at announce cadence, and a modeled mover (copy+verify delay, then
    commit) applies rung transitions.  Working set A is sealed and
    carries the day traffic; it must ride the full ladder down
    (hot -> EC -> cloud) overnight and climb back (cloud -> EC -> hot)
    at dawn.  A steady-warm sealed set B and the writable background
    volumes must never move, a scripted telemetry-silence window must
    pause planning outright, and the whole day costs zero failed
    client ops."""
    from seaweedfs_tpu.storage.tiering import (RUNG_CLOUD, RUNG_EC,
                                               RUNG_HOT, TieringPlanner)

    ladder = (RUNG_HOT, RUNG_EC, RUNG_CLOUD)
    day_end, night_end, duration = 14.0, 40.0, 60.0
    sil_start, sil_end = 16.0, 22.5    # telemetry goes dark overnight
    move_bytes = 64 << 20              # modeled .dat size per move
    n_vids = cluster.n_vids
    set_a = tuple(range(0, 6))         # diurnal set (sealed)
    # the steady set is kept small so its per-vid rate (30% of load
    # over 3 vids) sits far above the cool band — at 16 actors a
    # 6-vid steady set leaves each vid thin enough that an honest
    # multi-bin traffic lull reads as genuine cooling
    set_b = tuple(range(6, 9))         # steady-warm set (sealed)
    bg = tuple(range(9, n_vids))       # writable background
    sealed = set(set_a) | set(set_b)

    # thresholds scale with the offered per-vid hot rate so the same
    # EWMA half-life walks the bands at any actor count
    hot_rate = 0.6 * rate / len(set_a)
    planner = TieringPlanner(
        window_s=5.0, ewma_alpha=0.5,
        cool_max=0.15 * hot_rate, cold_max=0.013 * hot_rate,
        heat_min=0.5 * hot_rate, min_age_s=8.0, cooldown_s=6.0,
        max_moves_per_plan=len(set_a), cloud_enabled=True)

    rung = {vid: RUNG_HOT for vid in range(n_vids)}
    has_shards = {vid: False for vid in range(n_vids)}
    reads_cum = {vid: 0 for vid in range(n_vids)}
    moves_log: list = []
    planned_in_silence: list = []
    last_obs = [0.0]
    seq = [0]

    def a_is_hot(now: float) -> bool:
        return now < day_end or now >= night_end

    def dispatch(op) -> None:
        # 60% of traffic follows the diurnal set (sleeping on the
        # background volumes overnight), 30% holds set B steady-warm,
        # the rest trickles over the writable background.  The split
        # mixes a dispatch counter into the hash so it is uniform
        # per-op (zipf keys alone are too concentrated to warm every
        # vid of a set), and stays a pure function of the seed.
        h = ((op.key * 1103515245 + 12345)
             ^ (seq[0] * 2654435761)) & 0x7FFFFFFF
        seq[0] += 1
        r, base = h % 10, h // 10
        now = cluster.kernel.now
        if r < 6 and a_is_hot(now):
            vid = set_a[base % len(set_a)]
        elif 6 <= r < 9:
            vid = set_b[base % len(set_b)]
        else:
            vid = bg[base % len(bg)]
        op.key = base * n_vids + vid   # FilerActor routes key % n_vids
        reads_cum[vid] += 1
        cluster._start_op(cluster.filers[base % len(cluster.filers)], op)

    wl = ZipfWorkload(default_tenants(4, rate), seed=cluster.kernel.seed)
    for op in wl.generate(duration):
        cluster.kernel.schedule(op.t, dispatch, op)

    def mover(move):
        yield 1.0  # modeled stream + verify-before-delete readback
        vid = move["vid"]
        rung[vid] = move["to"]
        if move["to"] == RUNG_EC:
            has_shards[vid] = True  # encode keeps shards alongside
        elif move["to"] == RUNG_HOT:
            has_shards[vid] = False
        planner.note_committed(vid, now=cluster.kernel.now)
        moves_log.append((cluster.kernel.now, move))
        cluster.kernel.note("incident", "tier_move",
                            f"vid={vid} {move['from']}->{move['to']}")

    def control_loop():
        # the master's heartbeat-ingest cadence: every 2s one modeled
        # volume server reports cumulative reads + rung state, then
        # the planner gets one shot
        while cluster.kernel.now < duration:
            yield 2.0
            now = cluster.kernel.now
            if not (sil_start <= now < sil_end):
                planner.observe("vs-sim", {"volumes": {
                    vid: {"reads": reads_cum[vid], "rung": rung[vid],
                          "size": move_bytes,
                          "read_only": vid in sealed,
                          "has_ec_shards": has_shards[vid]}
                    for vid in range(n_vids)}}, now=now)
                last_obs[0] = now
            plan = planner.plan(now=now)
            if plan is None:
                continue
            if now - last_obs[0] > planner.window_s:
                planned_in_silence.append(now)  # must stay empty
                continue
            for m in plan["moves"]:
                cluster.kernel.spawn(mover(m))

    cluster.kernel.spawn(control_loop())
    cluster.run(duration)
    _settle(cluster, wl, duration, 10.0)
    cluster.run(duration + 12.0)

    checks: list = []
    _common_invariants(cluster, checks)
    checks.append(_check(
        "zero_failed_client_requests", cluster.metrics.fail_total == 0,
        f"{cluster.metrics.fail_total} failed ops "
        f"(samples: {cluster.metrics.fail_samples[:3]})"
        if cluster.metrics.fail_total else
        f"all {cluster.metrics.ops_total()} ops succeeded across "
        f"{len(moves_log)} tier move(s)"))
    by_vid: dict = {}
    for t, m in moves_log:
        by_vid.setdefault(m["vid"], []).append((t, m))
    reached_cloud = [v for v in set_a
                     if any(m["to"] == RUNG_CLOUD
                            for _, m in by_vid.get(v, []))]
    checks.append(_check(
        "cooled_set_reached_cloud", len(reached_cloud) == len(set_a),
        f"{len(reached_cloud)}/{len(set_a)} diurnal vids demoted to "
        f"the cloud rung overnight"))
    back_hot = [v for v in set_a if rung[v] == RUNG_HOT]
    checks.append(_check(
        "reheated_set_promoted_home", len(back_hot) == len(set_a),
        f"{len(back_hot)}/{len(set_a)} diurnal vids back on the hot "
        f"rung at dusk (end rungs: "
        f"{sorted(set(rung[v] for v in set_a))})"))
    strays = sorted(set(by_vid) - set(set_a))
    checks.append(_check(
        "only_diurnal_set_moved", not strays,
        f"steady-warm + writable volumes untouched "
        f"({len(moves_log)} moves, all within the diurnal set)"
        if not strays else f"unexpected moves for vids {strays}"))
    ping_pong = []
    for v, seq in by_vid.items():
        demoting = [ladder.index(m["to"]) > ladder.index(m["from"])
                    for _, m in seq]
        # a day is one descent then one climb: any demotion after the
        # first promotion is thrash
        first_promo = demoting.index(False) if False in demoting \
            else len(demoting)
        if len(seq) > 4 or any(demoting[first_promo:]):
            ping_pong.append(v)
    checks.append(_check(
        "no_ping_pong", not ping_pong,
        "each vid descends then climbs at most once "
        f"({max((len(s) for s in by_vid.values()), default=0)} moves "
        "max per vid)" if not ping_pong
        else f"thrashing vids {ping_pong}"))
    checks.append(_check(
        "silence_paused_planner",
        not planned_in_silence and planner.paused_on_silence > 0,
        f"planner held {planner.paused_on_silence} plan tick(s) "
        f"through the {sil_end - sil_start:.1f}s telemetry-dark window"
        if not planned_in_silence else
        f"plans fired on stale telemetry at t={planned_in_silence}"))
    _tenant_invariant(cluster, checks)
    _breaker_invariant(cluster, checks)
    return checks


INCIDENTS = {
    "az_loss": _az_loss,
    "rolling_restart": _rolling_restart,
    "herd_repair": _herd_repair,
    "tenant_flood": _tenant_flood,
    "partition_heal_mid_repair": _partition_heal_mid_repair,
    "hot_shard_migration": _hot_shard_migration,
    "diurnal_sweep": _diurnal_sweep,
    "ec_single_shard_loss": _ec_single_shard_loss,
    "master_failover_mid_write": _master_failover_mid_write,
    "master_failover_mid_repair": _master_failover_mid_repair,
}


def run_incident(name: str, seed: int = 0, n_actors: int = 100,
                 n_filers: int = 4, rate: float = 0.0) -> dict:
    """Run one scripted incident; returns the JSON-able report.
    ``rate`` 0 auto-sizes offered load to ~2.4 ops/s per actor."""
    if name not in INCIDENTS:
        raise KeyError(f"unknown incident {name!r} "
                       f"(have {sorted(INCIDENTS)})")
    if rate <= 0:
        rate = 2.4 * n_actors
    cluster = SimCluster(n_volume_actors=n_actors, n_filers=n_filers,
                         seed=seed)
    checks = INCIDENTS[name](cluster, n_actors, rate)
    report = cluster.report()
    report.update({
        "incident": name, "seed": seed, "actors": n_actors,
        "invariants": checks,
        "passed": all(c["ok"] for c in checks),
    })
    return report
